"""Device-native array redistribution: compiled minimal-collective reshard().

Every sharding-layout transition used to be either a bespoke shard_map or
a full host round-trip through ``DeviceComm.to_ranks``/``from_ranks`` —
the staging anti-pattern the coll layer exists to avoid.  Following
"Memory-efficient array redistribution through portable collective
communication" (arXiv 2112.01075, PAPERS.md), an arbitrary
NamedSharding→NamedSharding transition decomposes into a short sequence
of the device collectives the stack already has, with bounded peak
memory:

  plan grammar (one collective per step, docs/resharding.md):
    all_to_all[a:d->e]   move axis ``a`` from array dim d to dim e
                         (flat memory: in == out == shard bytes)
    all_gather[a@d]      unshard dim d over axis ``a`` (grow)
    slice[a@d]           shard a replicated dim d over axis ``a``
                         (shrink, zero wire bytes — a local slice)
    ppermute[g~b@..]     exchange same-sized axes g and b (a pure device
                         transposition: flat memory, one hop per device)
    device_put           the whole-array XLA resharding transfer — the
                         device-native fallback for ragged/irregular
                         specs the step grammar cannot express exactly,
                         and for plans whose step sequence would breach
                         the peak-memory bound

  ordering discipline: shrinking slices fire as soon as their dim's
  prefix is ready, moves/swaps run flat, gathers are deferred to last —
  so intermediate shards never exceed max(src_shard, dst_shard) and the
  per-step live set (input + output) stays within
  ``reshard_peak_factor × max(src_shard, dst_shard)``.  A plan that
  would breach the bound (e.g. a transposition of unequal-sized axes,
  which needs a gather-sized intermediate) is REPLACED by the
  single-step device_put plan, whose live set is src+dst ≤ 2×max by
  construction — the bound is a contract, not a hint.

``reduce_scatter_axis`` is part of the vocabulary for future
partial-sum redistribution (reducing while resharding); pure layout
plans never emit it — a layout change has nothing to reduce.

First-class citizenship in the PR 1–9 stack:

* plans are cached by ``(src_spec, dst_spec, shape, dtype)`` per mesh
  and each step's executable goes through the same cache discipline as
  ``DeviceComm._compiled`` (build:* compile spans, cache_hit:*
  instants, device_cache_misses pvars);
* every step dispatches under coll name ``reshard`` through
  ``coll.xla.decide_mode`` (force var ``coll_xla_reshard_mode``,
  DEVICE_RULES ``reshard`` rows, ``learned`` consulting the perf
  ledger) and emits exactly ONE decision-audit event naming the plan;
* traffic attribution charges each step's real edge set (ring for
  gathers, bipartite for all_to_all, explicit perm pairs for
  ppermute) so the conservation invariant ``edge-sum ==
  coll_wire_bytes`` spans resharding traffic;
* the perf ledger grows ``reshard`` and ``reshard@<plane>`` cells from
  measured step durations, which is what ``coll_xla_rules=learned``
  reads back.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import jaxcompat as _compat, trace
from ..core import var as _var
from .collectives import all_to_all_axis
from .mesh import classify_axes

_var.register("reshard", "", "peak_factor", 2.0, type=float, level=3,
              help="Peak-live-bytes bound for compiled reshard plans, "
                   "as a multiple of max(src_shard, dst_shard) per "
                   "device (arXiv 2112.01075).  A plan whose step "
                   "accounting would breach the bound is replaced by "
                   "the single-step device_put plan (live set src+dst "
                   "<= 2x max by construction).")

PVARS = ("reshard_plans", "reshard_steps", "reshard_bytes")

_lock = threading.Lock()
_counts: Dict[str, int] = {"reshard_plans": 0, "reshard_steps": 0,
                           "reshard_bytes": 0}
# compiled-plan summaries + the last executed plan's audit, for
# comm_doctor --reshard (bounded: the doctor renders a cache view, not
# a history)
_plan_log: "deque" = deque(maxlen=32)
_last_run: Optional[Dict[str, Any]] = None


class ReshardError(ValueError):
    """A (src, dst, mesh, shape) tuple the plan compiler rejects loudly
    (unknown/repeated mesh axes — never a silent host fallback)."""


# ---------------------------------------------------------------------------
# plan representation
# ---------------------------------------------------------------------------

Placement = Tuple[Tuple[str, ...], ...]     # per-dim axis groups


@dataclass(frozen=True)
class PlanStep:
    op: str                       # all_to_all|all_gather|slice|ppermute|device_put
    axes: Tuple[str, ...]         # mesh axes driving the step
    dim: int                      # array dim acted on / move target dim
    src_dim: int                  # move/exchange source dim (== dim otherwise)
    in_spec: P
    out_spec: P
    in_bytes: int                 # per-device live bytes entering the step
    out_bytes: int                # per-device live bytes leaving the step
    wire_bytes: int               # modeled per-rank wire bytes
    perm: Tuple[Tuple[int, int], ...] = ()   # ppermute pairs (flat positions)

    def describe(self) -> str:
        if self.op == "all_to_all":
            return (f"all_to_all[{'+'.join(self.axes)}:"
                    f"{self.src_dim}->{self.dim}]")
        if self.op == "all_gather":
            return f"all_gather[{self.axes[0]}@{self.dim}]"
        if self.op == "slice":
            return f"slice[{self.axes[0]}@{self.dim}]"
        if self.op == "ppermute":
            g, b = self.axes
            if self.src_dim == self.dim:
                return f"ppermute[{g}~{b}@{self.dim}]"
            return f"ppermute[{g}@{self.src_dim}~{b}@{self.dim}]"
        return self.op


@dataclass(frozen=True)
class ReshardPlan:
    key: tuple
    shape: Tuple[int, ...]
    dtype: str
    src: Placement
    dst: Placement
    steps: Tuple[PlanStep, ...]
    src_shard_bytes: int
    dst_shard_bytes: int
    peak_bytes: int               # max per-step (in + out) live bytes
    wire_bytes: int               # sum of step wire figures
    bound_bytes: int              # factor * max(src_shard, dst_shard)
    fallback_reason: str = ""     # non-empty when device_put replaced steps

    def describe(self) -> List[str]:
        return [s.describe() for s in self.steps]

    @property
    def label(self) -> str:
        return (f"{_fmt_placement(self.src)}->{_fmt_placement(self.dst)}"
                f"/{self.dtype}{list(self.shape)}")


def _fmt_placement(pl: Placement) -> str:
    parts = []
    for grp in pl:
        if not grp:
            parts.append("_")
        elif len(grp) == 1:
            parts.append(grp[0])
        else:
            parts.append("(" + "+".join(grp) + ")")
    return "[" + ",".join(parts) + "]"


def _norm(spec, ndim: int) -> Placement:
    """PartitionSpec/sequence → per-dim tuples of axis names."""
    parts: Sequence = tuple(spec) if spec is not None else ()
    out: List[Tuple[str, ...]] = []
    for d in range(ndim):
        e = parts[d] if d < len(parts) else None
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(str(a) for a in e))
        else:
            out.append((str(e),))
    return tuple(out)


def _spec_of(pl: Placement) -> P:
    ents = []
    for grp in pl:
        if not grp:
            ents.append(None)
        elif len(grp) == 1:
            ents.append(grp[0])
        else:
            ents.append(tuple(grp))
    return P(*ents)


# ---------------------------------------------------------------------------
# plan compiler
# ---------------------------------------------------------------------------

def compile_plan(shape: Sequence[int], dtype, src_spec, dst_spec,
                 mesh: Mesh, peak_factor: Optional[float] = None
                 ) -> ReshardPlan:
    """Compile a (src, dst, mesh) triple into a minimal collective
    sequence.  Pure host math — no device work, no caches, no audit;
    the Resharder wraps this with caching and per-step dispatch."""
    shape = tuple(int(s) for s in shape)
    dt = np.dtype(jnp.dtype(dtype).name) if not isinstance(dtype, np.dtype) \
        else dtype
    src = _norm(src_spec, len(shape))
    dst = _norm(dst_spec, len(shape))
    sizes = {a: int(mesh.shape[a]) for a in mesh.axis_names}
    for pl, which in ((src, "src"), (dst, "dst")):
        seen = set()
        for grp in pl:
            for a in grp:
                if a not in sizes:
                    raise ReshardError(
                        f"reshard: {which} spec names axis {a!r} not on "
                        f"mesh {tuple(mesh.axis_names)}")
                if a in seen:
                    raise ReshardError(
                        f"reshard: {which} spec uses axis {a!r} on more "
                        "than one dim")
                seen.add(a)
    factor = float(peak_factor if peak_factor is not None
                   else _var.get("reshard_peak_factor", 2.0))
    itemsize = dt.itemsize
    total = itemsize * int(np.prod(shape)) if shape else itemsize

    def nshards(grp: Tuple[str, ...]) -> int:
        n = 1
        for a in grp:
            n *= sizes[a]
        return n

    def shard_bytes(pl: Placement) -> int:
        b = total
        for d, grp in enumerate(pl):
            n = nshards(grp)
            b = b // n if b % n == 0 else int(math.ceil(b / n))
        return max(b, itemsize)

    src_b, dst_b = shard_bytes(src), shard_bytes(dst)
    bound = int(factor * max(src_b, dst_b))
    key = (src, dst, shape, dt.name)

    def _plan(steps, peak, wire, why=""):
        return ReshardPlan(key=key, shape=shape, dtype=dt.name,
                           src=src, dst=dst, steps=tuple(steps),
                           src_shard_bytes=src_b, dst_shard_bytes=dst_b,
                           peak_bytes=peak, wire_bytes=wire,
                           bound_bytes=bound, fallback_reason=why)

    if src == dst:
        return _plan((), 0, 0)

    def _device_put_plan(why: str) -> ReshardPlan:
        # single XLA resharding transfer: device-native, live set
        # src+dst, wire modeled as the destination shard each device
        # must assemble
        step = PlanStep(op="device_put", axes=tuple(mesh.axis_names),
                        dim=0, src_dim=0, in_spec=_spec_of(src),
                        out_spec=_spec_of(dst), in_bytes=src_b,
                        out_bytes=dst_b, wire_bytes=dst_b)
        return _plan((step,), src_b + dst_b, dst_b, why)

    divisible = all(
        shape[d] % nshards(src[d]) == 0 and shape[d] % nshards(dst[d]) == 0
        for d in range(len(shape)))
    if not divisible:
        return _device_put_plan(
            "ragged: a dim does not divide by its sharding axes "
            "(shard_map steps need even shards)")

    placement: List[Tuple[str, ...]] = list(src)
    ndim = len(shape)
    dst_dim_of: Dict[str, int] = {a: d for d, grp in enumerate(dst)
                                  for a in grp}
    steps: List[PlanStep] = []
    cur_b = src_b
    peak = 0
    wire_total = 0

    def placed_anywhere(a: str) -> bool:
        return any(a in grp for grp in placement)

    def emit(op: str, axes: Tuple[str, ...], dim: int, src_dim: int,
             before: Placement, after: Placement, wire: int,
             perm: Tuple = ()) -> None:
        nonlocal cur_b, peak, wire_total
        in_b, out_b = shard_bytes(before), shard_bytes(after)
        steps.append(PlanStep(op=op, axes=axes, dim=dim, src_dim=src_dim,
                              in_spec=_spec_of(before),
                              out_spec=_spec_of(after), in_bytes=in_b,
                              out_bytes=out_b, wire_bytes=int(wire),
                              perm=perm))
        cur_b = out_b
        peak = max(peak, in_b + out_b)
        wire_total += int(wire)

    def _transpose_perm(n: int) -> Tuple[Tuple[int, int], ...]:
        # device (i, j) over the joint (g, b) space receives from (j, i)
        return tuple((j * n + i, i * n + j)
                     for i in range(n) for j in range(n))

    guard = 0
    while tuple(placement) != dst:
        guard += 1
        if guard > 8 * ndim * (len(sizes) + 1):
            return _device_put_plan("scheduler found no step sequence")
        progress = False
        before = tuple(placement)

        # 1) ppermute: same-dim axis substitution g -> b (equal sizes,
        #    g leaving the layout entirely, b entering it) — flat memory
        #    where gather+slice would blow up n-fold
        for d in range(ndim):
            cur, want = placement[d], dst[d]
            if (cur and want and len(cur) == len(want)
                    and cur[:-1] == want[:-1] and cur[-1] != want[-1]):
                g, b = cur[-1], want[-1]
                if (sizes[g] == sizes[b] and g not in dst_dim_of
                        and not placed_anywhere(b)):
                    after = list(placement)
                    after[d] = want
                    n = sizes[g]
                    w = cur_b * (n * n - n) // (n * n)
                    emit("ppermute", (g, b), d, d, tuple(placement),
                         tuple(after), w, _transpose_perm(n))
                    placement[d] = want
                    progress = True

        # 2) ppermute: dim-pair exchange g@d <-> b@e (equal sizes) —
        #    the cyclic-move deadlock resolved in one flat hop
        for d in range(ndim):
            for e in range(ndim):
                if d == e:
                    continue
                cd, wd = placement[d], dst[d]
                ce, we = placement[e], dst[e]
                if not (cd and ce and wd and we):
                    continue
                g, b = cd[-1], ce[-1]
                if (g != b and sizes[g] == sizes[b]
                        and wd == cd[:-1] + (b,) and we == ce[:-1] + (g,)):
                    after = list(placement)
                    after[d], after[e] = wd, we
                    n = sizes[g]
                    w = cur_b * (n * n - n) // (n * n)
                    emit("ppermute", (g, b), e, d, tuple(placement),
                         tuple(after), w, _transpose_perm(n))
                    placement[d], placement[e] = wd, we
                    progress = True

        # 3) moves: an innermost suffix of dim d's axes belongs — in
        #    order — on dim e whose prefix is ready: one all_to_all
        #    over the (joint) axis group, flat memory.  Longest suffix
        #    first, so a whole group like ("x","y") moves in a single
        #    step instead of two.
        for d in range(ndim):
            cur = placement[d]
            for k in range(len(cur), 0, -1):
                grp = cur[-k:]
                e = dst_dim_of.get(grp[0])
                if e is None or e == d:
                    continue
                q = len(placement[e])
                if (placement[e] == dst[e][:q]
                        and dst[e][q:q + k] == grp):
                    after = list(placement)
                    after[d] = cur[:-k]
                    after[e] = placement[e] + grp
                    m = nshards(grp)
                    w = cur_b * (m - 1) // m
                    emit("all_to_all", grp, e, d, tuple(placement),
                         tuple(after), w)
                    placement[d], placement[e] = after[d], after[e]
                    progress = True
                    break

        # 4) slices: the next wanted axis of a ready dim is currently
        #    unplaced — shard it locally (shrinks, zero wire)
        for d in range(ndim):
            cur, want = placement[d], dst[d]
            if cur == want[:len(cur)] and len(want) > len(cur):
                b = want[len(cur)]
                if not placed_anywhere(b):
                    after = list(placement)
                    after[d] = cur + (b,)
                    emit("slice", (b,), d, d, tuple(placement),
                         tuple(after), 0)
                    placement[d] = after[d]
                    progress = True

        if progress:
            continue

        # 5) gathers, last: remove the innermost axis past some dim's
        #    common prefix (also breaks move deadlocks — a gathered
        #    axis becomes re-addable by slice, since the data is then
        #    replicated over it)
        for d in range(ndim):
            cur, want = placement[d], dst[d]
            p = 0
            while p < min(len(cur), len(want)) and cur[p] == want[p]:
                p += 1
            if len(cur) > p:
                g = cur[-1]
                after = list(placement)
                after[d] = cur[:-1]
                m = sizes[g]
                w = cur_b * (m - 1)
                emit("all_gather", (g,), d, d, tuple(placement),
                     tuple(after), w)
                placement[d] = after[d]
                progress = True
                break
        if not progress:
            return _device_put_plan("scheduler found no step sequence")

    if peak > bound:
        return _device_put_plan(
            f"peak {peak}B over bound {bound}B "
            f"(reshard_peak_factor={factor:g})")
    return _plan(steps, peak, wire_total)


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------

class Resharder:
    """Per-mesh plan cache + step executor.

    Mirrors DeviceComm's executable-cache discipline exactly: one
    compiled program per (step, shape, dtype) key, build:* compile
    spans and cache_hit:* instants under trace, device_cache_misses /
    cache_miss_count pvars when an SPC table is attached."""

    def __init__(self, mesh: Mesh, spc=None) -> None:
        self.mesh = mesh
        self.spc = spc
        self._plans: Dict[tuple, ReshardPlan] = {}
        self._plan_hits = 0
        self._cache: Dict[tuple, Callable] = {}
        self._sizes = {a: int(mesh.shape[a]) for a in mesh.axis_names}
        self._axis_plane = classify_axes(mesh)
        self._platform = jax.devices()[0].platform

    # -- caches ---------------------------------------------------------

    def cache_info(self) -> Dict[str, int]:
        return {"plans": len(self._plans), "plan_hits": self._plan_hits,
                "executables": len(self._cache)}

    def _compiled(self, key: tuple, build: Callable) -> Callable:
        fn = self._cache.get(key)
        if fn is None:
            if trace.enabled:
                t0 = time.perf_counter()
                try:
                    fn = build()
                except BaseException:
                    trace.record_span(f"build:{key[0]}", "compile", t0,
                                      time.perf_counter(),
                                      args={"key": repr(key),
                                            "status": "error"})
                    raise
                trace.record_span(f"build:{key[0]}", "compile", t0,
                                  time.perf_counter(),
                                  args={"key": repr(key)})
            else:
                fn = build()
            self._cache[key] = fn
            if self.spc is not None:
                self.spc.inc("device_cache_misses")
                self.spc.inc("cache_miss_count")
        elif trace.enabled:
            trace.instant(f"cache_hit:{key[0]}", "cache",
                          args={"key": repr(key)})
        return fn

    def plan(self, shape, dtype, src_spec, dst_spec) -> ReshardPlan:
        dt = jnp.dtype(dtype).name
        key = (_norm(src_spec, len(shape)), _norm(dst_spec, len(shape)),
               tuple(int(s) for s in shape), dt)
        hit = self._plans.get(key)
        if hit is not None:
            self._plan_hits += 1
            if trace.enabled:
                trace.instant("cache_hit:reshard_plan", "cache",
                              args={"plan": hit.label})
            return hit
        t0 = time.perf_counter()
        try:
            plan = compile_plan(shape, dtype, src_spec, dst_spec, self.mesh)
        except BaseException:
            if trace.enabled:
                trace.record_span("reshard:compile_plan", "compile", t0,
                                  time.perf_counter(),
                                  args={"status": "error"})
            raise
        self._plans[key] = plan
        with _lock:
            _counts["reshard_plans"] += 1
            _plan_log.append({
                "plan": plan.label, "steps": plan.describe(),
                "wire_bytes": plan.wire_bytes,
                "peak_bytes": plan.peak_bytes,
                "bound_bytes": plan.bound_bytes,
                "src_shard_bytes": plan.src_shard_bytes,
                "dst_shard_bytes": plan.dst_shard_bytes,
                "fallback_reason": plan.fallback_reason,
                "mesh": dict(self.mesh.shape)})
        if trace.enabled:
            trace.record_span("reshard:compile_plan", "compile", t0,
                              time.perf_counter(),
                              args={"plan": plan.label,
                                    "steps": plan.describe(),
                                    "peak_bytes": plan.peak_bytes,
                                    "wire_bytes": plan.wire_bytes})
        return plan

    # -- per-step programs ---------------------------------------------

    def _exe(self, plan: ReshardPlan, i: int) -> Callable:
        step = plan.steps[i]
        key = ("reshard_" + step.op, step.axes, step.dim, step.src_dim,
               plan.shape, plan.dtype, str(step.in_spec),
               str(step.out_spec))
        mesh, sizes = self.mesh, self._sizes

        def build():
            if step.op == "device_put":
                dst = NamedSharding(mesh, step.out_spec)
                return jax.jit(lambda v: v, out_shardings=dst)
            if step.op == "all_to_all":
                d, e = step.src_dim, step.dim
                ax = step.axes[0] if len(step.axes) == 1 else step.axes

                def inner(xs):
                    return all_to_all_axis(xs, ax, split_dim=e,
                                           concat_dim=d)
            elif step.op == "all_gather":
                ax, d = step.axes[0], step.dim

                def inner(xs):
                    return lax.all_gather(xs, ax, axis=d, tiled=True)
            elif step.op == "slice":
                ax, d = step.axes[0], step.dim
                m = sizes[ax]

                def inner(xs):
                    blk = xs.shape[d] // m
                    idx = lax.axis_index(ax)
                    return lax.dynamic_slice_in_dim(xs, idx * blk, blk, d)
            elif step.op == "ppermute":
                axes, perm = step.axes, list(step.perm)

                def inner(xs):
                    return lax.ppermute(xs, axes, perm=perm)
            else:                   # pragma: no cover — grammar is closed
                raise ReshardError(f"unknown plan op {step.op!r}")
            return jax.jit(_compat.shard_map(inner, mesh=mesh,
                                             in_specs=step.in_spec,
                                             out_specs=step.out_spec))
        return self._compiled(key, build)

    # -- decision + audit ----------------------------------------------

    def _decide(self, step: PlanStep, ndev: int) -> Tuple[str, str, list]:
        from ..coll import xla as _xla
        plane = ("dcn" if any(self._axis_plane.get(a) == "dcn"
                              for a in step.axes) else "ici")
        return _xla.decide_mode(
            "reshard", step.wire_bytes, ndev, self._platform,
            _xla._load_device_rules(), allowed=("native",),
            quant_ok=False, dtype=None, op=None, plane=plane,
            hier_ok=False,
            hier_why="reshard steps are single layout-pure collectives")

    def _audit_step(self, plan: ReshardPlan, i: int, arm: str,
                    reason: str, chain: list, ndev: int,
                    dur_s: Optional[float]) -> None:
        from .. import perf, traffic
        step = plan.steps[i]
        wire = int(step.wire_bytes)
        with _lock:
            _counts["reshard_steps"] += 1
            _counts["reshard_bytes"] += wire
        if self.spc is not None:
            self.spc.inc(f"coll_arm_{arm}_count")
            if wire:
                self.spc.inc("coll_wire_bytes", wire)
        planes: Dict[str, int] = {}
        if traffic.enabled and wire:
            kind = {"all_to_all": "a2a", "all_gather": "ring",
                    "ppermute": "perm", "device_put": "a2a"}.get(step.op)
            if kind is not None:
                planes = traffic.note_reshard_step(
                    self.mesh, kind, step.axes, wire,
                    pairs=step.perm or None)
        if perf.enabled and dur_s is not None and wire and ndev >= 2:
            perf.note_sample("reshard", arm, wire, dur_s, ndev,
                             planes=planes)
        if trace.enabled:
            trace.decision(
                "reshard", arm=arm, reason=reason, verdict=None,
                nbytes=wire,
                step=i, step_op=step.describe(), plan=plan.label,
                plan_steps=len(plan.steps), peak_bytes=plan.peak_bytes,
                bound_bytes=plan.bound_bytes, ndev=ndev,
                wire_bytes=wire, chain=chain)
        return planes

    # -- execution ------------------------------------------------------

    def run(self, x: jax.Array, dst_spec) -> jax.Array:
        from .. import perf
        global _last_run
        src_spec = x.sharding.spec
        plan = self.plan(x.shape, x.dtype, src_spec, dst_spec)
        if not plan.steps:
            return x
        audit: List[Dict[str, Any]] = []
        for i, step in enumerate(plan.steps):
            ndev = 1
            for a in step.axes:
                ndev *= self._sizes[a]
            arm, reason, chain = self._decide(step, ndev)
            exe = self._exe(plan, i)
            t0 = time.perf_counter()
            x = exe(x)
            dur = None
            if perf.enabled:
                jax.block_until_ready(x)
                dur = time.perf_counter() - t0
            self._audit_step(plan, i, arm, reason, chain, ndev, dur)
            audit.append({"step": i, "op": step.describe(), "arm": arm,
                          "reason": reason, "wire_bytes": step.wire_bytes,
                          "dur_us": (round(dur * 1e6, 1)
                                     if dur is not None else None)})
        with _lock:
            _last_run = {"plan": plan.label, "steps": audit,
                         "wire_bytes": plan.wire_bytes,
                         "peak_bytes": plan.peak_bytes,
                         "bound_bytes": plan.bound_bytes,
                         "fallback_reason": plan.fallback_reason}
        return x


# ---------------------------------------------------------------------------
# module-level face
# ---------------------------------------------------------------------------

_resharders: Dict[Mesh, Resharder] = {}
_RESHARDER_CAP = 8


def resharder(mesh: Mesh, spc=None) -> Resharder:
    """The per-mesh Resharder (bounded registry; the newest SPC table
    attaches — the latest Context wins, like DeviceComm.spc)."""
    with _lock:
        r = _resharders.get(mesh)
        if r is None:
            if len(_resharders) >= _RESHARDER_CAP:
                _resharders.pop(next(iter(_resharders)))
            r = _resharders[mesh] = Resharder(mesh, spc=spc)
        if spc is not None:
            r.spc = spc
    return r


def reshard(x, dst, mesh: Optional[Mesh] = None, spc=None) -> jax.Array:
    """Redistribute ``x`` onto ``dst`` (NamedSharding or PartitionSpec)
    through a compiled minimal-collective plan — entirely on device.

    An input that is not already a NamedSharding-on-this-mesh array (a
    host ndarray, a fresh single-device array) is ingested with one
    ``device_put`` — that is a placement, not a redistribution, and is
    not audited as one."""
    if isinstance(dst, NamedSharding):
        mesh = mesh if mesh is not None else dst.mesh
        dst_spec = dst.spec
    elif isinstance(dst, P):
        dst_spec = dst
    elif isinstance(dst, (tuple, list)):
        dst_spec = P(*dst)
    else:
        raise TypeError(f"reshard: dst must be a NamedSharding or "
                        f"PartitionSpec, got {type(dst).__name__}")
    if mesh is None:
        s = getattr(x, "sharding", None)
        mesh = getattr(s, "mesh", None)
    if mesh is None:
        raise ReshardError("reshard: no mesh — pass one, or a "
                           "NamedSharding dst")
    if isinstance(mesh, jax.sharding.AbstractMesh):     # tracing context
        raise ReshardError("reshard: needs a concrete Mesh (called "
                           "under tracing?)")
    s = getattr(x, "sharding", None)
    if not (isinstance(x, jax.Array) and isinstance(s, NamedSharding)
            and s.mesh == mesh):
        return jax.device_put(x, NamedSharding(mesh, dst_spec))
    return resharder(mesh, spc=spc).run(x, dst_spec)


# ---------------------------------------------------------------------------
# cross-mesh planning mode (source mesh ⊃ dest mesh)
# ---------------------------------------------------------------------------
#
# compile_plan above assumes ONE fixed mesh: every step is a collective
# over axes both layouts share.  Elastic recovery (ft/elastic) needs the
# other shape: the array lives on the FULL mesh, some of whose devices
# are dead, and must land on a survivor mesh that is a strict subset of
# the original devices.  The cross plan decomposes that transition into
# per-destination-device piece moves — each destination shard is tiled
# by whole source shards (or crops of replicas), moved point-to-point
# and assembled in place with donated dynamic_update_slice programs so
# the per-device live set stays within the same peak contract as the
# single-mesh planner: resident src shard + assembled dst shard + one
# in-flight piece <= reshard_peak_factor * max(src_shard, dst_shard)
# when the factor is the default 2.  Pieces whose source device is dead
# are sourced from caller-provided REPLACEMENTS (ft/elastic's in-memory
# peer shadows) — the dead device's buffers are never read, and no
# filesystem round-trip happens.

@dataclass(frozen=True)
class CrossPiece:
    """One source block of a destination shard."""
    dst_pos: int                  # flat position in the SOURCE mesh
    src_pos: int                  # flat position in the SOURCE mesh
    start: Tuple[int, ...]        # piece origin in global index space
    sizes: Tuple[int, ...]        # piece extent per dim
    nbytes: int
    from_shadow: bool             # sourced from a replacement, not x


@dataclass(frozen=True)
class CrossMeshPlan:
    key: tuple
    shape: Tuple[int, ...]
    dtype: str
    src: Placement
    dst: Placement
    pieces: Tuple[CrossPiece, ...]
    src_shard_bytes: int
    dst_shard_bytes: int
    peak_bytes: int               # modeled per-device live-set maximum
    wire_bytes: int               # modeled cross-device piece bytes
    bound_bytes: int
    n_src: int
    n_dst: int
    fallback_reason: str = ""     # non-empty when device_put replaced pieces

    @property
    def label(self) -> str:
        return (f"{_fmt_placement(self.src)}x{self.n_src}->"
                f"{_fmt_placement(self.dst)}x{self.n_dst}"
                f"/{self.dtype}{list(self.shape)}")

    def describe(self) -> List[str]:
        if self.fallback_reason:
            return ["device_put"]
        return [f"cross_migrate[{len(self.pieces)} piece(s), "
                f"{sum(1 for p in self.pieces if p.from_shadow)} shadow]"]


def _region(idx, shape) -> Tuple[Tuple[int, int], ...]:
    """A devices_indices_map entry -> ((start, stop), ...) per dim."""
    out = []
    for d, s in enumerate(idx):
        start = 0 if s.start is None else int(s.start)
        stop = int(shape[d]) if s.stop is None else int(s.stop)
        out.append((start, stop))
    return tuple(out)


def _contains(outer, inner) -> bool:
    return all(o0 <= i0 and i1 <= o1
               for (o0, o1), (i0, i1) in zip(outer, inner))


def _rsize(reg) -> int:
    n = 1
    for a, b in reg:
        n *= max(b - a, 0)
    return n


def compile_cross_plan(shape: Sequence[int], dtype, src_spec, dst_spec,
                       src_mesh: Mesh, dst_mesh: Mesh,
                       dead: Sequence[int] = (),
                       peak_factor: Optional[float] = None
                       ) -> CrossMeshPlan:
    """Compile a source-mesh ⊃ dest-mesh transition into per-device piece
    moves.  ``dead`` holds flat positions (into ``src_mesh.devices``) of
    devices whose shards must never be read — those pieces are marked
    ``from_shadow`` and the executor sources them from caller
    replacements.  Pure host math, like :func:`compile_plan`."""
    shape = tuple(int(s) for s in shape)
    dt = np.dtype(jnp.dtype(dtype).name) if not isinstance(dtype, np.dtype) \
        else dtype
    itemsize = dt.itemsize
    src = _norm(src_spec, len(shape))
    dst = _norm(dst_spec, len(shape))
    src_devs = list(np.asarray(src_mesh.devices).flat)
    dst_devs = list(np.asarray(dst_mesh.devices).flat)
    pos_of = {d: i for i, d in enumerate(src_devs)}
    dead_set = frozenset(int(p) for p in dead)
    missing = [d for d in dst_devs if d not in pos_of]
    if missing:
        raise ReshardError(
            "cross_reshard: dest mesh is not a subset of the source mesh "
            f"(devices {missing} not on the source mesh)")
    bad = [pos_of[d] for d in dst_devs if pos_of[d] in dead_set]
    if bad:
        raise ReshardError(
            f"cross_reshard: dest mesh includes dead device position(s) "
            f"{sorted(bad)} — shrink to survivors first")
    src_sh = NamedSharding(src_mesh, _spec_of(src))
    dst_sh = NamedSharding(dst_mesh, _spec_of(dst))
    src_map = {pos_of[d]: _region(idx, shape)
               for d, idx in src_sh.devices_indices_map(shape).items()}
    dst_map = {pos_of[d]: _region(idx, shape)
               for d, idx in dst_sh.devices_indices_map(shape).items()}
    total = itemsize * int(np.prod(shape)) if shape else itemsize
    src_b = max(max((_rsize(r) for r in src_map.values()), default=1)
                * itemsize, itemsize)
    dst_b = max(max((_rsize(r) for r in dst_map.values()), default=1)
                * itemsize, itemsize)
    factor = float(peak_factor if peak_factor is not None
                   else _var.get("reshard_peak_factor", 2.0))
    bound = int(factor * max(src_b, dst_b))
    key = (src, dst, shape, dt.name,
           tuple(id(d) for d in src_devs), tuple(id(d) for d in dst_devs),
           dead_set)

    def _fallback(why: str) -> CrossMeshPlan:
        if dead_set:
            raise ReshardError(
                f"cross_reshard: {why} — and dead position(s) "
                f"{sorted(dead_set)} rule out the whole-array device_put "
                "fallback (it would read their shards)")
        return CrossMeshPlan(
            key=key, shape=shape, dtype=dt.name, src=src, dst=dst,
            pieces=(), src_shard_bytes=src_b, dst_shard_bytes=dst_b,
            peak_bytes=src_b + dst_b, wire_bytes=dst_b, bound_bytes=bound,
            n_src=len(src_devs), n_dst=len(dst_devs), fallback_reason=why)

    # group source holders by region (partial replication: several
    # devices may hold identical blocks)
    holders: Dict[Tuple, List[int]] = {}
    for p, reg in src_map.items():
        holders.setdefault(reg, []).append(p)

    pieces: List[CrossPiece] = []
    peak = 0
    wire = 0
    for dpos, R in sorted(dst_map.items()):
        cand = [(reg, ps) for reg, ps in holders.items()
                if _contains(R, reg)]
        if sum(_rsize(reg) for reg, _ in cand) != _rsize(R):
            return _fallback(
                "irregular tiling: a source shard straddles a dest shard "
                "boundary (cross plans need dest shards tiled by whole "
                "source blocks)")
        dev_pieces: List[CrossPiece] = []
        for reg, ps in sorted(cand):
            alive = [p for p in sorted(ps) if p not in dead_set]
            shadow = not alive
            if shadow:
                p = min(ps)                     # replacement keyed here
            elif dpos in alive:
                p = dpos                        # local copy: zero wire
            else:
                p = alive[0]
            nb = _rsize(reg) * itemsize
            dev_pieces.append(CrossPiece(
                dst_pos=dpos, src_pos=p,
                start=tuple(a for a, _ in reg),
                sizes=tuple(b - a for a, b in reg),
                nbytes=nb, from_shadow=shadow))
            if shadow or p != dpos:
                wire += nb
        pieces.extend(dev_pieces)
        # live-set model per dest device: resident src shard + the
        # assembled dst shard + one in-flight piece (assembly is
        # sequential donated update_slice, never a full concat)
        max_piece = max((pc.nbytes for pc in dev_pieces), default=0)
        if len(dev_pieces) == 1 and dev_pieces[0].src_pos == dpos \
                and not dev_pieces[0].from_shadow \
                and dev_pieces[0].nbytes == _rsize(src_map[dpos]) * itemsize:
            live = src_b                        # pure alias, no assembly
        else:
            live = src_b + _rsize(R) * itemsize + max_piece
        peak = max(peak, live)
    if peak > bound:
        return _fallback(
            f"peak {peak}B over bound {bound}B "
            f"(reshard_peak_factor={factor:g})")
    return CrossMeshPlan(
        key=key, shape=shape, dtype=dt.name, src=src, dst=dst,
        pieces=tuple(pieces), src_shard_bytes=src_b, dst_shard_bytes=dst_b,
        peak_bytes=peak, wire_bytes=wire, bound_bytes=bound,
        n_src=len(src_devs), n_dst=len(dst_devs))


_cross_plans: Dict[tuple, CrossMeshPlan] = {}
_cross_exe: Dict[tuple, Callable] = {}
_CROSS_CAP = 256


def _cross_compiled(key: tuple, build: Callable, spc=None) -> Callable:
    """Executable-cache discipline for cross-plan piece programs (same
    build:*/cache_hit:* spans and pvars as Resharder._compiled)."""
    fn = _cross_exe.get(key)
    if fn is None:
        if len(_cross_exe) >= _CROSS_CAP:
            _cross_exe.pop(next(iter(_cross_exe)))
        if trace.enabled:
            t0 = time.perf_counter()
            try:
                fn = build()
            except BaseException:
                trace.record_span(f"build:{key[0]}", "compile", t0,
                                  time.perf_counter(),
                                  args={"key": repr(key),
                                        "status": "error"})
                raise
            trace.record_span(f"build:{key[0]}", "compile", t0,
                              time.perf_counter(), args={"key": repr(key)})
        else:
            fn = build()
        _cross_exe[key] = fn
        if spc is not None:
            spc.inc("device_cache_misses")
            spc.inc("cache_miss_count")
    elif trace.enabled:
        trace.instant(f"cache_hit:{key[0]}", "cache",
                      args={"key": repr(key)})
    return fn


def _cross_plan(shape, dtype, src_spec, dst_spec, src_mesh, dst_mesh,
                dead) -> CrossMeshPlan:
    dt = jnp.dtype(dtype).name
    key = (_norm(src_spec, len(shape)), _norm(dst_spec, len(shape)),
           tuple(int(s) for s in shape), dt,
           tuple(id(d) for d in np.asarray(src_mesh.devices).flat),
           tuple(id(d) for d in np.asarray(dst_mesh.devices).flat),
           frozenset(int(p) for p in dead))
    hit = _cross_plans.get(key)
    if hit is not None:
        if trace.enabled:
            trace.instant("cache_hit:reshard_cross_plan", "cache",
                          args={"plan": hit.label})
        return hit
    if len(_cross_plans) >= _CROSS_CAP:
        _cross_plans.pop(next(iter(_cross_plans)))
    t0 = time.perf_counter()
    try:
        plan = compile_cross_plan(shape, dtype, src_spec, dst_spec,
                                  src_mesh, dst_mesh, dead=dead)
    except BaseException:
        if trace.enabled:
            trace.record_span("reshard:compile_cross_plan", "compile", t0,
                              time.perf_counter(),
                              args={"status": "error"})
        raise
    if trace.enabled:
        trace.record_span("reshard:compile_cross_plan", "compile", t0,
                          time.perf_counter(),
                          args={"plan": plan.label,
                                "pieces": len(plan.pieces),
                                "peak_bytes": plan.peak_bytes,
                                "wire_bytes": plan.wire_bytes})
    _cross_plans[key] = plan
    with _lock:
        _counts["reshard_plans"] += 1
        _plan_log.append({
            "plan": plan.label, "steps": plan.describe(),
            "wire_bytes": plan.wire_bytes, "peak_bytes": plan.peak_bytes,
            "bound_bytes": plan.bound_bytes,
            "src_shard_bytes": plan.src_shard_bytes,
            "dst_shard_bytes": plan.dst_shard_bytes,
            "fallback_reason": plan.fallback_reason,
            "cross": True, "dead": sorted(int(p) for p in dead),
            "mesh": {"src": dict(src_mesh.shape),
                     "dst": dict(dst_mesh.shape)}})
    return plan


def cross_reshard(x: jax.Array, dst: NamedSharding, *,
                  dead: Sequence[int] = (), replacements=None,
                  spc=None) -> jax.Array:
    """Redistribute ``x`` from its (larger) source mesh onto ``dst``'s
    survivor mesh.  ``dead`` flat source positions are never read; each
    of their blocks must be covered by ``replacements[pos]`` — a
    device-resident array equal to that position's lost shard (the
    peer-shadow copy ft/elastic maintains).  Audited exactly like a
    single-mesh plan: one decide:reshard event for the migrate step,
    per-pair traffic attribution on the source mesh's edge space, and
    the reshard_* pvars."""
    global _last_run
    if not isinstance(dst, NamedSharding):
        raise TypeError("cross_reshard: dst must be a NamedSharding "
                        f"(got {type(dst).__name__})")
    s = getattr(x, "sharding", None)
    if not (isinstance(x, jax.Array) and isinstance(s, NamedSharding)):
        raise ReshardError("cross_reshard: x must be a mesh-sharded "
                           "jax.Array (got an uncommitted input)")
    src_mesh = s.mesh
    if src_mesh == dst.mesh and not dead:
        return resharder(src_mesh, spc=spc).run(x, dst.spec)
    replacements = dict(replacements or {})
    plan = _cross_plan(x.shape, x.dtype, s.spec, dst.spec,
                       src_mesh, dst.mesh, dead)
    from .. import perf
    from ..coll import xla as _xla
    src_devs = list(np.asarray(src_mesh.devices).flat)
    itemsize = np.dtype(plan.dtype).itemsize
    t0 = time.perf_counter()
    if plan.fallback_reason:
        out = jax.device_put(x, dst)
        pair_bytes: Dict[Tuple[int, int], int] = {}
        wire = plan.wire_bytes
    else:
        shards = {}
        for sh in x.addressable_shards:
            shards[src_devs.index(sh.device)] = sh.data
        by_dst: Dict[int, List[CrossPiece]] = {}
        for pc in plan.pieces:
            by_dst.setdefault(pc.dst_pos, []).append(pc)
        pair_bytes = {}
        wire = 0
        blocks = []
        order = []
        src_sh_map = {src_devs.index(d): _region(idx, x.shape)
                      for d, idx in
                      NamedSharding(src_mesh, s.spec)
                      .devices_indices_map(x.shape).items()}
        for dev, idx in dst.devices_indices_map(x.shape).items():
            dpos = src_devs.index(dev)
            R = _region(idx, x.shape)
            pcs = by_dst[dpos]
            whole = (len(pcs) == 1 and not pcs[0].from_shadow
                     and pcs[0].src_pos == dpos
                     and pcs[0].sizes == tuple(b - a for a, b in
                                               src_sh_map[dpos]))
            if whole:
                blocks.append(shards[dpos])
                order.append(dev)
                continue
            rshape = tuple(b - a for a, b in R)
            zkey = ("reshard_cross_zeros", rshape, plan.dtype, id(dev))
            zfn = _cross_compiled(
                zkey,
                lambda rs=rshape, dv=dev: jax.jit(
                    lambda: jnp.zeros(rs, plan.dtype),
                    out_shardings=jax.sharding.SingleDeviceSharding(dv)),
                spc=spc)
            block = zfn()
            for pc in pcs:
                if pc.from_shadow:
                    repl = replacements.get(pc.src_pos)
                    if repl is None:
                        raise ReshardError(
                            f"cross_reshard: dead position {pc.src_pos} "
                            "has no replacement shard (peer shadow "
                            "missing) — cannot recover its block")
                    base = src_sh_map[pc.src_pos]
                    arr = repl
                    holder = next(iter(arr.devices())) \
                        if hasattr(arr, "devices") else dev
                    src_pos_real = (src_devs.index(holder)
                                    if holder in src_devs else pc.src_pos)
                else:
                    base = src_sh_map[pc.src_pos]
                    arr = shards[pc.src_pos]
                    src_pos_real = pc.src_pos
                crop = tuple(
                    slice(st - b0, st - b0 + sz)
                    for st, sz, (b0, _b1) in zip(pc.start, pc.sizes, base))
                if any(c != slice(0, sh) for c, sh in zip(crop, arr.shape)):
                    arr = arr[crop]
                moved = jax.device_put(arr, dev)
                if src_pos_real != dpos:
                    nb = int(np.prod(pc.sizes)) * itemsize
                    wire += nb
                    pair_bytes[(src_pos_real, dpos)] = \
                        pair_bytes.get((src_pos_real, dpos), 0) + nb
                offs = tuple(st - a for st, (a, _b) in zip(pc.start, R))
                ukey = ("reshard_cross_update", rshape, moved.shape, offs,
                        plan.dtype, id(dev))
                ufn = _cross_compiled(
                    ukey,
                    lambda o=offs, dv=dev: jax.jit(
                        lambda b, p: lax.dynamic_update_slice(
                            b, p, o),
                        donate_argnums=(0,),
                        out_shardings=jax.sharding.SingleDeviceSharding(
                            dv)),
                    spc=spc)
                block = ufn(block, moved)
            blocks.append(block)
            order.append(dev)
        out = jax.make_array_from_single_device_arrays(
            x.shape, dst, blocks)
    dur = None
    if perf.enabled:
        jax.block_until_ready(out)
        dur = time.perf_counter() - t0
    # -- audit: one decision + counters + per-pair traffic ---------------
    plane = ("dcn" if any(classify_axes(src_mesh).get(a) == "dcn"
                          for a in src_mesh.axis_names) else "ici")
    arm, reason, chain = _xla.decide_mode(
        "reshard", wire, plan.n_src, jax.devices()[0].platform,
        _xla._load_device_rules(), allowed=("native",), quant_ok=False,
        dtype=None, op=None, plane=plane, hier_ok=False,
        hier_why="cross-mesh migrate is a fixed point-to-point schedule")
    with _lock:
        _counts["reshard_steps"] += 1
        _counts["reshard_bytes"] += int(wire)
    if spc is not None:
        spc.inc(f"coll_arm_{arm}_count")
        if wire:
            spc.inc("coll_wire_bytes", int(wire))
    planes: Dict[str, int] = {}
    from .. import traffic
    if traffic.enabled and wire:
        if pair_bytes:
            axes = tuple(src_mesh.axis_names)
            for (sp, dp), nb in sorted(pair_bytes.items()):
                part = traffic.note_reshard_step(
                    src_mesh, "perm", axes, nb, pairs=[(sp, dp)])
                for k, v in part.items():
                    planes[k] = planes.get(k, 0) + v
        else:       # device_put fallback: full exchange on the dst mesh
            planes = traffic.note_reshard_step(
                dst.mesh, "a2a", tuple(dst.mesh.axis_names), wire)
    if perf.enabled and dur is not None and wire and plan.n_src >= 2:
        perf.note_sample("reshard", arm, wire, dur, plan.n_src,
                         planes=planes)
    step_op = plan.describe()[0]
    if trace.enabled:
        trace.decision(
            "reshard", arm=arm, reason=reason, verdict=None,
            nbytes=int(wire),
            step=0, step_op=step_op, plan=plan.label, plan_steps=1,
            peak_bytes=plan.peak_bytes, bound_bytes=plan.bound_bytes,
            ndev=plan.n_src, wire_bytes=int(wire), chain=chain,
            cross=True, dead=sorted(int(p) for p in dead))
    with _lock:
        _last_run = {"plan": plan.label,
                     "steps": [{"step": 0, "op": step_op, "arm": arm,
                                "reason": reason, "wire_bytes": int(wire),
                                "dur_us": (round(dur * 1e6, 1)
                                           if dur is not None else None)}],
                     "wire_bytes": int(wire),
                     "peak_bytes": plan.peak_bytes,
                     "bound_bytes": plan.bound_bytes,
                     "fallback_reason": plan.fallback_reason}
    return out


# ---------------------------------------------------------------------------
# pvars + report
# ---------------------------------------------------------------------------

def pvar_value(name: str) -> float:
    with _lock:
        return float(_counts[name])


def report() -> Dict[str, Any]:
    """Structured snapshot for comm_doctor --reshard / the bench probe:
    the compiled-plan cache view and the last executed plan's per-step
    audit."""
    with _lock:
        return {"counters": dict(_counts),
                "plans": list(_plan_log),
                "last": dict(_last_run) if _last_run else None}


def reset() -> None:
    global _last_run
    with _lock:
        for k in _counts:
            _counts[k] = 0
        _plan_log.clear()
        _last_run = None
    _resharders.clear()
    _cross_plans.clear()
    _cross_exe.clear()
