"""Ring attention — context parallelism over a mesh axis.

Long-context support is first-class in this framework (SURVEY.md §5.7): the
reference's segmented-ring collectives (coll_base_allreduce.c:344,621) are
exactly the communication schedule of ring attention — neighbor exchange of
K/V blocks around a ring, overlapping compute with ICI transfers. Here that
schedule is expressed TPU-natively: a ``lax.fori_loop`` of
(block attention, ``lax.ppermute``) steps inside ``shard_map``, with online
softmax merging so sequence length scales linearly with ring size at O(seq/n)
memory per chip.

The inner block-attention is a plain jnp function by default (XLA fuses it
well); pass ``block_impl="pallas"`` to use the Pallas flash kernel
(ops/attention.py flash_attention_partials) for the VMEM-resident fast path.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..jaxcompat import pcast, shard_map, typeof_vma

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask):
    """One (q-block × kv-block) attention piece → (numerator, max, denom).

    q: (sq, d), k/v: (sk, d), mask: (sq, sk) additive or None.
    Returns o: (sq, d) un-normalized, m: (sq,) row max, l: (sq,) denom.
    """
    s = (q @ k.T) * scale                       # (sq, sk)
    if mask is not None:
        s = s + mask
    m = jnp.max(s, axis=-1)                     # (sq,)
    p = jnp.exp(s - m[:, None])
    l = jnp.sum(p, axis=-1)
    o = p @ v
    return o, m, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Merge two online-softmax partials (the flash-attention combine)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    o = o1 * a1[:, None] + o2 * a2[:, None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis: str = "sp", causal: bool = False,
                   scale: Optional[float] = None,
                   batch_axis: Optional[str] = None,
                   head_axis: Optional[str] = None,
                   block_impl: str = "jnp") -> jax.Array:
    """Attention over a sequence sharded on `axis`.

    q/k/v: (batch, seq, heads, head_dim) with seq sharded over `axis`;
    batch/heads may additionally be sharded over dp/tp axes (composes with
    data and tensor parallelism). Each ring step attends the local Q shard
    against the visiting K/V shard, then rotates K/V one hop (``ppermute``)
    — n_axis steps total; the rotation of step i+1 overlaps the compute of
    step i in XLA's schedule.
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    from .. import traffic
    if traffic.enabled and not isinstance(q, jax.core.Tracer):
        # all n ring steps rotate (the schedule permutes after the last
        # block too): per-rank wire = n x its K/V shard = full K+V bytes
        if mesh.shape[axis] > 1:
            traffic.note_ring(mesh, axis, k.nbytes + v.nbytes,
                              "ring_attention")
    return _build_ring(mesh, axis, bool(causal), float(scale),
                       batch_axis, head_axis, block_impl)(q, k, v)


@functools.lru_cache(maxsize=128)
def _build_ring(mesh: Mesh, axis: str, causal: bool, scale: float,
                batch_axis: Optional[str] = None,
                head_axis: Optional[str] = None,
                block_impl: str = "jnp"):
    """Compiled-program cache: one executable per (mesh, axis, causal, scale)
    × (shape, dtype) — the coll/xla cache discipline (SURVEY.md §7)."""
    n = mesh.shape[axis]

    def local(qs, ks, vs):
        # qs/ks/vs: (b, s_local, h, d)
        b, s, h, d = qs.shape
        my = lax.axis_index(axis)
        # fold batch*heads: (bh, s, d)
        qf = jnp.moveaxis(qs, 2, 1).reshape(b * h, s, d)
        kf0 = jnp.moveaxis(ks, 2, 1).reshape(b * h, s, d)
        vf0 = jnp.moveaxis(vs, 2, 1).reshape(b * h, s, d)

        q_pos = my * s + jnp.arange(s)           # global positions of my Q

        def step(i, carry):
            o, m, l, kf, vf = carry
            src = (my - i) % n                   # whose K/V is visiting
            if block_impl == "pallas":
                # VMEM-resident flash kernel (ops/attention.py) with the
                # traced global offsets driving the causal mask
                from ..ops.attention import flash_attention_partials
                bo, bm, bl = flash_attention_partials(
                    qf, kf, vf, causal=causal, scale=scale,
                    q_offset=my * s, kv_offset=src * s,
                    vma=frozenset(a for a in (batch_axis, axis, head_axis)
                                  if a is not None))
                bo = bo.astype(qf.dtype)
                bm = bm.astype(qf.dtype)
                bl = bl.astype(qf.dtype)
            else:
                kv_pos = src * s + jnp.arange(s)
                if causal:
                    mask = jnp.where(q_pos[:, None] >= kv_pos[None, :],
                                     0.0, NEG_INF).astype(qf.dtype)
                else:
                    mask = None
                bo, bm, bl = jax.vmap(
                    lambda qq, kk, vv: _block_attn(qq, kk, vv, scale, mask)
                )(qf, kf, vf)
            o, m, l = jax.vmap(_merge)(o, m, l, bo, bm, bl)
            # rotate K/V to the next ring position
            perm = [(j, (j + 1) % n) for j in range(n)]
            # comm-lint: disable=CL001 the ring hop IS the algorithm (not a reducible collective the engine could re-plan); attributed at the eager boundary via traffic.note_ring
            kf = lax.ppermute(kf, axis, perm)
            vf = lax.ppermute(vf, axis, perm)  # comm-lint: disable=CL001 same ring hop, V plane
            return o, m, l, kf, vf

        # mark the accumulators device-varying over exactly the mesh axes
        # this program's inputs are sharded on, so the fori carry types match
        # the per-shard outputs (vma rules)
        axes = tuple(a for a in (batch_axis, axis, head_axis)
                     if a is not None)

        def vary_all(x):
            if block_impl == "pallas":     # vma tracking is off (see below)
                return x
            missing = tuple(a for a in axes if a not in typeof_vma(x))
            return pcast(x, missing, to="varying") if missing else x

        o0 = vary_all(jnp.zeros_like(qf))
        m0 = vary_all(jnp.full(qf.shape[:2], NEG_INF, qf.dtype))
        l0 = vary_all(jnp.zeros(qf.shape[:2], qf.dtype))
        o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, kf0, vf0))
        out = o / jnp.maximum(l, 1e-20)[:, :, None]
        return jnp.moveaxis(out.reshape(b, h, s, d), 1, 2)

    spec = P(batch_axis, axis, head_axis, None)
    # check_vma off for the pallas block: the interpret-mode pallas_call
    # lowering can't yet propagate varying-manual-axes through its internal
    # dynamic_slice (jax suggests this exact workaround).
    # comm-lint: disable=CL001 ring attention is a leaf SPMD kernel: its only comm is the waived ppermute ring above, verified statically by analysis.commgraph
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec,
                             check_vma=(block_impl != "pallas")))


def attention_reference(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Dense single-device attention (ground truth for tests)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    b, s, h, d = q.shape
    qf = jnp.moveaxis(q, 2, 1)      # (b, h, s, d)
    kf = jnp.moveaxis(k, 2, 1)
    vf = jnp.moveaxis(v, 2, 1)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", w, vf)
    return jnp.moveaxis(out, 1, 2)
