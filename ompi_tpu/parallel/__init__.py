"""TPU-first parallelism layer: device meshes, named-axis collectives,
DeviceComm, sequence/context parallelism, hierarchical collectives.

This package is the re-imagined face of the reference's parallelism-backing
machinery (SURVEY.md §2.6): DP/TP rides allreduce/reduce-scatter/allgather,
SP/CP rides ppermute rings and all_to_all (Ulysses), hierarchical rides the
ICI/DCN axis split (≙ coll/han)."""

from .mesh import (  # noqa: F401
    STANDARD_AXES,
    classify_axes,
    make_mesh,
    replicated,
    shard_leading,
    sharded,
)
from .ring import attention_reference, ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .hierarchy import (  # noqa: F401
    auto_levels,
    hierarchical_allreduce,
    hierarchical_psum,
)
from .device_plane import device_plane_active, init_device_plane  # noqa: F401
from .collectives import (  # noqa: F401
    DeviceComm,
    all_gather_axis,
    all_to_all_axis,
    pbcast,
    pmax,
    pmin,
    ppermute,
    preduce,
    psum,
    reduce_scatter_axis,
    ring_shift,
)
from .reshard import (  # noqa: F401
    CrossMeshPlan,
    ReshardError,
    ReshardPlan,
    Resharder,
    compile_cross_plan,
    compile_plan,
    cross_reshard,
    reshard,
    resharder,
)


def attach_mesh(comm, mesh, axis) -> None:
    """Give a communicator a device mesh, enabling the coll/xla component
    (re-runs coll selection so xla outranks the host components).

    On an INTERcommunicator the mesh describes this side's local group;
    collectives then take the hierarchical ICI/DCN shape (InterXlaColl):
    intra-group phases as XLA programs over this mesh, leader bridge on
    the host path. Each side attaches its own mesh — two slices."""
    if comm.is_inter:
        lc = comm.local_comm
        if lc is None:
            raise ValueError(
                f"intercomm {comm.name} has no local_comm to carry a mesh")
        if getattr(lc, "device_comm", None) is None:
            attach_mesh(lc, mesh, axis)
        elif lc.device_mesh is not mesh or lc.device_axis != axis:
            # the collectives run on the local_comm's mesh — recording a
            # different one here would silently diverge from reality
            raise ValueError(
                f"intercomm {comm.name}: local_comm already carries mesh "
                f"axis {lc.device_axis!r}; detach or pass the same mesh")
        comm.device_mesh = lc.device_mesh
        comm.device_axis = lc.device_axis
        from ..coll.inter import InterXlaColl

        comm.coll = InterXlaColl()
        return
    if axis is None:
        # topology-only attach: the comm's ranks tile the WHOLE (possibly
        # multi-axis) mesh — records the machine hierarchy for topology
        # mapping (topo.cart_create reorder / hierarchy.auto_levels)
        # without electing a collective axis
        if comm.size != 1 and mesh.size != comm.size:
            raise ValueError(
                f"mesh has {mesh.size} devices but comm {comm.name} has "
                f"{comm.size} ranks")
        comm.device_mesh = mesh
        comm.device_axis = None
        return
    if isinstance(axis, (tuple, list)):
        # a tuple of axis names spans their row-major product — the
        # two-tier (ICI×DCN) comm shape the hier arm addresses by level
        axis = tuple(axis)
        ax_size = 1
        for a in axis:
            ax_size *= mesh.shape[a]
    else:
        ax_size = mesh.shape[axis]
    if comm.size != 1 and ax_size != comm.size:
        raise ValueError(
            f"mesh axis {axis!r} has {ax_size} devices but "
            f"comm {comm.name} has {comm.size} ranks")
    comm.device_mesh = mesh
    comm.device_axis = axis
    comm.device_comm = DeviceComm(mesh, axis)
    # device payloads on this comm ride the ICI p2p channel (p2p/devchan)
    p2p = getattr(getattr(comm, "ctx", None), "p2p", None)
    if p2p is not None:
        p2p.device_cids.add(comm.cid)
    from ..coll.framework import attach_coll

    attach_coll(comm)
