"""TPU-first parallelism layer: device meshes, named-axis collectives,
DeviceComm, sequence/context parallelism, hierarchical collectives.

This package is the re-imagined face of the reference's parallelism-backing
machinery (SURVEY.md §2.6): DP/TP rides allreduce/reduce-scatter/allgather,
SP/CP rides ppermute rings and all_to_all (Ulysses), hierarchical rides the
ICI/DCN axis split (≙ coll/han)."""

from .mesh import (  # noqa: F401
    STANDARD_AXES,
    classify_axes,
    make_mesh,
    replicated,
    shard_leading,
    sharded,
)
from .ring import attention_reference, ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .hierarchy import (  # noqa: F401
    auto_levels,
    hierarchical_allreduce,
    hierarchical_psum,
)
from .device_plane import device_plane_active, init_device_plane  # noqa: F401
from .collectives import (  # noqa: F401
    DeviceComm,
    all_gather_axis,
    all_to_all_axis,
    pbcast,
    pmax,
    pmin,
    ppermute,
    preduce,
    psum,
    reduce_scatter_axis,
    ring_shift,
)


def attach_mesh(comm, mesh, axis: str) -> None:
    """Give a communicator a device mesh, enabling the coll/xla component
    (re-runs coll selection so xla outranks the host components)."""
    if comm.size != 1 and mesh.shape[axis] != comm.size:
        raise ValueError(
            f"mesh axis {axis!r} has {mesh.shape[axis]} devices but "
            f"comm {comm.name} has {comm.size} ranks")
    comm.device_mesh = mesh
    comm.device_axis = axis
    comm.device_comm = DeviceComm(mesh, axis)
    from ..coll.framework import attach_coll

    attach_coll(comm)
