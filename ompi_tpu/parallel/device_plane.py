"""Multi-process device plane — rank-per-chip wiring (north star).

The reference's process model is one OS process per rank, bound to its
device by the launcher (PRRTE binding, ompi/runtime/ompi_rte.c:536). JAX's
single-controller mode (one process owns the whole mesh) is the opposite;
the north star (BASELINE.json) requires the MPI model: every tpurun rank is
its own process owning its own chip(s), and device collectives run across
processes over ICI.

This module bridges the two control planes: the ompi_tpu bootstrap (modex/
fence — our PMIx) elects and distributes the JAX coordination-service
address, then ``jax.distributed.initialize`` wires PJRT's cross-process
runtime. After ``init_device_plane(ctx)``:

  * ``jax.devices()`` spans every rank's chips (local + proxies);
  * a ``Mesh`` over them with ``DeviceComm.from_local``/``to_local`` gives
    MPI-shaped device collectives where each rank contributes its own rows
    — the multi-process analog of the single-controller ``from_ranks``;
  * compiled collectives execute as one SPMD program per rank, riding ICI
    on TPU pods (gloo on CPU hosts — the test fabric).

Chip pinning is the launcher's job (tpurun --chips-per-rank sets
TPU_VISIBLE_DEVICES per rank; --device-plane cpu forces the 1-device-per-
process CPU fabric for tests), mirroring how PRRTE owns binding.
"""

from __future__ import annotations

import os
import socket
from typing import Optional

_initialized = False


def _pick_port() -> int:
    # TOCTOU caveat: the port is free when probed, bound by the JAX
    # coordination service shortly after — another process could snipe it
    # in between (rare; manifests as a failed initialize and a failed job,
    # which the launcher surfaces). jax.distributed offers no bind-to-0 +
    # report-back path, so a probe is the practical option.
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def init_device_plane(ctx, coordinator: Optional[str] = None,
                      timeout_s: int = 60) -> None:
    """Wire JAX's multi-process runtime from the bootstrap control plane.

    Must run before the first JAX backend use in this process (the same
    constraint jax.distributed.initialize documents). Idempotent per
    process. Rank 0 hosts the coordination service; its address travels
    through the modex (≙ how PMIx distributes wire-up info at
    instance.c:529-596).
    """
    global _initialized
    if _initialized:
        return
    import jax

    # Honor the launcher's device-plane choice through jax.config: the
    # JAX_PLATFORMS env route can be ignored by sitecustomize-registered
    # plugins (and several rank processes concurrently initializing a
    # tunneled TPU plugin can wedge each other).
    if os.environ.get("OMPI_TPU_DEVICE_PLANE") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    # device-plane identity is WORLD-relative: a spawned child job elects
    # its own coordinator (its lowest world rank) and numbers processes by
    # world position, so process_id ∈ [0, num_processes) holds even though
    # global ranks start at WORLD_BASE
    members = list(getattr(ctx, "world_ranks", range(ctx.size)))
    pos = members.index(ctx.rank)
    if coordinator is None:
        if pos == 0:
            host = os.environ.get("OMPI_TPU_COORD", "127.0.0.1:0"
                                  ).rpartition(":")[0] or "127.0.0.1"
            coordinator = f"{host}:{_pick_port()}"
            ctx.bootstrap.put("jax_coordinator", coordinator)
        else:
            coordinator = str(ctx.bootstrap.get(members[0],
                                                "jax_coordinator",
                                                timeout=timeout_s))
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=ctx.size,
        process_id=pos,
        initialization_timeout=timeout_s,
    )
    _initialized = True


def device_plane_active() -> bool:
    return _initialized
