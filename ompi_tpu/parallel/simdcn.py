"""parallel/simdcn — the simulated-DCN delay shim.

A single-process CPU mesh is one flat ICI plane: every arm sees the same
fabric, so the hierarchical (`hier`) arm — whose entire value is moving
n_inner× fewer bytes over the SLOW plane — can never win a wall-clock
sweep in CI.  This shim makes the simulated slow plane cost something:
when ``topo_sim_dcn_us_per_mib`` is nonzero, every audited device
collective is charged a host-side sleep proportional to the bytes its
geometry moves across a simulated DCN boundary (axes named by
``topo_sim_dcn_axes``, the same override ``classify_axes`` and the
traffic plane's edge classifier honor).

The model is deliberately simple — a bandwidth-proportional penalty with
no contention — because its only job is to order arms the way a real
two-tier fabric would: flat arms pay for their full cross-boundary
share, `hier` pays only for the scattered outer stage, `hier+quant` for
a quarter of that.  The shim sits in coll/xla's audit path (one branch
when disabled) so `bench.py --pod`, `coll_tune --device` hier sweeps and
the plane-keyed perf-ledger cells all see the same skew.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple, Union

import numpy as np

from ..core import var as _var
from .mesh import classify_axes, sim_dcn_axes

AxisLike = Union[str, Tuple[str, ...]]

# ring-geometry DCN fraction per (mesh id, axis) — meshes are long-lived
# and few (same bound rationale as traffic/planes._PROC_CACHE)
_FRAC_CACHE: Dict[Tuple[int, AxisLike], float] = {}
_FRAC_CACHE_MAX = 32


def axis_tuple(axis: AxisLike) -> Tuple[str, ...]:
    """Normalize a DeviceComm axis (one name or a tuple) to a tuple."""
    return tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)


def us_per_mib() -> float:
    """Configured shim cost (0.0 = shim off)."""
    try:
        return float(_var.get("topo_sim_dcn_us_per_mib", 0.0))
    except (TypeError, ValueError):
        return 0.0


def ring_dcn_fraction(mesh, axis: AxisLike) -> float:
    """Fraction of the axis ring's hops that cross a DCN boundary.

    The ring runs over the flattened (row-major) product of the named
    axes — the same order a flat collective over a tuple axis uses — and
    a hop crosses DCN when the coordinate changes along any
    DCN-classified axis (real process boundaries or the sim override).
    """
    key = (id(mesh), axis_tuple(axis), tuple(sorted(sim_dcn_axes())))
    got = _FRAC_CACHE.get(key)
    if got is not None:
        return got
    axes = axis_tuple(axis)
    kinds = classify_axes(mesh)
    sizes = [int(mesh.shape[a]) for a in axes]
    n = int(np.prod(sizes))
    if n < 2:
        frac = 0.0
    else:
        dcn_dims = [k for k, a in enumerate(axes) if kinds.get(a) == "dcn"]
        if not dcn_dims:
            frac = 0.0
        else:
            cross = 0
            for i in range(n):
                ci = np.unravel_index(i, sizes)
                cj = np.unravel_index((i + 1) % n, sizes)
                if any(ci[k] != cj[k] for k in dcn_dims):
                    cross += 1
            frac = cross / n
    if len(_FRAC_CACHE) >= _FRAC_CACHE_MAX:
        _FRAC_CACHE.clear()
    _FRAC_CACHE[key] = frac
    return frac


def penalty_us(dcn_bytes: int, us_mib: float = None) -> float:
    """Modeled delay for ``dcn_bytes`` crossing the simulated boundary."""
    us = us_per_mib() if us_mib is None else us_mib
    if us <= 0 or dcn_bytes <= 0:
        return 0.0
    return dcn_bytes / float(1 << 20) * us


def charge(dcn_bytes: int) -> None:
    """Sleep the modeled delay (no-op when the shim is off)."""
    us = penalty_us(int(dcn_bytes))
    if us > 0:
        time.sleep(us * 1e-6)


def clear_cache() -> None:
    """Test helper: the fraction cache keys on mesh identity, but the
    classification behind it moves with the sim vars."""
    _FRAC_CACHE.clear()
