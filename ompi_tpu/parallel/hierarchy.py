"""Hierarchical (two-level) collectives — the HAN analog.

≙ ompi/mca/coll/han: split a collective into an intra-node stage and an
inter-node stage over sub-communicators (coll_han_allreduce.c:92,
coll_han_subcomms.c). On TPU the levels are mesh axes: `inner` rides ICI
within a slice, `outer` rides DCN between slices/hosts. The bandwidth shape
is the same as HAN's: reduce-scatter inner → allreduce outer on 1/n_inner of
the data → allgather inner, so the slow (DCN) hops carry only the scattered
fraction.

On a single-slice mesh XLA would fuse a plain two-axis psum anyway; the
explicit staged form exists because on multi-slice meshes the outer allreduce
must move n_inner× less data over DCN — the entire point of HAN.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..jaxcompat import shard_map
from .mesh import classify_axes

# classify_axes is re-exported here as the PUBLIC topology-inference
# entry point: the traffic plane (traffic/planes.py) and auto_levels
# both key off the same ICI/DCN axis split, so there is exactly one
# implementation to pin in tests.
__all__ = ["classify_axes", "hierarchical_psum", "hierarchical_psum_quant",
           "hierarchical_allreduce", "auto_levels", "hier_axes",
           "hier_wire_bytes"]


def _pad_to_inner(x, inner: str):
    """Zero-pad dim 0 to a multiple of the inner axis size (exact for a
    sum — the pad rows reduce to zero and are sliced off after the
    allgather).  Returns (padded, original_len)."""
    ni = lax.psum(1, inner)        # static under shard_map
    orig = x.shape[0]
    pad = (-orig) % ni
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
    return x, orig


def hierarchical_psum(x, inner: str, outer: str):
    """For use inside shard_map: reduce-scatter over `inner`, psum over
    `outer`, allgather over `inner`.  Dim 0 of any length: non-divisible
    shapes (real gradient flats) are zero-padded to a multiple of the
    inner axis size and sliced back after the allgather."""
    x, orig = _pad_to_inner(x, inner)
    scattered = lax.psum_scatter(x, inner, scatter_dimension=0, tiled=True)
    reduced = lax.psum(scattered, outer)
    out = lax.all_gather(reduced, inner, axis=0, tiled=True)
    return out[:orig] if out.shape[0] != orig else out


def hierarchical_psum_quant(x, inner: str, outer: str, n_outer: int,
                            block: int = None):
    """The `hier+quant` composition: same HAN shape, but the OUTER
    (DCN) allreduce rides the EQuARX block-quantized tier
    (coll/quant.psum_quant) while both inner (ICI) stages stay
    bitwise-native — the 2-rounding quantization error is paid only
    where the ~4x wire-byte cut buys wall-clock, on top of the
    n_inner× hierarchical reduction."""
    from ..coll.quant import psum_quant

    x, orig = _pad_to_inner(x, inner)
    scattered = lax.psum_scatter(x, inner, scatter_dimension=0, tiled=True)
    reduced = psum_quant(scattered, outer, n_outer, block=block)
    out = lax.all_gather(reduced, inner, axis=0, tiled=True)
    return out[:orig] if out.shape[0] != orig else out


def hierarchical_allreduce(x: jax.Array, mesh: Mesh, inner: str, outer: str
                           ) -> jax.Array:
    """Standalone two-level allreduce over both axes of a mesh.

    x: (n_outer, n_inner, *elem) sharded over (outer, inner) — each (i, j)
    row is that rank's buffer; every row gets the global reduction.
    """
    spec = P(outer, inner)

    def local(xs):                    # (1, 1, *elem)
        flat = xs.reshape(xs.shape[2:])
        out = hierarchical_psum(flat, inner, outer)
        return out[None, None]

    from .. import traffic
    if traffic.enabled and not isinstance(x, jax.core.Tracer):
        # inner RS/AG rings + the outer ring on the scattered 1/n_inner
        # fraction — the per-plane rollup shows the HAN bandwidth shape
        ni = mesh.devices.shape[mesh.axis_names.index(inner)]
        no = mesh.devices.shape[mesh.axis_names.index(outer)]
        traffic.note_hierarchical(mesh, inner, outer,
                                  x.nbytes // max(ni * no, 1))

    fn = jax.jit(shard_map(local, mesh=mesh, in_specs=spec,
                           out_specs=spec))
    return fn(x)


def auto_levels(mesh: Mesh):
    """Pick (inner, outer) from topology: ICI axes inner, DCN axes outer
    (classify_axes); falls back to (last, first) axis on flat meshes."""
    kinds = classify_axes(mesh)
    ici = [a for a, k in kinds.items() if k == "ici"]
    dcn = [a for a, k in kinds.items() if k == "dcn"]
    if ici and dcn:
        return ici[-1], dcn[0]
    names = list(mesh.axis_names)
    return names[-1], names[0]


def hier_axes(mesh: Mesh, axis):
    """Eligibility probe for the `hier` decision arm: given the axis (or
    axis tuple) a DeviceComm spans, return ``(inner, outer, None)`` when
    the comm is genuinely two-tier — at least one ICI level and one DCN
    level (classify_axes, including the ``topo_sim_dcn_axes`` override),
    both larger than 1 — else ``(None, None, why)`` where ``why`` is the
    human-readable ineligibility reason the decision audit records
    (``ineligible:hier:<why>``).  Unlike :func:`auto_levels` this never
    invents a split on a flat mesh: a single-plane comm has no slow tier
    to spare, so `hier` would only add stage latency."""
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    if len(axes) < 2:
        return None, None, "single-axis comm (no inner/outer levels)"
    kinds = classify_axes(mesh)
    dcn = [a for a in axes if kinds.get(a) == "dcn"]
    ici = [a for a in axes if kinds.get(a) == "ici"]
    if not dcn:
        return None, None, "single-plane mesh (no DCN axis among " \
            f"{axes})"
    if not ici:
        return None, None, "no ICI axis to scatter over (all of " \
            f"{axes} cross DCN)"
    inner, outer = ici[-1], dcn[0]
    if mesh.shape[inner] < 2:
        return None, None, f"degenerate inner level {inner!r} (size 1)"
    if mesh.shape[outer] < 2:
        return None, None, f"degenerate outer level {outer!r} (size 1)"
    return inner, outer, None


def hier_wire_bytes(count: int, dtype, ni: int, no: int,
                    quant: bool = False, block: int = None,
                    scale_dtype=None) -> dict:
    """Per-rank wire bytes of one hierarchical allreduce of ``count``
    elements: the HAN stage math — inner reduce-scatter and allgather
    each move (ni-1)/ni of the buffer over ICI, the outer allreduce
    moves 2(no-1)/no of the SCATTERED 1/ni fraction over DCN (the
    n_inner× slow-plane cut that is the algorithm's whole point).

    With ``quant`` the outer stage rides the EQuARX tier and its figure
    comes from coll/quant.wire_bytes (int8 payload + per-block scales);
    the inner stages stay native.  This is the single source of truth
    for the decision audit, the traffic plane's inner/outer split and
    the simulated-DCN delay shim — traffic conservation holds because
    all three read the same numbers.
    """
    import numpy as np

    esize = np.dtype(dtype).itemsize
    nbytes = int(count) * esize
    inner_stage = int((ni - 1) / ni * nbytes) if ni > 1 else 0
    outer_native = int(2 * (no - 1) / no * (nbytes // ni)) if no > 1 else 0
    outer = outer_native
    ratio = None
    if quant and no > 1:
        from ..coll.quant import wire_bytes as _qwire
        wb = _qwire("allreduce", max(int(count) // ni, 1), no, dtype,
                    block, scale_dtype)
        outer = wb["quant_bytes"]
        ratio = (outer / outer_native) if outer_native else None
    return {"inner_bytes": 2 * inner_stage,      # RS + AG stages
            "inner_stage_bytes": inner_stage,
            "outer_bytes": outer,
            "outer_native_bytes": outer_native,
            "total_bytes": 2 * inner_stage + outer,
            "ratio": ratio}
