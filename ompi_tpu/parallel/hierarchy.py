"""Hierarchical (two-level) collectives — the HAN analog.

≙ ompi/mca/coll/han: split a collective into an intra-node stage and an
inter-node stage over sub-communicators (coll_han_allreduce.c:92,
coll_han_subcomms.c). On TPU the levels are mesh axes: `inner` rides ICI
within a slice, `outer` rides DCN between slices/hosts. The bandwidth shape
is the same as HAN's: reduce-scatter inner → allreduce outer on 1/n_inner of
the data → allgather inner, so the slow (DCN) hops carry only the scattered
fraction.

On a single-slice mesh XLA would fuse a plain two-axis psum anyway; the
explicit staged form exists because on multi-slice meshes the outer allreduce
must move n_inner× less data over DCN — the entire point of HAN.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..jaxcompat import shard_map
from .mesh import classify_axes

# classify_axes is re-exported here as the PUBLIC topology-inference
# entry point: the traffic plane (traffic/planes.py) and auto_levels
# both key off the same ICI/DCN axis split, so there is exactly one
# implementation to pin in tests.
__all__ = ["classify_axes", "hierarchical_psum",
           "hierarchical_allreduce", "auto_levels"]


def hierarchical_psum(x, inner: str, outer: str):
    """For use inside shard_map: reduce-scatter over `inner`, psum over
    `outer`, allgather over `inner`. x's leading dim must be divisible by
    the inner axis size."""
    scattered = lax.psum_scatter(x, inner, scatter_dimension=0, tiled=True)
    reduced = lax.psum(scattered, outer)
    return lax.all_gather(reduced, inner, axis=0, tiled=True)


def hierarchical_allreduce(x: jax.Array, mesh: Mesh, inner: str, outer: str
                           ) -> jax.Array:
    """Standalone two-level allreduce over both axes of a mesh.

    x: (n_outer, n_inner, *elem) sharded over (outer, inner) — each (i, j)
    row is that rank's buffer; every row gets the global reduction.
    """
    spec = P(outer, inner)

    def local(xs):                    # (1, 1, *elem)
        flat = xs.reshape(xs.shape[2:])
        out = hierarchical_psum(flat, inner, outer)
        return out[None, None]

    from .. import traffic
    if traffic.enabled and not isinstance(x, jax.core.Tracer):
        # inner RS/AG rings + the outer ring on the scattered 1/n_inner
        # fraction — the per-plane rollup shows the HAN bandwidth shape
        ni = mesh.devices.shape[mesh.axis_names.index(inner)]
        no = mesh.devices.shape[mesh.axis_names.index(outer)]
        traffic.note_hierarchical(mesh, inner, outer,
                                  x.nbytes // max(ni * no, 1))

    fn = jax.jit(shard_map(local, mesh=mesh, in_specs=spec,
                           out_specs=spec))
    return fn(x)


def auto_levels(mesh: Mesh):
    """Pick (inner, outer) from topology: ICI axes inner, DCN axes outer
    (classify_axes); falls back to (last, first) axis on flat meshes."""
    kinds = classify_axes(mesh)
    ici = [a for a, k in kinds.items() if k == "ici"]
    dcn = [a for a, k in kinds.items() if k == "dcn"]
    if ici and dcn:
        return ici[-1], dcn[0]
    names = list(mesh.axis_names)
    return names[-1], names[0]
