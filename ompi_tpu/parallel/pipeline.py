"""Pipeline parallelism — a GPipe-style schedule over a mesh axis.

≙ what PP users build on the reference's p2p/partitioned sends
(pml_ob1_isend.c:249, ompi/mca/part/part.h:30 — SURVEY.md §2.6): stage
boundaries are neighbor exchanges. TPU-natively that is NOT host-driven
send/recv: all stages run ONE compiled SPMD program under ``shard_map``
over the ``pp`` axis, stage-local parameters come from a leading
stages-dimension sharded over that axis, and the boundary transfer is a
``lax.ppermute`` ring shift per schedule tick — the compiler overlaps the
shift with the next tick's compute on the MXU (the same
communication/compute overlap 1F1B hand-schedules on GPU clusters).

Schedule: M microbatches drain through P stages in M+P-1 ticks (GPipe).
Memory for the backward pass is handled by XLA's remat of the tick scan
(``jax.checkpoint`` on the stage function), not by hand-interleaving —
under jax.grad the whole pipeline differentiates as one program, which is
the TPU-first answer to 1F1B's purpose (bounding live activations).

Weight layout: ``stack_stage_params`` pytrees L layers into P stages of
L/P stacked layers; inside the program each stage reads its own slice via
``lax.axis_index``-free shard_map slicing (the leading dim IS the pp
shard), and runs its layers with a ``lax.scan`` (compile once per stage
depth, not per layer).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import trace
from ..jaxcompat import shard_map


def stack_stage_params(layer_params: list, n_stages: int):
    """[L per-layer pytrees] → pytree with leading (P, L//P) dims, ready to
    shard P over the pp axis."""
    n = len(layer_params)
    if n % n_stages:
        raise ValueError(f"{n} layers do not split into {n_stages} stages")
    per = n // n_stages
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layer_params)
    return jax.tree.map(
        lambda x: x.reshape((n_stages, per) + x.shape[1:]), stacked)


def shard_stage_params(stacked, mesh: Mesh, axis: str = "pp"):
    """Put the stages dimension on the pp axis (everything else replicated;
    compose with tp specs by sharding trailing dims upstream)."""
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))),
        stacked)


_RUN_CACHE: dict = {}


def _traced_run(jitted: Callable, stage_params, microbatches,
                n_stages: int, m_count: int, axis: str,
                cached: bool) -> jax.Array:
    """Execute the jitted schedule; when tracing is on, record one
    MEASURED run span (block_until_ready bounds it) plus per-tick spans.
    The host cannot observe tick boundaries inside the single compiled
    shard_map program, so tick spans are an even subdivision of the run —
    marked ``synthetic`` — annotating what each tick's ppermute ring
    shift sends and which stage ingests/emits a microbatch."""
    if not trace.enabled or isinstance(microbatches, jax.core.Tracer):
        # under an outer jit/grad trace there is nothing to time: the
        # schedule inlines into the caller's program
        return jitted(stage_params, microbatches)
    t0 = time.perf_counter()
    try:
        out = jax.block_until_ready(jitted(stage_params, microbatches))
    except BaseException:
        trace.record_span("pipeline:run", "pipeline", t0,
                          time.perf_counter(),
                          args={"stages": n_stages,
                                "microbatches": m_count,
                                "axis": axis, "status": "error"})
        raise
    t1 = time.perf_counter()
    ticks = m_count + n_stages - 1
    trace.record_span(
        "pipeline:run", "pipeline", t0, t1,
        args={"stages": n_stages, "microbatches": m_count,
              "ticks": ticks, "axis": axis,
              "cache": "hit" if cached else "miss"})
    per = (t1 - t0) / max(ticks, 1)
    for t in range(ticks):
        trace.record_span(
            "pipeline:tick", "pipeline-ticks",
            t0 + t * per, t0 + (t + 1) * per,
            args={"tick": t, "synthetic": True,
                  "send": "ppermute ring shift (stage i -> i+1)",
                  "ingest": t if t < m_count else None,
                  "emit": t - (n_stages - 1)
                  if t >= n_stages - 1 else None})
    return out


def pipeline(stage_fn: Callable[[Any, jax.Array], jax.Array],
             stage_params, microbatches: jax.Array, mesh: Mesh,
             axis: str = "pp", checkpoint: bool = True) -> jax.Array:
    """Run ``microbatches`` (M, mb, ...) through P pipeline stages.

    ``stage_fn(params_for_stage, x) -> y`` maps one microbatch through one
    stage; activations keep one shape across stages (the transformer
    residual-stream invariant). Returns (M, mb, ...) outputs of the LAST
    stage. Differentiable end-to-end (jax.grad through the tick scan).
    """
    n_stages = mesh.shape[axis]
    m_count = microbatches.shape[0]
    # cache the jitted schedule per (stage_fn, mesh, shape class): a fresh
    # closure per call would defeat jax.jit's cache and retrace every step.
    # Bounded FIFO: per-call stage_fn closures must not leak an executable
    # per step (they still miss — pass a stable stage_fn to actually cache)
    cache_key = (stage_fn, mesh, axis, checkpoint, m_count,
                 microbatches.ndim, jax.tree.structure(stage_params))
    cached = _RUN_CACHE.get(cache_key)
    if cached is not None:
        return _traced_run(cached, stage_params, microbatches,
                           n_stages, m_count, axis, cached=True)
    while len(_RUN_CACHE) >= 32:
        _RUN_CACHE.pop(next(iter(_RUN_CACHE)))
    fn = jax.checkpoint(stage_fn) if checkpoint else stage_fn

    mb_spec = P(*([None] * microbatches.ndim))
    par_spec = jax.tree.map(lambda _: P(axis), stage_params)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(par_spec, mb_spec),
        out_specs=mb_spec, check_vma=False)
    def run(params, mbs):
        # params leaves: (1, L/P, ...) — my stage's slice; mbs: (M, mb, ...)
        my = jax.tree.map(lambda x: x[0], params)
        stage = lax.axis_index(axis)
        last = n_stages - 1
        zero = jnp.zeros(mbs.shape[1:], mbs.dtype)

        def tick(carry, t):
            state, outs = carry
            # stage 0 ingests microbatch t (when one remains); others take
            # the ppermute'd activation from the previous tick
            feed = lax.cond(t < m_count,
                            lambda: lax.dynamic_index_in_dim(
                                mbs, jnp.minimum(t, m_count - 1), 0,
                                keepdims=False),
                            lambda: zero)
            x = jnp.where(stage == 0, feed, state)
            y = fn(my, x)
            # the microbatch leaving the LAST stage at tick t is t-(P-1)
            out_idx = t - last
            outs = lax.cond(
                (stage == last) & (out_idx >= 0),
                lambda: lax.dynamic_update_index_in_dim(
                    outs, y, jnp.maximum(out_idx, 0), 0),
                lambda: outs)
            # shift every stage's output one stage forward
            # comm-lint: disable=CL001 the stage->stage shift IS the 1F1B schedule; traced and span-annotated by _traced_run, not an engine-dispatchable collective
            state = lax.ppermute(
                y, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (state, outs), None

        outs0 = jnp.zeros_like(mbs)
        (_, outs), _ = lax.scan(
            tick, (zero, outs0), jnp.arange(m_count + n_stages - 1))
        # only the last stage holds real outputs; broadcast them to all
        # stages so the result is replicated over pp (psum of a one-hot)
        # comm-lint: disable=CL001 one-hot broadcast of the last stage's outputs; replication step of the schedule itself, not a tunable reduction
        outs = lax.psum(jnp.where(stage == last, outs, jnp.zeros_like(outs)),
                        axis)
        return outs

    # jit so the schedule compiles as one program even when called eagerly
    # (checkpointed stage_fn inside shard_map requires a surrounding jit;
    # nested jit is a no-op when the caller already traces)
    jitted = jax.jit(run)
    _RUN_CACHE[cache_key] = jitted
    return _traced_run(jitted, stage_params, microbatches,
                       n_stages, m_count, axis, cached=False)
