"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The alltoall-backed alternative to ring attention (SURVEY.md §5.7 — "Ulysses
= alltoall of heads", coll_base_alltoall.c): with sequence sharded over the
`sp` axis, two ``lax.all_to_all``s re-shard from sequence-parallel to
head-parallel, run *dense local attention over the full sequence* for the
local head subset, and shard back. Communication is 2 all-to-alls of
activation size versus ring attention's (n-1) K/V hops; on ICI-rich slices
with moderate sequence lengths this usually wins; ring wins at extreme
sequence lengths (K/V streaming, O(seq/n) memory).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..jaxcompat import shard_map
from .ring import attention_reference


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                      axis: str = "sp", causal: bool = False,
                      scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None) -> jax.Array:
    """q/k/v: (batch, seq, heads, head_dim), seq sharded over `axis`;
    heads must be divisible by the axis size."""
    n = mesh.shape[axis]
    if q.shape[2] % n != 0:
        raise ValueError(f"heads {q.shape[2]} not divisible by axis size {n}")
    from .. import traffic
    if traffic.enabled and not isinstance(q, jax.core.Tracer) and n > 1:
        # four tiled all_to_alls (q/k/v seq->heads + the output
        # heads->seq), each moving one per-rank shard: wire =
        # (q + k + v + out) / n with out the size of q — the figure
        # the static verifier re-derives from the traced per-shard
        # all_to_all avals (analysis/commgraph), byte-for-byte
        traffic.note_a2a(mesh, axis,
                         (2 * q.nbytes + k.nbytes + v.nbytes) // n,
                         "ulysses")
    return _build_ulysses(mesh, axis, bool(causal), scale, attn_fn)(q, k, v)


import functools


@functools.lru_cache(maxsize=128)
def _build_ulysses(mesh: Mesh, axis: str, causal: bool,
                   scale: Optional[float], attn_fn: Optional[Callable]):
    attn = attn_fn or (lambda qq, kk, vv: attention_reference(
        qq, kk, vv, causal=causal, scale=scale))

    def local(qs, ks, vs):
        # local: (b, s/n, h, d) → exchange → (b, s, h/n, d)
        def seq_to_heads(x):
            # comm-lint: disable=CL001 the tiled alltoall IS the ulysses algorithm (head/seq transpose); wire bytes attributed eagerly via traffic.note_a2a in ulysses_attention
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        def heads_to_seq(x):
            # comm-lint: disable=CL001 inverse transpose of the waived seq_to_heads exchange
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        qh, kh, vh = seq_to_heads(qs), seq_to_heads(ks), seq_to_heads(vs)
        out = attn(qh, kh, vh)            # dense attention, full sequence
        return heads_to_seq(out)

    spec = P(None, axis, None, None)
    # comm-lint: disable=CL001 leaf SPMD kernel: only comm is the waived alltoall pair, statically verified by analysis.commgraph
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec))
