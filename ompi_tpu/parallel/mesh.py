"""Device mesh construction and topology mapping.

The TPU-native answer to the reference's process/topology layer: where Open
MPI wires COMM_WORLD onto hosts/NICs via PRRTE + hwloc (SURVEY.md §3.4), a
TPU job wires its ranks onto a slice's chips via a named-axis
``jax.sharding.Mesh``. Axis names carry the parallelism intent (dp/fsdp/tp/
sp/pp/ep), and axis *order* encodes the ICI-vs-DCN hierarchy the same way
coll/han splits intra-node vs inter-node communicators
(ompi/mca/coll/han/coll_han_subcomms.c): the innermost axes should map onto
ICI neighbors, the outermost onto DCN (process) boundaries.

``jax.make_mesh`` already performs topology-aware device ordering on TPU;
these helpers add the job-level conventions (standard axis names, hierarchy
classification, per-axis subcommunicator views).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import var as _var
from ..jaxcompat import auto_axis_types

# conventional axis names, outer→inner (DCN-most → ICI-most)
STANDARD_AXES = ("dp", "fsdp", "pp", "ep", "sp", "tp")

# the simulated DCN plane: a single-process CPU test mesh has no real
# slice boundaries, so the two-tier decision layer (hier arm, plane-keyed
# rules, per-plane traffic rollup) would be untestable before multi-slice
# hardware.  Naming axes here force-classifies them as 'dcn' everywhere
# the topology is consulted (classify_axes, traffic/planes.plane_fn); the
# companion delay shim (parallel/simdcn) charges wall-clock per byte that
# crosses the simulated boundary so arm sweeps see a skewed fabric.
_var.register("topo", "sim", "dcn_axes", "", type=str, level=4,
              help="Comma-separated mesh axis names to force-classify as "
                   "DCN (simulated slow plane for single-process test "
                   "meshes; empty = infer from process boundaries).")
_var.register("topo", "sim", "dcn_us_per_mib", 0.0, type=float, level=4,
              help="Simulated-DCN delay shim: host-side microseconds "
                   "charged per MiB that crosses a simulated DCN "
                   "boundary (parallel/simdcn; 0 = shim off).")


def sim_dcn_axes() -> FrozenSet[str]:
    """Axis names the sim-DCN override forces to 'dcn' (empty = off)."""
    raw = str(_var.get("topo_sim_dcn_axes", "") or "")
    return frozenset(a.strip() for a in raw.split(",") if a.strip())


def make_mesh(axes: Dict[str, int],
              devices: Optional[Sequence] = None) -> Mesh:
    """Create a named mesh, e.g. ``make_mesh({"dp": 2, "tp": 4})``.

    Axis sizes must multiply to the device count; pass ``-1`` for at most one
    axis to absorb the remainder (like a reshape). Axes are *Auto* (GSPMD
    infers intermediate shardings from annotations — the classic
    annotate-and-let-XLA-insert-collectives mode); shard_map programs enter
    Manual mode on top of this as usual.
    """
    devs = list(devices) if devices is not None else jax.devices()
    names, sizes = list(axes.keys()), list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devs) // known
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, "
            f"have {len(devs)}")
    auto = auto_axis_types(len(names))
    if devices is None:
        return jax.make_mesh(tuple(sizes), tuple(names), **auto)
    return Mesh(np.asarray(devs).reshape(sizes), tuple(names), **auto)


def axis_index_of(mesh: Mesh, axis: str, device) -> int:
    """Which position along `axis` a device occupies."""
    coords = np.argwhere(mesh.devices == device)
    return int(coords[0][mesh.axis_names.index(axis)])


def classify_axes(mesh: Mesh) -> Dict[str, str]:
    """Classify each axis as 'ici' (within a process/slice) or 'dcn'
    (crosses process boundaries) — the han intra/inter split. An axis is
    'dcn' when moving along it changes the process index on ANY line of
    the mesh, not just the first one (the old first-line probe missed
    meshes whose process boundary only shows up at nonzero coordinates
    of the other axes). On CPU test meshes everything is 'ici' unless
    the ``topo_sim_dcn_axes`` override names a simulated slow plane."""
    out = {}
    sim = sim_dcn_axes()
    devs = np.asarray(mesh.devices)
    procs = np.frompyfunc(
        lambda d: int(getattr(d, "process_index", 0)), 1, 1)(
        devs).astype(np.int64)
    for i, name in enumerate(mesh.axis_names):
        if name in sim:
            out[name] = "dcn"
            continue
        moved = np.moveaxis(procs, i, 0)
        out[name] = "dcn" if bool((moved != moved[:1]).any()) else "ici"
    return out


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def shard_leading(mesh: Mesh, axis: str) -> NamedSharding:
    """Shard dim 0 over `axis` — the canonical layout for per-rank blocks."""
    return NamedSharding(mesh, P(axis))
