"""parallel/overlap — bucketed backward-overlapped gradient sync.

The dp gradient allreduce is the framework's highest-volume collective,
and the seed issued it in the worst possible shape: one collective per
parameter leaf AFTER the full backward (``_quant_grad_sync``), so tiny
leaves (norms, biases) pay the dispatch latency floor and the ICI sits
idle during all of backward.  This module is the DDP-style answer:
gradients are flattened into fixed-byte BUCKETS (default ~4 MiB,
``coll_xla_grad_bucket_bytes`` / ``Config(grad_bucket_bytes=...)``) in
reverse flatten order — the order the backward pass produces them — and
each bucket's allreduce is issued the moment its last cotangent exists,
so bucket *i*'s exchange overlaps the remaining backward compute (XLA's
latency-hiding scheduler interleaves the collective with the ongoing
dots) instead of serializing after it.

Mechanism: an identity ``jax.custom_vjp`` "tag" wraps each bucket's
parameter leaves on the way INTO the loss; its backward rule therefore
receives exactly that bucket's cotangents at the point in the backward
graph where they are produced, concatenates them into one flat f32
vector, runs ONE allreduce — native ``lax.pmean`` or the block-quantized
``coll/quant.psum_quant`` (EQuARX tier), chosen per bucket by the same
decision layer that arbitrates every other device collective
(``coll/xla.decide_mode`` with coll name ``grad_sync``: force var >
blanket switch > DEVICE_RULES rows > platform default) — and splits the
result back into per-leaf gradients.  The per-leaf collective storm
collapses to at most ``ceil(total_grad_bytes / bucket_bytes)`` exchanges.

Like ``_quant_grad_sync``, the shard_map here runs over ``dp`` only: on
a dp×tp/sp mesh it would replicate the other axes and silently undo
their parameter sharding, so such meshes are refused loudly.

Observability: one ``trace.decision("grad_sync", ...)`` per bucket per
build (``explain_last("grad_sync")`` names the chosen arm + bucket
size), pvars ``grad_bucket_count`` / ``grad_bucket_bytes`` (read-through
from :mod:`ompi_tpu.spc`), and — when the sync runs outside a jit trace
with tracing on — one measured ``grad_sync:run`` span plus synthetic
per-bucket spans (the host cannot see bucket boundaries inside the
compiled program; same idiom as ``parallel/pipeline``'s tick spans).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import trace
from ..core import var as _var
from ..jaxcompat import shard_map

GRAD_SYNC_MODES = ("perleaf", "bucketed", "unsynced")

# pvar state (read-through from spc.Counters): the most recently built
# grad-sync plan — how many bucket exchanges it issues and the total
# gradient bytes they carry
_PVARS = {"grad_bucket_count": 0, "grad_bucket_bytes": 0}
_last_plan: Optional[Tuple["BucketPlan", Tuple[str, ...]]] = None


def pvar_value(name: str) -> int:
    """MPI_T read-through accessor (spc.Counters.get/snapshot)."""
    return _PVARS[name]


# -- post-sync hooks ---------------------------------------------------------
# callables(grads) invoked after every eager grad sync, right before the
# (loss, grads) return — the piggyback point low-rate maintenance work
# rides on the sync cadence (ft/elastic's peer-shadow ring_shift refresh
# is the canonical rider).  Hooks run on the host, outside any trace; a
# raising hook is logged with attribution and dropped for the step
# rather than poisoning the training loop.

_post_sync_hooks: List[Callable] = []


def add_post_sync_hook(fn: Callable) -> Callable:
    _post_sync_hooks.append(fn)
    return fn


def remove_post_sync_hook(fn: Callable) -> None:
    try:
        _post_sync_hooks.remove(fn)
    except ValueError:
        pass


def _run_post_sync(grads) -> None:
    if not _post_sync_hooks:
        return
    from ..core.output import output
    for fn in list(_post_sync_hooks):
        try:
            fn(grads)
        except Exception as err:
            name = getattr(fn, "__qualname__",
                           getattr(fn, "__name__", repr(fn)))
            output.verbose(1, "overlap",
                           f"post-sync hook {name} raised "
                           f"{type(err).__name__}: {err}")


# -- bucket planning ---------------------------------------------------------

@dataclass(frozen=True)
class Bucket:
    indices: Tuple[int, ...]     # leaf indices into the FLATTEN order
    nbytes: int


@dataclass(frozen=True)
class BucketPlan:
    buckets: Tuple[Bucket, ...]
    total_bytes: int
    bucket_bytes: int            # the target size buckets close at
    n_leaves: int

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    @property
    def max_buckets(self) -> int:
        """The storm-collapse guarantee: ceil(total / bucket_bytes)."""
        return max(1, math.ceil(self.total_bytes / self.bucket_bytes))


def bucket_plan(leaves: Sequence, bucket_bytes: int) -> BucketPlan:
    """Group leaves (anything with .shape/.dtype, flatten order) into
    fixed-byte buckets walking the list in REVERSE — the approximate
    order the backward pass finalizes their cotangents (last layer
    first).  A bucket closes only AFTER its cumulative bytes reach the
    target, so every closed bucket carries >= bucket_bytes and the count
    is provably <= ceil(total_bytes / bucket_bytes)."""
    bucket_bytes = int(bucket_bytes)
    if bucket_bytes < 1:
        raise ValueError(f"bucket_bytes must be >= 1, got {bucket_bytes}")
    sizes = [int(np.prod(x.shape) if x.shape else 1)
             * np.dtype(x.dtype).itemsize for x in leaves]
    buckets: List[Bucket] = []
    group: List[int] = []
    acc = 0
    for i in reversed(range(len(sizes))):
        group.append(i)
        acc += sizes[i]
        if acc >= bucket_bytes:
            buckets.append(Bucket(tuple(group), acc))
            group, acc = [], 0
    if group:
        buckets.append(Bucket(tuple(group), acc))
    return BucketPlan(tuple(buckets), sum(sizes), bucket_bytes, len(sizes))


def resolve_bucket_bytes(bucket_bytes: Optional[int] = None) -> int:
    """Config override, else the coll_xla_grad_bucket_bytes var (~4 MiB)."""
    nb = int(bucket_bytes if bucket_bytes is not None
             else _var.get("coll_xla_grad_bucket_bytes", 4 << 20))
    if nb < 1:
        raise ValueError(f"grad_bucket_bytes must be >= 1, got {nb}")
    return nb


# -- decision + audit --------------------------------------------------------

def _mesh_platform(mesh: Mesh) -> str:
    return next(iter(mesh.devices.flat)).platform


def _decide_buckets(plan: BucketPlan, ndev: int, platform: str,
                    block: int, plane: Optional[str] = None,
                    hier_ok: bool = False,
                    hier_why: str = "") -> Tuple[str, ...]:
    """One decision-layer pass per bucket (coll name ``grad_sync``,
    arms native|quant|hier|hier+quant — the hier arms only when the
    sync spans a two-tier dpo×dp split) + the audit record feeding
    explain_last and the bucket pvars.  Runs at trace/build time — once
    per compiled program, which is exactly how often the arm can
    change."""
    from ..coll import xla as _xla

    rules = _xla._load_device_rules()
    arms = []
    for i, b in enumerate(plan.buckets):
        arm, reason, chain = _xla.decide_mode(
            "grad_sync", b.nbytes, ndev, platform, rules,
            allowed=("native", "quant"), quant_ok=True, dtype=np.float32,
            plane=plane, hier_ok=hier_ok, hier_why=hier_why)
        arms.append(arm)
        if trace.enabled:
            details = dict(bucket=i, n_buckets=plan.n_buckets,
                           bucket_bytes=plan.bucket_bytes,
                           leaves=len(b.indices), ndev=ndev,
                           total_bytes=plan.total_bytes, chain=list(chain))
            if arm == "quant":
                from ..coll.quant import grad_bucket_span_args
                details.update(grad_bucket_span_args(
                    b.nbytes, ndev, np.float32, block))
            trace.decision("grad_sync", arm=arm, reason=reason,
                           verdict=None, nbytes=b.nbytes, **details)
    _PVARS["grad_bucket_count"] = plan.n_buckets
    _PVARS["grad_bucket_bytes"] = plan.total_bytes
    return tuple(arms)


# -- the custom_vjp bucket tag ----------------------------------------------

def _make_bucket_tag(shapes, dtypes, arm: str, axis, n: int,
                     block: int, levels=None):
    """Identity on a tuple of leaves whose backward rule syncs the
    bucket: concatenate the cotangents into one flat f32 vector, ONE
    allreduce (native pmean, psum_quant, or the two-tier hierarchical
    form per the decided arm), split back.  The rule fires exactly when
    the backward pass has produced every cotangent in the bucket — the
    overlap point.  ``axis`` may be a tuple of mesh axis names (the
    dpo×dp sync domain); ``levels`` is ``(inner, outer, n_outer)`` for
    the hier arms."""
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)

    def sync(cts):
        parts = [jnp.reshape(c, (-1,)).astype(jnp.float32) for c in cts]
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        if arm in ("hier", "hier+quant"):
            # HAN shape over the two-tier sync domain: RS(inner ICI) →
            # allreduce(outer DCN, 1/n_inner of the bytes, quantized
            # for hier+quant) → AG(inner ICI); mean via the static n
            inner, outer, n_outer = levels
            from .hierarchy import (hierarchical_psum,
                                    hierarchical_psum_quant)
            if arm == "hier+quant":
                flat = hierarchical_psum_quant(flat, inner, outer,
                                               n_outer, block=block) / n
            else:
                flat = hierarchical_psum(flat, inner, outer) / n
        elif arm == "quant":
            from ..coll.quant import psum_quant
            flat = psum_quant(flat, axis, n, avg=True, block=block)
        else:
            flat = lax.pmean(flat, axis)
        out, off = [], 0
        for shape, size, dt in zip(shapes, sizes, dtypes):
            out.append(jnp.reshape(
                lax.dynamic_slice_in_dim(flat, off, size), shape)
                .astype(dt))
            off += size
        return tuple(out)

    @jax.custom_vjp
    def tag(group):
        return group

    def fwd(group):
        return group, None

    def bwd(_, cts):
        return (sync(cts),)

    tag.defvjp(fwd, bwd)
    return tag


def _apply_bucket_tags(leaves: list, plan: BucketPlan,
                       arms: Sequence[str], axis, n: int,
                       block: int, levels=None) -> list:
    out = list(leaves)
    for b, arm in zip(plan.buckets, arms):
        group = tuple(out[j] for j in b.indices)
        tag = _make_bucket_tag(
            tuple(tuple(x.shape) for x in group),
            tuple(jnp.result_type(x) for x in group),
            arm, axis, n, block, levels=levels)
        for j, t in zip(b.indices, tag(group)):
            out[j] = t
    return out


# -- grad-sync builders ------------------------------------------------------

def dp_sync_axes(mesh: Mesh):
    """The sync domain: ``("dpo", "dp")`` when the mesh carries an
    outer data-parallel axis (the two-tier ICI×DCN shape the hier arms
    address by level), else plain ``"dp"``."""
    return ("dpo", "dp") if "dpo" in mesh.axis_names else "dp"


def check_dp_mesh(mesh: Mesh, what: str) -> int:
    """dp-only contract shared with _quant_grad_sync: a shard_map over
    the data-parallel axes replicates every other axis, which would
    silently undo tp/sp parameter sharding — refuse instead.  An
    optional ``dpo`` outer data-parallel axis (slice-of-slices DP over
    DCN) is part of the sync domain, not a sharded dimension."""
    if "dp" not in mesh.axis_names:
        raise ValueError(
            f"{what} needs a 'dp' mesh axis to sync over "
            f"(mesh axes: {mesh.axis_names})")
    n = mesh.shape["dp"]
    for a in mesh.axis_names:
        if a == "dpo":
            n *= mesh.shape[a]
        elif a != "dp" and mesh.shape[a] > 1:
            raise ValueError(
                f"{what} is dp-only: the shard_map over dp would "
                f"replicate axis {a!r} (size {mesh.shape[a]}) and undo "
                "its parameter sharding; use grad_sync='native' on "
                "dp×tp/sp meshes")
    return n


def make_grad_sync(mode: str, mesh: Mesh, local_loss: Callable,
                   bucket_bytes: Optional[int] = None,
                   quant_block: int = 256) -> Callable:
    """Build ``(params, batch) -> (loss, grads)`` with the dp gradient
    sync carried by the requested scheduler:

      * ``perleaf``  — one native ``lax.pmean`` per leaf after the full
        backward (the explicit form of the seed's storm; the baseline
        the bucketed arm is benched and numerically pinned against).
      * ``bucketed`` — fixed-byte buckets in reverse flatten order, each
        synced by ONE allreduce the moment its cotangents exist; the
        arm per bucket (native|quant) comes from the decision layer.
      * ``unsynced`` — no gradient exchange at all (loss still pmean'd).
        MEASUREMENT-ONLY: its step time is the pure-compute floor the
        bench's overlap-efficiency column divides against; training
        with it diverges the replicas.

    ``local_loss(params, batch)`` must evaluate the PER-SHARD loss with
    no mesh inside (the one cross-shard exchange is the sync built
    here).
    """
    if mode not in GRAD_SYNC_MODES:
        raise ValueError(f"unknown grad sync mode {mode!r} "
                         f"(expected one of {GRAD_SYNC_MODES})")
    n = check_dp_mesh(mesh, f"grad_sync={mode!r}")
    platform = _mesh_platform(mesh)
    nb = resolve_bucket_bytes(bucket_bytes)
    sync_axis = dp_sync_axes(mesh)
    if isinstance(sync_axis, tuple):
        # batch dim 0 shards over the row-major dpo×dp product; the
        # two-tier context feeds the hier arms + '@<plane>' rule rows
        data_spec = P(sync_axis)
        from .hierarchy import classify_axes, hier_axes
        h_inner, h_outer, h_why = hier_axes(mesh, sync_axis)
        kinds = classify_axes(mesh)
        plane = ("dcn" if any(kinds.get(a) == "dcn" for a in sync_axis)
                 else "ici")
        levels = ((h_inner, h_outer, mesh.shape[h_outer])
                  if h_inner is not None else None)
    else:
        data_spec = P(*("dp" if a == "dp" else None
                        for a in mesh.axis_names))
        h_inner, h_why = None, "single-axis comm (no inner/outer levels)"
        plane, levels = None, None

    def local(params, batch):
        if mode == "bucketed":
            leaves, _ = jax.tree_util.tree_flatten(params)
            plan = bucket_plan(leaves, nb)
            arms = _decide_buckets(plan, n, platform, quant_block,
                                   plane=plane,
                                   hier_ok=(h_inner is not None),
                                   hier_why=h_why or "")
            global _last_plan
            _last_plan = (plan, arms)

            def tagged_loss(p, t):
                lv, td = jax.tree_util.tree_flatten(p)
                lv = _apply_bucket_tags(lv, plan, arms, sync_axis, n,
                                        quant_block, levels=levels)
                return local_loss(jax.tree_util.tree_unflatten(td, lv), t)

            loss, grads = jax.value_and_grad(tagged_loss)(params, batch)
        else:
            loss, grads = jax.value_and_grad(local_loss)(params, batch)
            if mode == "perleaf":
                grads = jax.tree.map(
                    lambda g: lax.pmean(g, sync_axis), grads)
        return lax.pmean(loss, sync_axis), grads

    inner = shard_map(local, mesh=mesh, in_specs=(P(), data_spec),
                      out_specs=(P(), P()))

    def _note_traffic(grads):
        # ring-allreduce model of the sync over the (possibly two-tier)
        # sync domain: 2(n-1)/n x grad bytes per rank (the bucketed
        # arm's quant buckets send less — the matrix keeps the
        # native-wire convention the busbw factors use).  Buckets the
        # decision layer routed to a hier arm charge the HAN stage
        # split instead: inner RS/AG rings + the outer ring on the
        # scattered 1/n_inner fraction.
        from .. import traffic
        if not traffic.enabled or mode == "unsynced" or n < 2:
            return
        tot = sum(g.nbytes for g in jax.tree_util.tree_leaves(grads))
        hier_b = 0
        if (mode == "bucketed" and _last_plan is not None
                and levels is not None):
            plan, arms = _last_plan
            hier_b = sum(b.nbytes for b, a in zip(plan.buckets, arms)
                         if a in ("hier", "hier+quant"))
            hier_b = min(hier_b, tot)
            if hier_b:
                traffic.note_hierarchical(mesh, levels[0], levels[1],
                                          hier_b)
        flat_b = tot - hier_b
        if flat_b:
            traffic.note_ring(mesh, sync_axis,
                              2 * (n - 1) * flat_b // n, "grad_sync")

    def _note_numerics(grads):
        # payload fingerprints at the grad-sync boundary: grad-norm /
        # non-finite telemetry with bucket attribution when the bucketed
        # plan is in hand (ompi_tpu/numerics).  Callers gate on
        # numerics.enabled — the disabled path stays one attribute read.
        from .. import numerics
        if mode == "unsynced":
            return
        leaves = jax.tree_util.tree_leaves(grads)
        plan, arms = ((_last_plan if mode == "bucketed" and
                       _last_plan is not None else (None, None)))
        numerics.observe_grad_sync(leaves, mode, n, plan=plan, arms=arms)

    def vg(params, batch):
        from .. import numerics
        if isinstance(batch, jax.core.Tracer):
            # under an outer jit/grad trace there is nothing to time or
            # attribute: the sync inlines into the caller's program
            return inner(params, batch)
        if not trace.enabled:
            loss, grads = inner(params, batch)
            _note_traffic(grads)
            if numerics.enabled:
                _note_numerics(grads)
            _run_post_sync(grads)
            return loss, grads
        t0 = time.perf_counter()
        try:
            loss, grads = inner(params, batch)
            jax.block_until_ready(grads)
        except BaseException:
            # a raising sync (revoked comm, watchdog timeout) still
            # closes its span, tagged error — never open-ended, never a
            # latency sample for the perf cost model
            trace.record_span(
                "grad_sync:run", "overlap", t0, time.perf_counter(),
                args={"mode": mode, "ndev": n, "status": "error"})
            raise
        t1 = time.perf_counter()
        trace.record_span(
            "grad_sync:run", "overlap", t0, t1,
            args={"mode": mode, "ndev": n,
                  "buckets": _PVARS["grad_bucket_count"]
                  if mode == "bucketed" else None,
                  "total_bytes": _PVARS["grad_bucket_bytes"]
                  if mode == "bucketed" else None})
        if mode == "bucketed" and _last_plan is not None:
            # the host cannot see bucket boundaries inside the compiled
            # program: even subdivision, marked synthetic (the
            # pipeline-tick idiom)
            plan, arms = _last_plan
            per = (t1 - t0) / max(plan.n_buckets, 1)
            for i, (b, arm) in enumerate(zip(plan.buckets, arms)):
                trace.record_span(
                    "grad_sync:bucket", "overlap-buckets",
                    t0 + i * per, t0 + (i + 1) * per,
                    args={"bucket": i, "synthetic": True, "arm": arm,
                          "nbytes": b.nbytes, "ndev": n,
                          "leaves": len(b.indices)})
        _note_traffic(grads)
        if numerics.enabled:
            _note_numerics(grads)
        _run_post_sync(grads)
        return loss, grads

    return vg


# -- collective-matmul ring arbitration --------------------------------------

def decide_collmm(kind: str, nbytes: int, mesh: Mesh, axis: str,
                  eligible_bidir: bool) -> str:
    """Ring-direction pick for one collective-matmul call site via the
    shared decision layer (coll name ``collmm``, arms native = one ring
    | bidir = two half-rings on both ICI directions).  Shapes whose
    per-rank row count is odd drop the bidir arm — the decision never
    names a schedule the op cannot execute.  One audit event per
    compiled call site feeds ``explain_last("collmm")``."""
    from ..coll import xla as _xla

    n = mesh.shape[axis]
    allowed = ("native", "bidir") if eligible_bidir else ("native",)
    arm, reason, chain = _xla.decide_mode(
        "collmm", int(nbytes), n, _mesh_platform(mesh),
        _xla._load_device_rules(), allowed, quant_ok=False)
    if trace.enabled:
        trace.decision("collmm", arm=arm, reason=reason, verdict=None,
                       nbytes=int(nbytes), ndev=n, op_kind=kind,
                       chain=list(chain))
    return arm
