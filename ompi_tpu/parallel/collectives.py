"""Device collectives: named-axis primitives + the DeviceComm engine.

This is the heart of the TPU-native design (BASELINE.json north_star): where
the reference's coll components drive host loops over p2p (§3.2) and its
coll/accelerator component stages HBM→host before reducing
(coll_accelerator_allreduce.c:31-60), here collectives on device-resident
data are XLA collective *programs* executed over ICI — ``lax.psum`` /
``all_gather`` / ``psum_scatter`` / ``all_to_all`` / ``ppermute`` inside
``shard_map`` — with an executable cache playing the role ob1's protocol
state machine plays on the host path ("the analog ... in compilation space",
SURVEY.md §7 hard parts).

Two API levels:
  * free functions (``psum``, ``all_gather_axis``, ...) usable inside any
    user shard_map/jit — the idiomatic JAX face;
  * ``DeviceComm`` — MPI-shaped collectives over one mesh axis on standalone
    arrays, caching one compiled executable per (collective, op, shape,
    dtype) bucket, for OSU-style benchmarking and the coll/xla component.

Layout convention for DeviceComm: an "MPI buffer per rank" is row i of an
array of shape (n, *elem) sharded on dim 0 over the comm axis; results keep
that layout (every row holds that rank's result), so chained collectives
stay on device with no resharding.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..op import MAX, MIN, SUM, Op

# ---------------------------------------------------------------------------
# named-axis primitives (for use inside shard_map) — thin, explicit wrappers
# ---------------------------------------------------------------------------


def psum(x, axis: str):
    return lax.psum(x, axis)


def pmax(x, axis: str):
    return lax.pmax(x, axis)


def pmin(x, axis: str):
    return lax.pmin(x, axis)


def _op_identity(op: Op, like):
    """Identity element of the named op, shaped like ``like``."""
    if op.name in ("sum", "lor", "bor", "bxor"):
        return jnp.zeros_like(like)
    if op.name in ("prod",):
        return jnp.ones_like(like)
    if op.name == "land":
        return jnp.ones_like(like, dtype=bool).astype(like.dtype)
    if op.name == "band":
        return jnp.full_like(like, ~jnp.zeros((), like.dtype)
                             if jnp.issubdtype(like.dtype, jnp.integer)
                             else 1)
    if op.name in ("max", "min"):
        if jnp.issubdtype(like.dtype, jnp.floating):
            v = -jnp.inf if op.name == "max" else jnp.inf
        elif like.dtype == jnp.bool_:
            v = op.name == "min"
        else:
            info = jnp.iinfo(like.dtype)
            v = info.min if op.name == "max" else info.max
        return jnp.full_like(like, v)
    raise ValueError(f"no identity for op {op.name}")


def preduce(x, axis: str, op: Op):
    """Reduce over a mesh axis with any Op. SUM/MAX/MIN lower to native
    psum/pmax/pmin (single ICI reduction); other ops all_gather + fold."""
    if op.name == "sum":
        return lax.psum(x, axis)
    if op.name == "max":
        return lax.pmax(x, axis)
    if op.name == "min":
        return lax.pmin(x, axis)
    gathered = lax.all_gather(x, axis)           # (n, *x.shape)
    if op.name == "prod":
        return jnp.prod(gathered, axis=0)
    if op.name in ("land", "band"):
        return jnp.all(gathered.astype(bool), axis=0).astype(x.dtype) \
            if op.name == "land" else functools.reduce(
                jnp.bitwise_and, [gathered[i] for i in range(gathered.shape[0])])
    if op.name in ("lor", "bor"):
        return jnp.any(gathered.astype(bool), axis=0).astype(x.dtype) \
            if op.name == "lor" else functools.reduce(
                jnp.bitwise_or, [gathered[i] for i in range(gathered.shape[0])])
    if op.name in ("lxor", "bxor"):
        red = functools.reduce(jnp.bitwise_xor,
                               [gathered[i].astype(jnp.int32)
                                for i in range(gathered.shape[0])])
        return red.astype(x.dtype)
    # generic fold (user op whose fn is jax-traceable)
    acc = gathered[0]
    for i in range(1, gathered.shape[0]):
        acc = op.fn(acc, gathered[i])
    return acc


def all_gather_axis(x, axis: str, tiled: bool = True):
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter_axis(x, axis: str):
    """psum_scatter over dim 0 (must be divisible by axis size)."""
    return lax.psum_scatter(x, axis, tiled=True)


def all_to_all_axis(x, axis: str, split_dim: int = 0, concat_dim: int = 0):
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def ppermute(x, axis: str, perm: Sequence[Tuple[int, int]]):
    return lax.ppermute(x, axis, perm=list(perm))


def ring_shift(x, axis: str, n: int, shift: int = 1):
    """Neighbor exchange on a ring — the schedule ring attention and the
    ring/segmented-ring collectives share (coll_base_allreduce.c:344,621)."""
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm=perm)


def pbcast(x, axis: str, root: int = 0):
    """Broadcast root's shard to every member of the axis."""
    return lax.all_gather(x, axis)[root]


# ---------------------------------------------------------------------------
# DeviceComm: MPI-shaped device collectives with an executable cache
# ---------------------------------------------------------------------------


class DeviceComm:
    """Collectives over one axis of a mesh, single-controller.

    ``n`` "ranks" = positions along `axis`. Input arrays use the canonical
    (n, *elem) dim-0-sharded layout (see module docstring); `from_ranks`/
    `to_ranks` convert to/from per-rank host arrays.
    """

    def __init__(self, mesh: Mesh, axis: str) -> None:
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self._cache: Dict[tuple, Callable] = {}
        self._spec = P(axis)
        self.spc = None          # optional SPC counters

    # -- layout helpers -----------------------------------------------------

    def sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self._spec)

    def from_ranks(self, arrays: Sequence[np.ndarray]) -> jax.Array:
        """Stack per-rank buffers into the canonical device layout."""
        stacked = jnp.stack([jnp.asarray(a) for a in arrays])
        return jax.device_put(stacked, self.sharding())

    def to_ranks(self, x: jax.Array) -> list:
        host = np.asarray(jax.device_get(x))
        return [host[i] for i in range(host.shape[0])]

    # -- multi-process (rank-per-chip) layout helpers -----------------------
    # In the device-plane model (parallel/device_plane.py) each process owns
    # only its own rows; the global array is assembled from per-process
    # shards — the multi-process analog of from_ranks/to_ranks.

    def from_local(self, local_rows: np.ndarray) -> jax.Array:
        """This process's rows (r, *e) → the global (R, *e) sharded array."""
        return jax.make_array_from_process_local_data(
            self.sharding(), np.asarray(local_rows))

    def to_local(self, x: jax.Array) -> np.ndarray:
        """This process's rows of a global array, as one host ndarray.
        Deduplicates replicated shards (meshes with extra axes hold one
        copy per replica device)."""
        by_start = {}
        for s in x.addressable_shards:
            by_start.setdefault(s.index[0].start or 0, s)
        return np.concatenate(
            [np.asarray(by_start[k].data) for k in sorted(by_start)], axis=0)

    # -- compiled-collective cache (≙ the coll/xla executable cache,
    #    SURVEY.md §7 "ICI collectives outside a single XLA program") -------

    def _compiled(self, key: tuple, build: Callable) -> Callable:
        fn = self._cache.get(key)
        if fn is None:
            fn = build()
            self._cache[key] = fn
            if self.spc is not None:
                self.spc.inc("device_cache_misses")
        if self.spc is not None:
            self.spc.inc("device_collectives")
        return fn

    def _shard_map(self, fn, in_specs, out_specs):
        return jax.jit(jax.shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                     out_specs=out_specs))

    def cache_info(self) -> Dict[str, int]:
        return {"entries": len(self._cache)}

    # -- collectives --------------------------------------------------------
    #
    # Rows ("MPI ranks") may outnumber mesh positions: with R total rows on
    # an n-device axis each device owns r = R/n local rows (rank-per-chip is
    # r=1; the single-chip bench runs all R rows on one device). Every
    # collective below handles both regimes: local fold/slice over the r
    # rows, ICI collective across devices.

    def _fold_local(self, xs, op: Op):
        """op-reduce the local rows (r, *e) → (*e)."""
        if op.name == "sum":
            return jnp.sum(xs, axis=0)
        if op.name == "max":
            return jnp.max(xs, axis=0)
        if op.name == "min":
            return jnp.min(xs, axis=0)
        if op.name == "prod":
            return jnp.prod(xs, axis=0)
        acc = xs[0]
        for i in range(1, xs.shape[0]):
            acc = op.fn(acc, xs[i])
        return acc

    def allreduce(self, x: jax.Array, op: Op = SUM) -> jax.Array:
        """Every rank's row ← op over all rows. (R,*e) → (R,*e)."""
        key = ("allreduce", op.name, x.shape, str(x.dtype))

        def build():
            def inner(xs):           # xs: (r, *e) local shard
                red = preduce(self._fold_local(xs, op), self.axis, op)
                return jnp.broadcast_to(red[None], xs.shape)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def reduce(self, x: jax.Array, op: Op = SUM, root: int = 0) -> jax.Array:
        """MPI semantics only promise the root's row; this returns the
        reduction in every row (same executable as allreduce — on ICI the
        broadcast halves are fused anyway)."""
        return self.allreduce(x, op)

    def bcast(self, x: jax.Array, root: int = 0) -> jax.Array:
        """One-to-all as a masked psum: the root's device contributes its
        row, everyone else zeros — traffic is one element-size reduction
        over ICI instead of the R× blowup of all_gather-then-index (the
        round-1 implementation; VERDICT r1 weak#7)."""
        R = x.shape[0]
        r = R // self.n
        key = ("bcast", int(root), x.shape, str(x.dtype))

        def build():
            root_dev, root_local = divmod(int(root), r)

            def inner(xs):           # (r, *e)
                i = lax.axis_index(self.axis)
                contrib = jnp.where(i == root_dev, xs[root_local],
                                    jnp.zeros_like(xs[root_local]))
                row = lax.psum(contrib, self.axis)
                return jnp.broadcast_to(row[None], xs.shape)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def allgather(self, x: jax.Array) -> jax.Array:
        """(R, b, *e) → (R, R*b, *e): every row = concat of all rows."""
        key = ("allgather", x.shape, str(x.dtype))

        def build():
            def inner(xs):           # (r, b, *e)
                full = lax.all_gather(xs, self.axis, axis=0, tiled=True)
                flat = full.reshape((-1,) + full.shape[2:])   # (R*b, *e)
                return jnp.broadcast_to(flat[None],
                                        (xs.shape[0],) + flat.shape)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def reduce_scatter(self, x: jax.Array, op: Op = SUM) -> jax.Array:
        """(R, R*b, *e) → (R, b, *e): row i = op-reduced i-th block."""
        R = x.shape[0]
        b = x.shape[1] // R
        r = R // self.n
        key = ("reduce_scatter", op.name, x.shape, str(x.dtype))

        def build():
            def inner(xs):           # (r, R*b, *e)
                folded = self._fold_local(xs, op)          # (R*b, *e)
                if op.name == "sum":
                    mine = lax.psum_scatter(folded, self.axis,
                                            scatter_dimension=0, tiled=True)
                else:
                    red = preduce(folded, self.axis, op)   # (R*b, *e)
                    i = lax.axis_index(self.axis)
                    mine = lax.dynamic_slice_in_dim(red, i * r * b, r * b, 0)
                return mine.reshape((r, b) + xs.shape[2:])
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def alltoall(self, x: jax.Array) -> jax.Array:
        """(R, R, b, *e) → (R, R, b, *e): out[i, j] = in[j, i]."""
        R = x.shape[0]
        r = R // self.n
        key = ("alltoall", x.shape, str(x.dtype))

        def build():
            if r == 1:
                def inner(xs):       # (1, R, b, *e): native ICI all-to-all
                    return lax.all_to_all(xs, self.axis, split_axis=1,
                                          concat_axis=1, tiled=True)
            else:
                def inner(xs):       # (r, R, b, *e): native all-to-all of
                    # r-row column blocks — each device exchanges only the
                    # blocks destined for each peer (n× less traffic than
                    # the old full all_gather; VERDICT r1 weak#7).
                    # received block from device k = in[k's rows, my cols]
                    mixed = lax.all_to_all(xs, self.axis, split_axis=1,
                                           concat_axis=0, tiled=True)
                    return jnp.swapaxes(mixed, 0, 1)   # (r, R, b, *e)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def ring_shift(self, x: jax.Array, shift: int = 1) -> jax.Array:
        """(R,*e) → (R,*e) with row i moved to row (i+shift)%R — the ppermute
        ring primitive (context-parallel neighbor exchange)."""
        R = x.shape[0]
        r = R // self.n
        key = ("ring", int(shift), x.shape, str(x.dtype))

        def build():
            if r == 1:
                def inner(xs):
                    return ring_shift(xs, self.axis, self.n, shift)
            else:
                # global row shift = at most two neighbor ppermutes: the
                # source rows of any device's block span exactly two peers
                # (offset is the same on every device, so both permutations
                # are static ring shifts) — O(row) traffic instead of the
                # old full all_gather (VERDICT r1 weak#7)
                s = shift % R
                off = (-s) % r                 # intra-block source offset
                q = (-s - off) // r            # uniform source-device delta
                n = self.n

                def inner(xs):                 # (r, *e)
                    a = lax.ppermute(
                        xs[off:], self.axis,
                        [((d + q) % n, d) for d in range(n)])
                    if off == 0:
                        return a
                    b = lax.ppermute(
                        xs[:off], self.axis,
                        [((d + q + 1) % n, d) for d in range(n)])
                    return jnp.concatenate([a, b], axis=0)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def scan(self, x: jax.Array, op: Op = SUM, exclusive: bool = False
             ) -> jax.Array:
        """Prefix reduction across ranks: row i ← op(rows 0..i)."""
        R = x.shape[0]
        r = R // self.n
        key = ("scan", op.name, bool(exclusive), x.shape, str(x.dtype))

        cum_local = {"sum": lax.cumsum, "max": lax.cummax,
                     "min": lax.cummin, "prod": lax.cumprod}.get(op.name)

        def build():
            if cum_local is not None:
                def inner(xs):       # (r, *e)
                    # local prefix + tiny exchange: only the per-DEVICE
                    # totals cross ICI (n rows, not R — the bandwidth shape
                    # VERDICT r1 weak#7 asked for), then each device offsets
                    # its local prefix by the scan of lower devices' totals
                    loc = cum_local(xs, axis=0)            # (r, *e)
                    totals = lax.all_gather(loc[-1], self.axis)  # (n, *e)
                    csum = cum_local(totals, axis=0)       # inclusive
                    i = lax.axis_index(self.axis)
                    base_idx = jnp.maximum(i - 1, 0)
                    base = jnp.where(i > 0, csum[base_idx],
                                     _op_identity(op, totals[0]))
                    out = op.fn(jnp.broadcast_to(base[None], loc.shape), loc)
                    if exclusive:
                        prev = jnp.concatenate(
                            [jnp.broadcast_to(base[None], loc[:1].shape),
                             out[:-1]], axis=0)
                        return prev
                    return out
            else:
                def inner(xs):       # general op: gather + associative scan
                    full = lax.all_gather(xs, self.axis, axis=0, tiled=True)
                    csum = lax.associative_scan(
                        lambda a, b: op.fn(a, b), full, axis=0)
                    if exclusive:
                        try:
                            z = _op_identity(op, csum[:1])
                        except ValueError:
                            # user op without a registered identity: MPI
                            # leaves exclusive row 0 undefined; zeros keep
                            # the historical behavior
                            z = jnp.zeros_like(csum[:1])
                        csum = jnp.concatenate([z, csum[:-1]], axis=0)
                    i = lax.axis_index(self.axis)
                    return lax.dynamic_slice_in_dim(csum, i * r, r, 0)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def barrier(self) -> None:
        """A real cross-device sync: tiny psum + block."""
        key = ("barrier",)

        def build():
            def inner(xs):
                return lax.psum(xs, self.axis)
            return self._shard_map(inner, P(self.axis), P())

        # from_local works in both the single-controller and multi-process
        # (rank-per-chip) regimes — device_put would reject the
        # non-addressable devices of other processes
        pid = jax.process_index()
        n_local = sum(1 for d in self.mesh.devices.flat
                      if d.process_index == pid)
        token = self.from_local(np.zeros((n_local,), np.int32))
        self._compiled(key, build)(token).block_until_ready()
