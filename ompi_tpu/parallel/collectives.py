"""Device collectives: named-axis primitives + the DeviceComm engine.

This is the heart of the TPU-native design (BASELINE.json north_star): where
the reference's coll components drive host loops over p2p (§3.2) and its
coll/accelerator component stages HBM→host before reducing
(coll_accelerator_allreduce.c:31-60), here collectives on device-resident
data are XLA collective *programs* executed over ICI — ``lax.psum`` /
``all_gather`` / ``psum_scatter`` / ``all_to_all`` / ``ppermute`` inside
``shard_map`` — with an executable cache playing the role ob1's protocol
state machine plays on the host path ("the analog ... in compilation space",
SURVEY.md §7 hard parts).

Two API levels:
  * free functions (``psum``, ``all_gather_axis``, ...) usable inside any
    user shard_map/jit — the idiomatic JAX face;
  * ``DeviceComm`` — MPI-shaped collectives over one mesh axis on standalone
    arrays, caching one compiled executable per (collective, op, shape,
    dtype) bucket, for OSU-style benchmarking and the coll/xla component.

Layout convention for DeviceComm: an "MPI buffer per rank" is row i of an
array of shape (n, *elem) sharded on dim 0 over the comm axis; results keep
that layout (every row holds that rank's result), so chained collectives
stay on device with no resharding.
"""

from __future__ import annotations

import collections
import functools
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import jaxcompat as _compat, trace
from ..core import var as _var
from ..op import MAX, MIN, SUM, Op

_var.register(
    "coll", "a2av", "slice_cap", 0, type=int, level=4,
    help="Capacity-slice size (elements) for the sliced-scan ragged "
         "alltoallv_from_rows exchange; bounds the per-step transient to "
         "O(R x slice_cap x elem) per device. 0 = auto (~1M elements per "
         "device row). The chosen value and the resulting scan-step count "
         "k are recorded in the decision audit of every collective that "
         "rides this path (alltoallv, moe_dispatch, moe_combine).")

# ---------------------------------------------------------------------------
# named-axis primitives (for use inside shard_map) — thin, explicit wrappers
# ---------------------------------------------------------------------------


def psum(x, axis: str):
    return lax.psum(x, axis)


def pmax(x, axis: str):
    return lax.pmax(x, axis)


def pmin(x, axis: str):
    return lax.pmin(x, axis)


def _op_identity(op: Op, like):
    """Identity element of the named op, shaped like ``like``."""
    if op.name in ("sum", "lor", "bor", "bxor"):
        return jnp.zeros_like(like)
    if op.name in ("prod",):
        return jnp.ones_like(like)
    if op.name == "land":
        return jnp.ones_like(like, dtype=bool).astype(like.dtype)
    if op.name == "band":
        return jnp.full_like(like, ~jnp.zeros((), like.dtype)
                             if jnp.issubdtype(like.dtype, jnp.integer)
                             else 1)
    if op.name in ("max", "min"):
        if jnp.issubdtype(like.dtype, jnp.floating):
            v = -jnp.inf if op.name == "max" else jnp.inf
        elif like.dtype == jnp.bool_:
            v = op.name == "min"
        else:
            info = jnp.iinfo(like.dtype)
            v = info.min if op.name == "max" else info.max
        return jnp.full_like(like, v)
    raise ValueError(f"no identity for op {op.name}")


def preduce(x, axis: str, op: Op):
    """Reduce over a mesh axis with any Op. SUM/MAX/MIN lower to native
    psum/pmax/pmin (single ICI reduction); other ops all_gather + fold."""
    if op.name == "sum":
        return lax.psum(x, axis)
    if op.name == "max":
        return lax.pmax(x, axis)
    if op.name == "min":
        return lax.pmin(x, axis)
    gathered = lax.all_gather(x, axis)           # (n, *x.shape)
    if op.name == "prod":
        return jnp.prod(gathered, axis=0)
    if op.name in ("land", "band"):
        return jnp.all(gathered.astype(bool), axis=0).astype(x.dtype) \
            if op.name == "land" else functools.reduce(
                jnp.bitwise_and, [gathered[i] for i in range(gathered.shape[0])])
    if op.name in ("lor", "bor"):
        return jnp.any(gathered.astype(bool), axis=0).astype(x.dtype) \
            if op.name == "lor" else functools.reduce(
                jnp.bitwise_or, [gathered[i] for i in range(gathered.shape[0])])
    if op.name in ("lxor", "bxor"):
        red = functools.reduce(jnp.bitwise_xor,
                               [gathered[i].astype(jnp.int32)
                                for i in range(gathered.shape[0])])
        return red.astype(x.dtype)
    # generic fold (user op whose fn is jax-traceable)
    acc = gathered[0]
    for i in range(1, gathered.shape[0]):
        acc = op.fn(acc, gathered[i])
    return acc


def all_gather_axis(x, axis: str, tiled: bool = True):
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter_axis(x, axis: str):
    """psum_scatter over dim 0 (must be divisible by axis size)."""
    return lax.psum_scatter(x, axis, tiled=True)


def all_to_all_axis(x, axis: str, split_dim: int = 0, concat_dim: int = 0):
    """Tiled all_to_all over a named axis (or tuple of axes): the local
    ``split_dim`` is scattered across the axis while each peer's block
    concatenates along ``concat_dim``.

    A ``split_dim`` that does not divide by the axis size is handled
    exactly with the zero-pad trick hierarchical_psum uses: the dim is
    padded to the next multiple of the axis size, so every peer receives
    an equal ceil-sized block.  The result follows the padded-block
    convention — position p along the axis holds rows
    ``[p*ceil, (p+1)*ceil)`` of the true extent, zeros past the end — so
    the inverse (``all_gather`` on the same dim + a ``[:L]`` slice)
    reconstructs the original bit-exactly.  Reshard plans lean on this
    to keep ragged exchanges on device instead of bouncing through host.
    """
    n = int(lax.psum(1, axis))     # static axis size under shard_map
    L = x.shape[split_dim]
    if L % n:
        pad = [(0, 0)] * x.ndim
        pad[split_dim] = (0, -(-L // n) * n - L)
        x = jnp.pad(x, pad)
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def ppermute(x, axis: str, perm: Sequence[Tuple[int, int]]):
    return lax.ppermute(x, axis, perm=list(perm))


def ring_shift(x, axis: str, n: int, shift: int = 1, steps: int = 1):
    """Neighbor exchange on a ring — the schedule ring attention and the
    ring/segmented-ring collectives share (coll_base_allreduce.c:344,621).

    ``steps > 1`` is the strided variant: the rotation decomposes into
    ``steps`` sequential hops of stride ``shift/steps`` (which must
    divide), the segmented-ring shape that bounds per-hop link pressure
    and gives the overlap tier ``steps`` interleaving points instead of
    one monolithic permute."""
    steps = int(steps)
    if steps <= 1:
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(x, axis, perm=perm)
    if shift % steps:
        raise ValueError(
            f"ring_shift: shift {shift} does not decompose into "
            f"{steps} equal strides (shift % steps must be 0)")
    stride = shift // steps
    perm = [(i, (i + stride) % n) for i in range(n)]
    for _ in range(steps):
        x = lax.ppermute(x, axis, perm=perm)
    return x


def pbcast(x, axis: str, root: int = 0):
    """Broadcast root's shard to every member of the axis."""
    return lax.all_gather(x, axis)[root]


# ---------------------------------------------------------------------------
# DeviceComm: MPI-shaped device collectives with an executable cache
# ---------------------------------------------------------------------------


class DeviceComm:
    """Collectives over one axis of a mesh, single-controller.

    ``n`` "ranks" = positions along `axis`. Input arrays use the canonical
    (n, *elem) dim-0-sharded layout (see module docstring); `from_ranks`/
    `to_ranks` convert to/from per-rank host arrays.

    ``axis`` may also be a TUPLE of axis names: the comm then spans the
    row-major product of those axes (outer-to-inner order), which is how
    a two-tier ICI×DCN comm presents one flat rank space while the
    hierarchical (`hier`) arm in coll/xla still addresses the individual
    levels by name.  Every flat collective here passes the tuple straight
    into the lax primitive (tuple axis names are first-class in jax);
    the cartesian/ring helpers, which need a single line geometry, keep
    requiring a single named axis.
    """

    def __init__(self, mesh: Mesh, axis) -> None:
        self.mesh = mesh
        if isinstance(axis, (tuple, list)):
            axis = tuple(axis)
            self.n = int(np.prod([mesh.shape[a] for a in axis]))
        else:
            self.n = mesh.shape[axis]
        self.axis = axis
        self._cache: Dict[tuple, Callable] = {}
        # counts → device gather maps, LRU-bounded: repeated patterns (the
        # bench, fixed decompositions) hit; per-step MoE routings churn
        # through without accumulating dead HBM buffers
        self._idx_cache: "collections.OrderedDict[tuple, Any]" = \
            collections.OrderedDict()
        self._idx_cache_cap = 64
        self._spec = P(axis)
        self.spc = None          # optional SPC counters
        self._quant = None       # lazy QuantDeviceComm (coll/quant)
        self._last_a2av = None   # last a2av_plan taken (audit breadcrumb)

    def _idx_cached(self, key: tuple, build: Callable) -> Any:
        hit = self._idx_cache.get(key)
        if hit is not None:
            self._idx_cache.move_to_end(key)
            return hit
        val = build()
        self._idx_cache[key] = val
        if len(self._idx_cache) > self._idx_cache_cap:
            self._idx_cache.popitem(last=False)
        return val

    # -- layout helpers -----------------------------------------------------

    def sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self._spec)

    def from_ranks(self, arrays: Sequence[np.ndarray]) -> jax.Array:
        """Stack per-rank buffers into the canonical device layout."""
        stacked = jnp.stack([jnp.asarray(a) for a in arrays])
        return jax.device_put(stacked, self.sharding())

    def to_ranks(self, x: jax.Array) -> list:
        host = np.asarray(jax.device_get(x))
        return [host[i] for i in range(host.shape[0])]

    def reshard(self, x: jax.Array, dst) -> jax.Array:
        """Device-native relayout of ``x`` onto ``dst`` (a NamedSharding
        or PartitionSpec over this comm's mesh) through the compiled
        minimal-collective plan engine (parallel/reshard) — the
        replacement for ``to_ranks()``/``from_ranks()`` round-trips:
        no host copy, peak live bytes bounded by ``reshard_peak_factor
        × max(src_shard, dst_shard)``, every plan step decision-audited
        and traffic-attributed under coll name ``reshard``."""
        from .reshard import reshard as _reshard
        return _reshard(x, dst, mesh=self.mesh, spc=self.spc)

    def canonicalize(self, x: jax.Array, dim: int) -> jax.Array:
        """Re-layout an array sharded over this comm's axis on dimension
        ``dim`` into the canonical ``(n, *local)`` dim-0 layout.  A pure
        local restack — ZERO wire: each rank lifts its own shard under a
        new leading rank dimension — so a consumer (the serving engine's
        weight-stationary decode pieces) can feed column-parallel shards
        straight into dim-0-batched compute without GSPMD guessing."""
        if not 0 <= dim < x.ndim:
            raise ValueError(f"canonicalize: dim {dim} out of range for "
                             f"rank-{x.ndim} array")
        if x.shape[dim] % self.n:
            raise ValueError(
                f"canonicalize: dim {dim} ({x.shape[dim]}) is not "
                f"divisible by the {self.n}-way comm axis")
        in_spec = P(*(self.axis if d == dim else None
                      for d in range(x.ndim)))
        key = ("canonicalize", dim, tuple(x.shape), str(x.dtype))

        def build():
            return self._shard_map(lambda a: a[None], (in_spec,),
                                   P(self.axis))
        return self._compiled(key, build)(x)

    # -- multi-process (rank-per-chip) layout helpers -----------------------
    # In the device-plane model (parallel/device_plane.py) each process owns
    # only its own rows; the global array is assembled from per-process
    # shards — the multi-process analog of from_ranks/to_ranks.

    def from_local(self, local_rows: np.ndarray) -> jax.Array:
        """This process's rows (r, *e) → the global (R, *e) sharded array."""
        return jax.make_array_from_process_local_data(
            self.sharding(), np.asarray(local_rows))

    def to_local(self, x: jax.Array) -> np.ndarray:
        """This process's rows of a global array, as one host ndarray.
        Deduplicates replicated shards (meshes with extra axes hold one
        copy per replica device)."""
        by_start = {}
        for s in x.addressable_shards:
            by_start.setdefault(s.index[0].start or 0, s)
        return np.concatenate(
            [np.asarray(by_start[k].data) for k in sorted(by_start)], axis=0)

    # -- compiled-collective cache (≙ the coll/xla executable cache,
    #    SURVEY.md §7 "ICI collectives outside a single XLA program") -------

    def _compiled(self, key: tuple, build: Callable) -> Callable:
        fn = self._cache.get(key)
        if fn is None:
            if trace.enabled:
                # build() constructs + jits the program; XLA compiles
                # lazily, so the first-execution compile lands inside
                # whatever execution span surrounds the miss
                t0 = time.perf_counter()
                try:
                    fn = build()
                except BaseException:
                    trace.record_span(f"build:{key[0]}", "compile", t0,
                                      time.perf_counter(),
                                      args={"key": repr(key),
                                            "status": "error"})
                    raise
                trace.record_span(f"build:{key[0]}", "compile", t0,
                                  time.perf_counter(),
                                  args={"key": repr(key)})
            else:
                fn = build()
            self._cache[key] = fn
            if self.spc is not None:
                self.spc.inc("device_cache_misses")
                self.spc.inc("cache_miss_count")
        elif trace.enabled:
            trace.instant(f"cache_hit:{key[0]}", "cache",
                          args={"key": repr(key)})
        if self.spc is not None:
            self.spc.inc("device_collectives")
        return fn

    def _shard_map(self, fn, in_specs, out_specs):
        return jax.jit(_compat.shard_map(fn, mesh=self.mesh,
                                         in_specs=in_specs,
                                         out_specs=out_specs))

    def cache_info(self) -> Dict[str, int]:
        return {"entries": len(self._cache)}

    @property
    def quant(self):
        """Block-quantized tier over the same axis/cache (coll/quant)."""
        if self._quant is None:
            from ..coll.quant import QuantDeviceComm
            self._quant = QuantDeviceComm(self)
        return self._quant

    # -- collectives --------------------------------------------------------
    #
    # Rows ("MPI ranks") may outnumber mesh positions: with R total rows on
    # an n-device axis each device owns r = R/n local rows (rank-per-chip is
    # r=1; the single-chip bench runs all R rows on one device). Every
    # collective below handles both regimes: local fold/slice over the r
    # rows, ICI collective across devices.

    def _fold_local(self, xs, op: Op):
        """op-reduce the local rows (r, *e) → (*e)."""
        if op.name == "sum":
            return jnp.sum(xs, axis=0)
        if op.name == "max":
            return jnp.max(xs, axis=0)
        if op.name == "min":
            return jnp.min(xs, axis=0)
        if op.name == "prod":
            return jnp.prod(xs, axis=0)
        acc = xs[0]
        for i in range(1, xs.shape[0]):
            acc = op.fn(acc, xs[i])
        return acc

    def allreduce(self, x: jax.Array, op: Op = SUM) -> jax.Array:
        """Every rank's row ← op over all rows. (R,*e) → (R,*e)."""
        key = ("allreduce", op.name, x.shape, str(x.dtype))

        def build():
            def inner(xs):           # xs: (r, *e) local shard
                red = preduce(self._fold_local(xs, op), self.axis, op)
                return jnp.broadcast_to(red[None], xs.shape)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def reduce(self, x: jax.Array, op: Op = SUM, root: int = 0) -> jax.Array:
        """MPI semantics only promise the root's row; this returns the
        reduction in every row (same executable as allreduce — on ICI the
        broadcast halves are fused anyway)."""
        return self.allreduce(x, op)

    def bcast(self, x: jax.Array, root: int = 0) -> jax.Array:
        """One-to-all as a masked psum: the root's device contributes its
        row, everyone else zeros — traffic is one element-size reduction
        over ICI instead of the R× blowup of all_gather-then-index (the
        round-1 implementation; VERDICT r1 weak#7)."""
        R = x.shape[0]
        r = R // self.n
        key = ("bcast", int(root), x.shape, str(x.dtype))

        def build():
            root_dev, root_local = divmod(int(root), r)

            def inner(xs):           # (r, *e)
                i = lax.axis_index(self.axis)
                contrib = jnp.where(i == root_dev, xs[root_local],
                                    jnp.zeros_like(xs[root_local]))
                row = lax.psum(contrib, self.axis)
                return jnp.broadcast_to(row[None], xs.shape)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def allgather(self, x: jax.Array) -> jax.Array:
        """(R, b, *e) → (R, R*b, *e): every row = concat of all rows.

        The canonical MPI layout: every RANK row holds the full gathered
        vector. When ranks outnumber devices (r = R/n > 1) each device
        writes r identical copies — use :meth:`allgather_dedup` where the
        consumer can share one copy per device (the single-chip regime's
        r× HBM saving; round-4 verdict weak#4)."""
        key = ("allgather", x.shape, str(x.dtype))

        def build():
            def inner(xs):           # (r, b, *e)
                full = lax.all_gather(xs, self.axis, axis=0, tiled=True)
                flat = full.reshape((-1,) + full.shape[2:])   # (R*b, *e)
                return jnp.broadcast_to(flat[None],
                                        (xs.shape[0],) + flat.shape)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def allgather_dedup(self, x: jax.Array) -> jax.Array:
        """(R, b, *e) → (n, R*b, *e): ONE gathered copy per DEVICE.

        Same information as :meth:`allgather` — dim 0 is mesh position,
        not rank; the r ranks co-resident on a device share its row (the
        reference's ring allgather memory discipline,
        coll_base_allgather.c:330: each process stores the result once).
        Identical to the canonical layout when r == 1; r× less HBM
        traffic when ranks share a device (single-chip: R× less)."""
        key = ("allgather_dedup", x.shape, str(x.dtype))

        def build():
            def inner(xs):           # (r, b, *e)
                full = lax.all_gather(xs, self.axis, axis=0, tiled=True)
                return full.reshape((1, -1) + full.shape[2:])  # (1,R*b,*e)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def dedup_to_ranks(self, x: jax.Array, ranks: int) -> list:
        """Per-rank host views of an ``allgather_dedup`` result: with
        r = ranks/n ranks per device, rank i reads its device's single
        copy, row i // r (no second materialization — numpy views)."""
        host = np.asarray(jax.device_get(x))
        n = host.shape[0]
        if n == 0 or ranks % n:
            raise ValueError(
                f"ranks ({ranks}) must be a positive multiple of the "
                f"result's device rows ({n})")
        r = ranks // n
        return [host[i // r] for i in range(ranks)]

    def reduce_scatter(self, x: jax.Array, op: Op = SUM) -> jax.Array:
        """(R, R*b, *e) → (R, b, *e): row i = op-reduced i-th block."""
        R = x.shape[0]
        b = x.shape[1] // R
        r = R // self.n
        key = ("reduce_scatter", op.name, x.shape, str(x.dtype))

        def build():
            def inner(xs):           # (r, R*b, *e)
                folded = self._fold_local(xs, op)          # (R*b, *e)
                if op.name == "sum":
                    mine = lax.psum_scatter(folded, self.axis,
                                            scatter_dimension=0, tiled=True)
                else:
                    red = preduce(folded, self.axis, op)   # (R*b, *e)
                    i = lax.axis_index(self.axis)
                    mine = lax.dynamic_slice_in_dim(red, i * r * b, r * b, 0)
                return mine.reshape((r, b) + xs.shape[2:])
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def alltoall(self, x: jax.Array) -> jax.Array:
        """(R, R, b, *e) → (R, R, b, *e): out[i, j] = in[j, i]."""
        R = x.shape[0]
        r = R // self.n
        key = ("alltoall", x.shape, str(x.dtype))

        def build():
            if r == 1:
                def inner(xs):       # (1, R, b, *e): native ICI all-to-all
                    return lax.all_to_all(xs, self.axis, split_axis=1,
                                          concat_axis=1, tiled=True)
            else:
                def inner(xs):       # (r, R, b, *e): native all-to-all of
                    # r-row column blocks — each device exchanges only the
                    # blocks destined for each peer (n× less traffic than
                    # the old full all_gather; VERDICT r1 weak#7).
                    # received block from device k = in[k's rows, my cols]
                    mixed = lax.all_to_all(xs, self.axis, split_axis=1,
                                           concat_axis=0, tiled=True)
                    return jnp.swapaxes(mixed, 0, 1)   # (r, R, b, *e)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def ring_shift(self, x: jax.Array, shift: int = 1,
                   steps: int = 1) -> jax.Array:
        """(R,*e) → (R,*e) with row i moved to row (i+shift)%R — the ppermute
        ring primitive (context-parallel neighbor exchange).

        ``steps > 1`` runs the strided decomposition: ``steps``
        sequential hops of stride ``shift/steps`` (must divide), each a
        cached one-hop executable with its own traffic attribution — the
        segmented-ring schedule whose intermediate rows an overlap tier
        can consume between hops."""
        if int(steps) > 1:
            if shift % int(steps):
                raise ValueError(
                    f"ring_shift: shift {shift} does not decompose into "
                    f"{steps} equal strides (shift % steps must be 0)")
            stride = shift // int(steps)
            for _ in range(int(steps)):
                x = self.ring_shift(x, stride)
            return x
        R = x.shape[0]
        r = R // self.n
        key = ("ring", int(shift), x.shape, str(x.dtype))

        def build():
            if r == 1:
                def inner(xs):
                    return ring_shift(xs, self.axis, self.n, shift)
            else:
                # global row shift = at most two neighbor ppermutes: the
                # source rows of any device's block span exactly two peers
                # (offset is the same on every device, so both permutations
                # are static ring shifts) — O(row) traffic instead of the
                # old full all_gather (VERDICT r1 weak#7)
                s = shift % R
                off = (-s) % r                 # intra-block source offset
                q = (-s - off) // r            # uniform source-device delta
                n = self.n

                def inner(xs):                 # (r, *e)
                    a = lax.ppermute(
                        xs[off:], self.axis,
                        [((d + q) % n, d) for d in range(n)])
                    if off == 0:
                        return a
                    b = lax.ppermute(
                        xs[:off], self.axis,
                        [((d + q + 1) % n, d) for d in range(n)])
                    return jnp.concatenate([a, b], axis=0)
            return self._shard_map(inner, self._spec, self._spec)

        from .. import traffic
        if traffic.enabled and not isinstance(x, jax.core.Tracer):
            # charge the same static perms `build` lowers to; per-rank
            # bytes, and note_ppermute banks the matching coll_wire_bytes
            row = x.nbytes // max(R, 1)
            if r == 1:
                traffic.note_ppermute(
                    self.mesh, self.axis,
                    [(i, (i + shift) % self.n) for i in range(self.n)],
                    row, spc=self.spc, coll="ring_shift")
            else:
                s = shift % R
                off = (-s) % r
                q = (-s - off) // r
                n = self.n
                traffic.note_ppermute(
                    self.mesh, self.axis,
                    [((d + q) % n, d) for d in range(n)],
                    (r - off) * row, spc=self.spc, coll="ring_shift")
                if off:
                    traffic.note_ppermute(
                        self.mesh, self.axis,
                        [((d + q + 1) % n, d) for d in range(n)],
                        off * row, spc=self.spc, coll="ring_shift")
        return self._compiled(key, build)(x)

    # -- cartesian neighborhood exchange (halo / stencil) -------------------
    #
    # ≙ the neighborhood collectives (coll_basic_neighbor_*.c) specialized
    # to PERIODIC cartesian topologies — the torus halo exchange stencil
    # codes live on (BASELINE.json configs[4], HPCG/miniFE). On a periodic
    # cart every neighbor slot (dim d, direction ±1) is ONE static ring
    # permutation of the whole rank set, so the exchange compiles to
    # 2·ndims ppermutes — no per-rank send/recv loops. Non-periodic carts
    # have ragged boundary neighborhoods; those stay on the host path.

    def _cart_perms(self, topo) -> list:
        """[(dim, dir, [(src, dst), ...])] in the standard's slot order
        (per dim: -1 then +1). Requires a fully periodic cart of exactly
        R ranks."""
        R = self.mesh.shape[self.axis]
        rows = R  # perms act on mesh positions; rows==R enforced by caller
        perms = []
        for dim in range(len(topo.dims)):
            for disp in (-1, 1):
                pairs = []
                for i in range(rows):
                    c = topo.coords(i)
                    c[dim] += disp           # periodic wrap in rank_of
                    # value FROM the disp-neighbor lands AT i
                    pairs.append((topo.rank_of(c), i))
                perms.append((dim, disp, pairs))
        return perms

    def _check_cart(self, x, topo) -> None:
        if not all(topo.periods):
            raise ValueError("device cart exchange requires a fully "
                             "periodic topology (host path otherwise)")
        if topo.size != x.shape[0] or x.shape[0] != self.n:
            raise ValueError(
                f"cart size {topo.size} / rows {x.shape[0]} / mesh "
                f"{self.n} disagree (rank-per-position layout required)")

    def neighbor_allgather_cart(self, x: jax.Array, topo) -> jax.Array:
        """(R, b, *e) → (R, k, b, *e): slot j of row i is neighbor j's
        row (k = 2·ndims, dim-major, -1 then +1)."""
        self._check_cart(x, topo)
        key = ("neighbor_ag", tuple(topo.dims), x.shape, str(x.dtype))

        def build():
            # perm construction lives inside build(): the key (dims,
            # shape) fully determines it, so cache hits on the stencil
            # hot path skip the O(R·ndims) coordinate math entirely
            perms = self._cart_perms(topo)

            def inner(xs):           # (1, b, *e) per position (r == 1)
                slots = [lax.ppermute(xs, self.axis, pairs)
                         for _d, _s, pairs in perms]
                return jnp.stack(slots, axis=1)   # (1, k, b, *e)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def neighbor_alltoall_cart(self, x: jax.Array, topo) -> jax.Array:
        """(R, k, b, *e) → (R, k, b, *e): block j of rank i travels to
        neighbor j, landing in the MIRROR slot (dim's -1 block arrives in
        the receiver's +1 slot) — the halo-exchange data motion."""
        self._check_cart(x, topo)
        k = 2 * len(topo.dims)
        if x.shape[1] != k:
            raise ValueError(f"block dim {x.shape[1]} != {k} neighbors")
        key = ("neighbor_a2a", tuple(topo.dims), x.shape, str(x.dtype))

        def build():
            perms = self._cart_perms(topo)

            def inner(xs):           # (1, k, b, *e)
                slots = []
                for j, (_d, _s, pairs) in enumerate(perms):
                    mirror = j ^ 1   # (-1, +1) pair within the dim
                    slots.append(lax.ppermute(xs[:, mirror], self.axis,
                                              pairs))
                return jnp.stack(slots, axis=1)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def neighbor_allgather_graph(self, x: jax.Array, topo) -> jax.Array:
        """General-topology neighborhood allgather on device: (R, b, *e) →
        (R, maxdeg, b, *e), slot j of row i = in-neighbor j's row (rows
        past row i's degree are zeros). One all_gather + a cached masked
        gather-map — O(R·b) traffic rather than the periodic cart's
        neighbor-sparse 2·ndims ppermutes, but it serves ARBITRARY graphs
        and ragged degrees (coll_basic_neighbor_allgather.c generality).
        Degrees are host metadata; callers slice by topo.in_neighbors."""
        R = x.shape[0]
        if R != self.n or getattr(topo, "size",
                                  getattr(topo, "nnodes", R)) != R:
            raise ValueError(
                f"graph exchange needs rank-per-position layout (rows "
                f"{R} == mesh {self.n} == topo size)")
        # topologies are immutable: memoize the neighbor index ON the
        # topo so steady-state halo steps skip the O(R·maxdeg) rebuild
        idx = getattr(topo, "_dc_nbr_idx", None)
        if idx is None:
            nbrs = [list(topo.in_neighbors(i)) for i in range(R)]
            maxdeg = max((len(nb) for nb in nbrs), default=0)
            idx = np.full((R, max(maxdeg, 0)), -1, np.int32)
            for i, nb in enumerate(nbrs):
                idx[i, :len(nb)] = nb
            topo._dc_nbr_idx = idx
        maxdeg = idx.shape[1]
        if maxdeg == 0:
            return jnp.zeros((R, 0) + x.shape[1:], x.dtype)

        def build_idx():
            return jax.device_put(jnp.asarray(idx), self.sharding())

        idx_dev = self._idx_cached(
            ("neighbor_graph", idx.tobytes()), build_idx)
        key = ("neighbor_graph", maxdeg, x.shape, str(x.dtype))

        def build():
            def inner(xs, idxs):     # (1, b, *e), (1, maxdeg)
                full = lax.all_gather(xs, self.axis, axis=0,
                                      tiled=True)    # (R, b, *e)
                safe = jnp.maximum(idxs[0], 0)
                out = jnp.take(full, safe, axis=0)   # (maxdeg, b, *e)
                mask = (idxs[0] >= 0).reshape(
                    (maxdeg,) + (1,) * (out.ndim - 1))
                return jnp.where(mask, out, jnp.zeros_like(out))[None]
            return self._shard_map(inner, (self._spec, self._spec),
                                   self._spec)

        return self._compiled(key, build)(x, idx_dev)

    def neighbor_alltoall_graph(self, x: jax.Array, topo) -> jax.Array:
        """General-topology neighborhood alltoall: x (R, outdeg_max, b,
        *e) — block p of rank i goes to its p-th OUT-neighbor — →
        (R, indeg_max, b, *e), slot k of rank j from its k-th
        IN-neighbor (zeros past each rank's degree). Composed from the
        existing primitives: a per-row scatter onto destination ranks
        (row_gather), the dense-block ragged alltoallv, and a per-row
        reorder into in-neighbor slot order. Maps are memoized on the
        immutable topology per block size."""
        R = x.shape[0]
        if R != self.n or getattr(topo, "size", R) != R:
            raise ValueError(
                f"graph exchange needs rank-per-position layout (rows "
                f"{R} == mesh {self.n} == topo size)")
        K, b = x.shape[1], x.shape[2]
        elem = x.shape[3:]
        memo = getattr(topo, "_dc_a2a_maps", None)
        if memo is None or memo[0] != (K, b):
            outs = [list(topo.out_neighbors(i)) for i in range(R)]
            ins = [list(topo.in_neighbors(i)) for i in range(R)]
            if max((len(o) for o in outs), default=0) > K:
                raise ValueError(
                    f"block dim {K} < max out-degree "
                    f"{max(len(o) for o in outs)}")
            for o in outs:
                if len(set(o)) != len(o):
                    raise ValueError("repeated edges are not supported "
                                     "on the device graph path")
            # dst_map[i, j] = position of dst j in i's out-list (else -1)
            dst_map = np.full((R, R), -1, np.int32)
            for i, o in enumerate(outs):
                for p, j in enumerate(o):
                    dst_map[i, j] = p
            C = np.zeros((R, R), np.int64)     # elements i → j
            for i, o in enumerate(outs):
                for j in o:
                    C[i, j] = b
            # receiver: alltoallv concatenates by ASCENDING source; slot
            # k must hold in_neighbors[k] — element-level reorder map
            indeg_max = max((len(s) for s in ins), default=0)
            rd = np.full((R, indeg_max * b), -1, np.int32) \
                if indeg_max else np.zeros((R, 0), np.int32)
            for j, srcs in enumerate(ins):
                ordered = sorted(srcs)
                for k, s in enumerate(srcs):
                    pos = ordered.index(s)
                    rd[j, k * b:(k + 1) * b] = pos * b + np.arange(b)
            topo._dc_a2a_maps = memo = ((K, b), dst_map, C, rd, indeg_max)
        _kb, dst_map, C, rd, indeg_max = memo
        if indeg_max == 0:
            return jnp.zeros((R, 0, b) + elem, x.dtype)
        # static topology → the two device maps upload ONCE (LRU cache),
        # not per halo step like row_gather's per-call EP-routing form
        dst_dev = self._idx_cached(
            ("ga2a_dst", dst_map.tobytes()),
            lambda: jax.device_put(jnp.asarray(dst_map), self.sharding()))
        rd_dev = self._idx_cached(
            ("ga2a_rd", rd.tobytes()),
            lambda: jax.device_put(jnp.asarray(rd), self.sharding()))
        flat_blocks = x.reshape(R, K, -1)
        by_dst = self._row_gather_dev(flat_blocks, dst_dev,
                                      dst_map.shape[1])  # (R, R, b·e)
        blocks = by_dst.reshape((R, R, b) + elem)
        recv, _tot = self.alltoallv(blocks, C)           # (R, out_cap, *e)
        slot_elems = self._row_gather_dev(recv, rd_dev,
                                          rd.shape[1])   # (R, indeg·b, *e)
        return slot_elems.reshape((R, indeg_max, b) + elem)

    def push_row(self, x: jax.Array, src: int, dst: int) -> jax.Array:
        """ICI p2p: (R, *e) → (R, *e) with row dst ← row src's data, other
        rows unchanged — the one-hop collective-permute program behind
        device-payload send/recv on mesh comms (≙ the device-direct role of
        btl/smcuda GPU-IPC vs pml_ob1_accelerator.c host staging; SURVEY §7
        phase 4c). Only the one row crosses ICI; the executable is cached
        per (src, dst, shape, dtype), so a pipeline's stage→stage handoff
        compiles once."""
        R = x.shape[0]
        r = R // self.n
        key = ("push_row", int(src), int(dst), x.shape, str(x.dtype))

        def build():
            src_dev, src_loc = divmod(int(src), r)
            dst_dev, dst_loc = divmod(int(dst), r)

            def inner(xs):           # (r, *e)
                row = xs[src_loc]
                if src_dev != dst_dev:
                    row = lax.ppermute(row, self.axis,
                                       [(src_dev, dst_dev)])
                i = lax.axis_index(self.axis)
                updated = lax.dynamic_update_index_in_dim(
                    xs, row.astype(xs.dtype), dst_loc, 0)
                return jnp.where(i == dst_dev, updated, xs)
            return self._shard_map(inner, self._spec, self._spec)

        from .. import traffic
        if traffic.enabled and not isinstance(x, jax.core.Tracer):
            src_dev = int(src) // r
            dst_dev = int(dst) // r
            if src_dev != dst_dev:
                # exactly one row crosses ICI (the [(src_dev, dst_dev)]
                # perm inner lowers to)
                traffic.note_ppermute(
                    self.mesh, self.axis, [(src_dev, dst_dev)],
                    x.nbytes // max(R, 1), spc=self.spc, coll="push_row")
        return self._compiled(key, build)(x)

    def scan(self, x: jax.Array, op: Op = SUM, exclusive: bool = False
             ) -> jax.Array:
        """Prefix reduction across ranks: row i ← op(rows 0..i)."""
        R = x.shape[0]
        r = R // self.n
        key = ("scan", op.name, bool(exclusive), x.shape, str(x.dtype))

        cum_local = {"sum": lax.cumsum, "max": lax.cummax,
                     "min": lax.cummin, "prod": lax.cumprod}.get(op.name)

        def build():
            if cum_local is not None:
                def inner(xs):       # (r, *e)
                    # local prefix + tiny exchange: only the per-DEVICE
                    # totals cross ICI (n rows, not R — the bandwidth shape
                    # VERDICT r1 weak#7 asked for), then each device offsets
                    # its local prefix by the scan of lower devices' totals
                    loc = cum_local(xs, axis=0)            # (r, *e)
                    totals = lax.all_gather(loc[-1], self.axis)  # (n, *e)
                    csum = cum_local(totals, axis=0)       # inclusive
                    i = lax.axis_index(self.axis)
                    base_idx = jnp.maximum(i - 1, 0)
                    base = jnp.where(i > 0, csum[base_idx],
                                     _op_identity(op, totals[0]))
                    out = op.fn(jnp.broadcast_to(base[None], loc.shape), loc)
                    if exclusive:
                        prev = jnp.concatenate(
                            [jnp.broadcast_to(base[None], loc[:1].shape),
                             out[:-1]], axis=0)
                        return prev
                    return out
            else:
                def inner(xs):       # general op: gather + associative scan
                    full = lax.all_gather(xs, self.axis, axis=0, tiled=True)
                    csum = lax.associative_scan(
                        lambda a, b: op.fn(a, b), full, axis=0)
                    if exclusive:
                        try:
                            z = _op_identity(op, csum[:1])
                        except ValueError:
                            # user op without a registered identity: MPI
                            # leaves exclusive row 0 undefined; zeros keep
                            # the historical behavior
                            z = jnp.zeros_like(csum[:1])
                        csum = jnp.concatenate([z, csum[:-1]], axis=0)
                    i = lax.axis_index(self.axis)
                    return lax.dynamic_slice_in_dim(csum, i * r, r, 0)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    # -- ragged (v-variant) collectives ------------------------------------
    #
    # TPU-first shape for the reference's v-collectives
    # (coll_base_alltoallv.c:194 pairwise, coll_base_allgatherv.c:95 bruck,
    # coll_base_gather.c:41, coll_base_scatter.c:63): ragged buffers live on
    # device as PADDED blocks — (R, cap, *e) with row i holding counts[i]
    # valid elements — and the ragged structure travels as a DEVICE ARGUMENT
    # (a host-computed int32 gather map + mask), never as a baked constant.
    # Executables are therefore keyed on bucketed shapes only: an MoE router
    # whose per-expert counts change every step reuses one compiled program
    # as long as the capacity bucket and total are stable (token routing
    # conserves the total), which is the whole game for the EP hot path.

    @staticmethod
    def _bucket(n: int) -> int:
        """Next power-of-two capacity bucket (≥1)."""
        return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1

    @staticmethod
    def pack_ragged_blocks(rows: np.ndarray, C: np.ndarray,
                           cap: int) -> np.ndarray:
        """Host helper: dense per-rank rows (R, total, *e) + counts matrix
        C (C[i, j] = elements rank i sends to j, row sums ≤ total) → the
        padded (R, R, cap, *e) block layout alltoallv consumes. One
        implementation shared by the bench, the tuner, and tests."""
        rows = np.asarray(rows)
        R = C.shape[0]
        out = np.zeros((R, R, cap) + rows.shape[2:], rows.dtype)
        for i in range(R):
            off = 0
            for j in range(R):
                c = int(C[i, j])
                out[i, j, :c] = rows[i, off:off + c]
                off += c
        return out

    @staticmethod
    def compact_ragged_blocks(blocks: np.ndarray, C: np.ndarray,
                              out_cap: int) -> np.ndarray:
        """Host helper: the inverse compaction — padded (R, R, cap, *e)
        blocks → (R, out_cap, *e) rows, row j the dense concatenation of
        every source's valid elements for j (the staged arm of
        alltoallv, and the expected-value oracle in tests)."""
        blocks = np.asarray(blocks)
        R = C.shape[0]
        out = np.zeros((R, out_cap) + blocks.shape[3:], blocks.dtype)
        for j in range(R):
            pos = 0
            for i in range(R):
                c = int(C[i, j])
                out[j, pos:pos + c] = blocks[i, j, :c]
                pos += c
        return out

    def pad_ragged(self, arrays: Sequence[np.ndarray]
                   ) -> Tuple[jax.Array, list]:
        """Per-rank ragged host buffers → ((R, cap_bucket, *e) padded device
        array, counts). The ragged analog of from_ranks."""
        counts = [int(np.asarray(a).shape[0]) for a in arrays]
        cap = self._bucket(max(counts) if counts else 1)
        elem = np.asarray(arrays[0]).shape[1:]
        out = np.zeros((len(arrays), cap) + elem,
                       dtype=np.asarray(arrays[0]).dtype)
        for i, a in enumerate(arrays):
            out[i, :counts[i]] = a
        return jax.device_put(jnp.asarray(out), self.sharding()), counts

    def unpad_ragged(self, x: jax.Array, counts: Sequence[int]) -> list:
        """Padded (R, cap, *e) → list of exact per-rank host arrays."""
        host = np.asarray(jax.device_get(x))
        return [host[i, :int(c)] for i, c in enumerate(counts)]

    def _replicated(self, a: np.ndarray) -> jax.Array:
        return jax.device_put(jnp.asarray(a),
                              NamedSharding(self.mesh, P()))

    def allgatherv(self, x: jax.Array, counts: Sequence[int]) -> jax.Array:
        """(R, cap, *e) padded + counts → (R, total, *e): every row is the
        dense concatenation of all ranks' valid elements (MPI_Allgatherv
        with default contiguous displacements)."""
        R, cap = x.shape[0], x.shape[1]
        counts = [int(c) for c in counts]
        total = sum(counts)
        def build_idx():
            # gather map: output position → flattened (rank, offset) source;
            # cached on device so a repeated counts pattern pays the host
            # build + H2D once, not per call
            idx = np.concatenate(
                [np.arange(c, dtype=np.int32) + i * cap
                 for i, c in enumerate(counts)]) if total else \
                np.zeros((0,), np.int32)
            return self._replicated(idx)

        idx_dev = self._idx_cached(("allgatherv", cap, tuple(counts)),
                                   build_idx)
        key = ("allgatherv", x.shape, total, str(x.dtype))

        def build():
            def inner(xs, idxs):     # xs (r, cap, *e); idxs (total,) replic.
                full = lax.all_gather(xs, self.axis, axis=0, tiled=True)
                flat = full.reshape((-1,) + full.shape[2:])   # (R*cap, *e)
                out = jnp.take(flat, idxs, axis=0)            # (total, *e)
                return jnp.broadcast_to(out[None],
                                        (xs.shape[0],) + out.shape)
            return self._shard_map(inner, (self._spec, P()), self._spec)

        return self._compiled(key, build)(x, idx_dev)

    def gather(self, x: jax.Array, root: int = 0) -> jax.Array:
        """Rooted gather: MPI promises only the root's row; on ICI the
        allgather executable IS the gather (result replicated is free
        relative to the ring traffic) — same collapse as reduce≡allreduce."""
        return self.allgather(x)

    def gatherv(self, x: jax.Array, counts: Sequence[int],
                root: int = 0) -> jax.Array:
        return self.allgatherv(x, counts)

    def scatter(self, x: jax.Array, root: int = 0) -> jax.Array:
        """(R, R, b, *e) — row `root` holds R blocks — → (R, b, *e): row i
        gets root's block i. Root's row crosses ICI once (masked psum, the
        bcast trick), then every device slices its own blocks locally."""
        R = x.shape[0]
        r = R // self.n
        key = ("scatter", int(root), x.shape, str(x.dtype))

        def build():
            root_dev, root_local = divmod(int(root), r)

            def inner(xs):           # (r, R, b, *e)
                i = lax.axis_index(self.axis)
                contrib = jnp.where(i == root_dev, xs[root_local],
                                    jnp.zeros_like(xs[root_local]))
                full = lax.psum(contrib, self.axis)       # (R, b, *e)
                return lax.dynamic_slice_in_dim(full, i * r, r, 0)
            return self._shard_map(inner, self._spec, self._spec)

        return self._compiled(key, build)(x)

    def scatterv(self, x: jax.Array, counts: Sequence[int],
                 root: int = 0) -> jax.Array:
        """(R, R, cap, *e) padded blocks in row `root` → (R, cap, *e):
        row i gets root's block i (counts[i] valid elements, still padded —
        unpad_ragged for exact rows)."""
        return self.scatter(x, root)

    def alltoallv(self, x: jax.Array, counts) -> Tuple[jax.Array, list]:
        """Ragged all-to-all. x: (R, R, cap, *e) padded blocks — block
        [i, j] holds counts[i][j] valid elements from rank i to rank j.
        Returns ((R, out_cap, *e) padded, recv_counts): row j is the dense
        concatenation over sources of their valid elements for j.

        The dense ICI all-to-all moves the padded blocks (same program as
        alltoall); compaction happens target-side via a per-row gather map
        passed as a sharded device argument. One executable per
        (in-shape, out_cap-bucket, dtype) — routing patterns that keep the
        capacity bucket stable share it.
        """
        C = np.asarray(counts, dtype=np.int64)
        R, cap = x.shape[0], x.shape[2]
        r = R // self.n
        recv_tot = C.sum(axis=0)                  # per-destination totals
        out_cap = self._bucket(int(recv_tot.max()) if R else 1)
        def build_idx():
            # per-destination gather map over the post-exchange (R*cap)
            # flat block layout; -1 = padding (masked to zero). Cached on
            # device per counts matrix.
            idx = np.full((R, out_cap), -1, np.int32)
            for j in range(R):
                pos = 0
                for i in range(R):
                    c = int(C[i, j])
                    idx[j, pos:pos + c] = np.arange(c, dtype=np.int32) \
                        + i * cap
                    pos += c
            return jax.device_put(jnp.asarray(idx), self.sharding())

        idx_dev = self._idx_cached(("alltoallv", cap, C.tobytes()),
                                   build_idx)
        key = ("alltoallv", x.shape, out_cap, str(x.dtype))

        def build():
            def inner(xs, idxs):     # xs (r, R, cap, *e); idxs (r, out_cap)
                if r == 1:
                    mixed = lax.all_to_all(xs, self.axis, split_axis=1,
                                           concat_axis=1, tiled=True)
                else:
                    mixed = lax.all_to_all(xs, self.axis, split_axis=1,
                                           concat_axis=0, tiled=True)
                    mixed = jnp.swapaxes(mixed, 0, 1)     # (r, R, cap, *e)
                flat = mixed.reshape((mixed.shape[0], -1) + mixed.shape[3:])
                safe = jnp.maximum(idxs, 0)
                out = jax.vmap(lambda f, i: jnp.take(f, i, axis=0))(
                    flat, safe)                           # (r, out_cap, *e)
                mask = (idxs >= 0).reshape(idxs.shape + (1,) * (out.ndim - 2))
                return jnp.where(mask, out, jnp.zeros_like(out))
            return self._shard_map(inner, (self._spec, self._spec),
                                   self._spec)

        out = self._compiled(key, build)(x, idx_dev)
        return out, [int(t) for t in recv_tot]

    @staticmethod
    def compact_from_rows(rows: np.ndarray, C: np.ndarray,
                          out_cap: int) -> np.ndarray:
        """Host oracle/staged arm for :meth:`alltoallv_from_rows`: dense
        per-rank send rows + counts matrix → the compact padded receive
        rows, by direct O(total) segment copies (no padded block
        intermediate). One implementation shared by the coll/xla staged
        arm, the bench, and tests."""
        rows = np.asarray(rows)
        C = np.asarray(C, dtype=np.int64)
        R = C.shape[0]
        soff = np.zeros((R, R), np.int64)
        soff[:, 1:] = np.cumsum(C, axis=1)[:, :-1]
        out = np.zeros((R, int(out_cap)) + rows.shape[2:], rows.dtype)
        for j in range(R):
            pos = 0
            for i in range(R):
                c = int(C[i, j])
                out[j, pos:pos + c] = rows[i, soff[i, j]:soff[i, j] + c]
                pos += c
        return out

    def a2av_plan(self, shape: tuple, counts,
                  slice_cap: Optional[int] = None) -> Dict[str, int]:
        """The (slice_cap, scan_steps, out_cap) figures the sliced ragged
        exchange takes for a (R, L, *e) send of ``shape`` + counts matrix
        — pure shape math, no dispatch. An explicit ``slice_cap`` wins;
        else the ``coll_a2av_slice_cap`` var; else the ~1M-element
        transient heuristic. Decision audits record these figures so the
        footprint/padding trade is visible per collective."""
        C = np.asarray(counts, dtype=np.int64)
        R = shape[0]
        cap = self._bucket(int(C.max()) if C.size else 1)
        out_cap = self._bucket(int(C.sum(axis=0).max()) if C.size else 1)
        elem = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        if slice_cap is None:
            cfgd = int(_var.get("coll_a2av_slice_cap", 0) or 0)
            if cfgd > 0:
                slice_cap = min(cap, cfgd)
            else:
                # bound the per-step transient (the (R, S, *e) gather) to
                # ~1M ELEMENTS per device row — trailing elem dims count
                slice_cap = min(cap, max(64, self._bucket(
                    max(1, (1 << 20) // max(R * elem, 1)))))
        slice_cap = max(1, int(slice_cap))
        return {"slice_cap": int(slice_cap),
                "scan_steps": int(-(-cap // slice_cap)),
                "out_cap": int(out_cap)}

    def alltoallv_from_rows(self, x: jax.Array, counts,
                            slice_cap: Optional[int] = None
                            ) -> Tuple[jax.Array, list]:
        """Ragged all-to-all straight from DENSE rows: (R, L, *e) + counts
        matrix C → ((R, out_cap, *e) padded-dense, recv_counts), the same
        result as ``pack_ragged_blocks`` + :meth:`alltoallv` — but the
        (R, R, cap) padded block tensor NEVER materializes anywhere.
        The capacity dimension is processed in ``slice_cap``-sized slices
        inside one ``lax.scan``: each step gathers the slice of every
        destination block from the dense row (device-side, from cumsum
        offsets), exchanges it with one dense ``all_to_all``, and
        scatters it into its final position in the output. Peak extra HBM
        per device is O(R·slice_cap·r) instead of O(R·cap·r) — at the
        bench's 16 MB/rank ragged shape that is the difference between a
        256 MiB resident padding blowup (the round-2→5 sweep truncation)
        and a few-MB transient. Wire traffic is the same padded-slice
        volume the block form sends (ragged rows mean some slice padding;
        the scan trades that for footprint).

        Row i of ``x`` holds its sends dense and concatenated in
        destination order (sum_j C[i,j] valid elements). recv row j is
        the dense concatenation over sources, like :meth:`alltoallv`."""
        C = np.asarray(counts, dtype=np.int64)
        R = x.shape[0]
        r = R // self.n
        L = x.shape[1]
        plan = self.a2av_plan(x.shape, C, slice_cap)
        slice_cap = plan["slice_cap"]
        k = plan["scan_steps"]
        out_cap = plan["out_cap"]
        # stash the footprint/padding trade this call actually took so the
        # caller's decision audit can record it
        self._last_a2av = dict(plan)
        # k is BAKED into the compiled scan: it must be in the cache key
        # (bucketed cap keeps nearby routings sharing one executable;
        # without k in the key a smaller-cap executable would be reused
        # and silently drop the tail slices)

        def build_maps():
            soff = np.zeros((R, R), np.int32)  # send offsets in row i
            soff[:, 1:] = np.cumsum(C, axis=1)[:, :-1]
            roff = np.zeros((R, R), np.int32)  # recv offsets in row j
            roff[1:, :] = np.cumsum(C, axis=0)[:-1, :]
            put = lambda a: jax.device_put(jnp.asarray(a),
                                           self.sharding())
            return (put(soff), put(C.astype(np.int32)),
                    put(roff.T.copy()), put(C.T.astype(np.int32).copy()))

        soff_d, crow_d, rofft_d, ccolt_d = self._idx_cached(
            ("a2av_rows", C.tobytes()), build_maps)
        key = ("alltoallv_from_rows", x.shape, out_cap, slice_cap, k,
               str(x.dtype))

        def build():
            S = slice_cap
            e_shape = x.shape[2:]

            def inner(xs, soff, crow, rofft, ccolt):
                # xs (r, L, *e); soff/crow: send offsets/counts for the
                # LOCAL source rows; rofft/ccolt: recv offsets/counts for
                # the LOCAL destination rows (transposed views)
                rr = xs.shape[0]
                p = jnp.arange(S, dtype=jnp.int32)

                def one_row_gather(row, off, cnt, base):
                    # (L, *e), (R,), (R,) → (R, S, *e) slice of each block
                    src = off[:, None] + base + p[None, :]
                    valid = (base + p)[None, :] < cnt[:, None]
                    g = jnp.take(row, jnp.clip(src, 0, L - 1).reshape(-1),
                                 axis=0).reshape((R, S) + e_shape)
                    m = valid.reshape((R, S) + (1,) * len(e_shape))
                    return jnp.where(m, g, jnp.zeros_like(g))

                def one_row_scatter(out, vals, off, cnt, base):
                    # out (out_cap+S, *e); vals (R, S, *e): place block
                    # slice from source i at roff + base + p
                    pos = off[:, None] + base + p[None, :]
                    valid = (base + p)[None, :] < cnt[:, None]
                    pos = jnp.where(valid, pos, out_cap)   # trash slot
                    return out.at[pos.reshape(-1)].set(
                        vals.reshape((R * S,) + e_shape))

                def body(out, s):
                    base = s * S
                    g = jax.vmap(one_row_gather,
                                 in_axes=(0, 0, 0, None))(
                        xs, soff, crow, base)              # (rr, R, S, *e)
                    if r == 1:
                        mixed = lax.all_to_all(g, self.axis, split_axis=1,
                                               concat_axis=1, tiled=True)
                    else:
                        mixed = lax.all_to_all(g, self.axis, split_axis=1,
                                               concat_axis=0, tiled=True)
                        mixed = jnp.swapaxes(mixed, 0, 1)  # (rr, R, S, *e)
                    out = jax.vmap(one_row_scatter,
                                   in_axes=(0, 0, 0, 0, None))(
                        out, mixed, rofft, ccolt, base)
                    return out, None

                out0 = jnp.zeros((rr, out_cap + S) + e_shape, xs.dtype)
                # the body's all_to_all makes the carry VARYING over the
                # mesh axis; the zeros init must match (shard_map VMA)
                out0 = _compat.pcast(out0, (self.axis,), to="varying")
                out, _ = lax.scan(body, out0,
                                  jnp.arange(k, dtype=jnp.int32))
                return out[:, :out_cap]

            return self._shard_map(
                inner, (self._spec,) * 5, self._spec)

        out = self._compiled(key, build)(x, soff_d, crow_d, rofft_d,
                                         ccolt_d)
        return out, [int(t) for t in C.sum(axis=0)]

    def _row_gather_dev(self, x: jax.Array, idx_dev, m: int) -> jax.Array:
        """row_gather against an ALREADY-device-resident (R, m) map —
        the zero-upload form static-topology callers use."""
        key = ("row_gather", x.shape, m, str(x.dtype))

        def build():
            def inner(xs, idxs):     # (r, T, *e), (r, M)
                safe = jnp.maximum(idxs, 0)
                out = jax.vmap(lambda f, i: jnp.take(f, i, axis=0))(
                    xs, safe)
                mask = (idxs >= 0).reshape(
                    idxs.shape + (1,) * (out.ndim - 2))
                return jnp.where(mask, out, jnp.zeros_like(out))
            return self._shard_map(inner, (self._spec, self._spec),
                                   self._spec)

        return self._compiled(key, build)(x, idx_dev)

    def row_gather(self, x: jax.Array, idx: np.ndarray) -> jax.Array:
        """Per-row device gather: (R, T, *e) + host map idx (R, M) →
        (R, M, *e), out[i, m] = x[i, idx[i, m]] (idx −1 → zeros). The map
        travels as a sharded device argument, so one executable per
        (shape, M, dtype) serves every permutation — the building block the
        ragged EP pipeline uses to form/unform alltoallv blocks. The map
        uploads per call (EP routing changes every step); static-topology
        callers cache the device map and use _row_gather_dev."""
        idx = np.asarray(idx, np.int32)
        return self._row_gather_dev(
            x, jax.device_put(jnp.asarray(idx), self.sharding()),
            idx.shape[1])

    def reduce_scatter_v(self, x: jax.Array, counts: Sequence[int],
                         op: Op = SUM) -> jax.Array:
        """(R, total, *e) + counts → (R, cap, *e) padded: row i holds the
        op-reduction of every rank's block [displ_i : displ_i+counts_i].
        SUM rides psum_scatter (traffic-optimal, the Rabenseifner half);
        other ops reduce fully then slice."""
        counts = [int(c) for c in counts]
        R = x.shape[0]
        r = R // self.n
        cap = self._bucket(max(counts) if counts else 1)
        def build_idx():
            displs = np.concatenate(
                [[0], np.cumsum(counts)[:-1]]).astype(np.int64)
            # block map: (R, cap) position → source offset in the dense row
            idx = np.full((R, cap), -1, np.int32)
            for i, c in enumerate(counts):
                idx[i, :c] = np.arange(c, dtype=np.int32) + int(displs[i])
            return (self._replicated(np.maximum(idx, 0)),
                    self._replicated(idx >= 0))

        safe_dev, mask_dev = self._idx_cached(
            ("reduce_scatter_v", cap, tuple(counts)), build_idx)
        key = ("reduce_scatter_v", op.name, x.shape, cap, str(x.dtype))

        def build():
            if op.name == "sum":
                def inner(xs, safe, mask):   # xs (r, total, *e)
                    folded = self._fold_local(xs, op)        # (total, *e)
                    # rearrange into padded blocks (R*cap, *e), zeros in pad
                    blocks = jnp.take(folded, safe.reshape(-1), axis=0)
                    m = mask.reshape((-1,) + (1,) * (blocks.ndim - 1))
                    blocks = jnp.where(m, blocks, jnp.zeros_like(blocks))
                    mine = lax.psum_scatter(blocks, self.axis,
                                            scatter_dimension=0, tiled=True)
                    return mine.reshape((r, cap) + xs.shape[2:])
                return self._shard_map(inner, (self._spec, P(), P()),
                                       self._spec)

            def inner(xs, safe, mask):
                red = preduce(self._fold_local(xs, op), self.axis, op)
                i = lax.axis_index(self.axis)
                my_safe = lax.dynamic_slice_in_dim(safe, i * r, r, 0)
                my_mask = lax.dynamic_slice_in_dim(mask, i * r, r, 0)
                mine = jax.vmap(lambda s: jnp.take(red, s, axis=0))(my_safe)
                m = my_mask.reshape(my_mask.shape + (1,) * (mine.ndim - 2))
                return jnp.where(m, mine, jnp.zeros_like(mine))
            return self._shard_map(inner, (self._spec, P(), P()), self._spec)

        return self._compiled(key, build)(x, safe_dev, mask_dev)

    def barrier(self) -> None:
        """A real cross-device sync: tiny psum + block."""
        key = ("barrier",)

        def build():
            def inner(xs):
                return lax.psum(xs, self.axis)
            return self._shard_map(inner, P(self.axis), P())

        # from_local works in both the single-controller and multi-process
        # (rank-per-chip) regimes — device_put would reject the
        # non-addressable devices of other processes
        pid = jax.process_index()
        n_local = sum(1 for d in self.mesh.devices.flat
                      if d.process_index == pid)
        token = self.from_local(np.zeros((n_local,), np.int32))
        self._compiled(key, build)(token).block_until_ready()
