"""Byte-transport framework (≙ the BTL, opal/mca/btl/btl.h:1172).

A transport moves opaque frames (header dict + payload bytes) between ranks.
Kept from the reference's BTL contract:
  * ``eager_limit`` / ``max_send_size`` per-transport tunables
    (btl.h:1176,1179) registered as variables;
  * active-message dispatch: received frames carry a *tag* that indexes a
    process-global callback table (btl.h:626
    ``mca_btl_base_active_message_trigger``) — the p2p protocol, one-sided,
    and FT heartbeats each own a tag;
  * components register into the ``transport`` framework and are selected
    per-peer by priority/reachability (≙ BML r2, ompi/mca/bml/bml.h:57-72).

Transports in-tree: ``self`` (loopback), ``tcp`` (DCN analog), ``shm``
(shared-memory ring buffers; native C++ fast path in native/shmbox.cpp).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List

from ..core import var as _var
from ..core.component import Component
from ..core.progress import _NULL_GUARD as _null_guard

# Active-message tags (≙ mca_btl_base_active_message_trigger indices)
AM_P2P = 1          # matched point-to-point protocol (p2p/pml.py)
AM_OSC = 2          # one-sided windows
AM_FT = 3           # failure-detector heartbeats
AM_COLL = 4         # collective internals (host path)


class Transport(Component):
    """Per-job transport *module*; the registered singleton acts as the
    component whose query() instantiates a fresh module (the reference's
    component-vs-module split, docs/mca.rst:14-28)."""

    # relative bandwidth class for fragment striping (≙ btl_bandwidth,
    # bml.h:57-72 weighting); overridden per transport
    bandwidth = 10

    def __init__(self) -> None:
        self.eager_limit = _var.register(
            "transport", self.name or "base", "eager_limit", 65536, type=int,
            level=4, help="Max bytes sent eagerly in one frame.").value
        self.max_send_size = _var.register(
            "transport", self.name or "base", "max_send_size", 1 << 20, type=int,
            level=4, help="Max fragment size for pipelined sends.").value
        # per-rank active-message dispatch: tag → cb(src, header, payload);
        # installed by the runtime Context before init_job
        self.dispatch: Dict[int, Callable[[int, Dict[str, Any], bytes], None]] = {}

    def deliver(self, src: int, tag: int, header: Dict[str, Any], payload: bytes) -> None:
        cb = self.dispatch.get(tag)
        if cb is None:
            raise RuntimeError(f"no active-message handler for tag {tag}")
        cb(src, header, payload)

    def query(self, scope: Any = None):
        """Create a fresh module instance (per rank/job)."""
        inst = type(self)()
        inst.priority = self.priority
        return self.priority, inst

    def init_job(self, bootstrap) -> None:
        """Wire up using the control plane (publish/lookup addresses)."""

    def reachable(self, peer: int) -> bool:
        raise NotImplementedError

    def send(self, peer: int, tag: int, header: Dict[str, Any], payload: bytes) -> None:
        """Enqueue a frame; delivery is asynchronous. Must be orderable:
        frames to the same peer+tag arrive in send order (MPI non-overtaking
        depends on this, like single-BTL ordering in the reference)."""
        raise NotImplementedError

    def progress(self) -> int:
        return 0

    def confirm(self, peer: int) -> None:
        """Block until frames previously accepted for ``peer`` are handed
        off (or raise on a failed path). Transports whose send() already
        guarantees handoff (shm rings, loopback) need nothing here; tcp
        overrides it to drain its outbuf and surface async errors."""

    def pending_count(self, exclude: frozenset = frozenset()) -> int:
        """Frames accepted by send() but not yet on the wire, not counting
        peers in ``exclude`` (dead ranks never drain their ring). Finalize
        must progress until every transport reports 0 — the reference spins
        opal_progress inside every blocking point for the same reason
        (opal/runtime/opal_progress.c:216)."""
        return 0

    def finalize(self) -> None:
        pass


class TransportLayer:
    """Per-peer transport choice (≙ BML r2's per-peer BTL arrays,
    bml_r2.c).

    The highest-priority transport that reports the peer reachable OWNS
    the peer: every control/ordered frame rides it (per-channel FIFO stays
    trivially correct, like single-BTL ordering). Large-message fragment
    trains may additionally STRIPE across every eligible transport
    (``paths_for_peer``), weighted by ``bandwidth`` — the bml.h:57-72
    scheduling — and ``mark_failed`` retires a path so the pml re-routes
    outstanding fragments over the survivors (r2 failover).
    """

    def __init__(self, transports: List[Transport]) -> None:
        self.transports = sorted(transports, key=lambda t: -t.priority)
        self._by_peer: Dict[int, Transport] = {}
        self._paths: Dict[int, List[Transport]] = {}
        self._failed: Dict[int, set] = {}
        self._lock = threading.Lock()
        self.guard = None     # async-progress RLock (Context wires it)
        # mark_failed listeners: upper layers with their own per-peer
        # routing caches (the native pml's fast-path table) invalidate here
        self.on_path_failed: List = []

    def for_peer(self, peer: int) -> Transport:
        with self._lock:
            t = self._by_peer.get(peer)
            if t is None:
                failed = self._failed.get(peer, ())
                for cand in self.transports:
                    if cand.name not in failed and cand.reachable(peer):
                        t = cand
                        break
                if t is None:
                    raise RuntimeError(f"no transport reaches rank {peer}")
                self._by_peer[peer] = t
            return t

    def paths_for_peer(self, peer: int) -> List[Transport]:
        """Every live transport that reaches the peer, primary first
        (≙ the r2 per-peer BTL array for btl_send). Loopback is sole-path:
        striping a self-send through the kernel tcp stack only adds
        copies, so when `self` owns the peer it is the ONLY path."""
        with self._lock:
            paths = self._paths.get(peer)
            if paths is None:
                failed = self._failed.get(peer, ())
                paths = [t for t in self.transports
                         if t.name not in failed and t.reachable(peer)]
                if paths and paths[0].name == "self":
                    paths = paths[:1]
                self._paths[peer] = paths
            return paths

    def mark_failed(self, peer: int, transport: Transport) -> None:
        """Retire a transport for a peer (error mid-stream): for_peer and
        paths_for_peer re-select from the survivors."""
        with self._lock:
            self._failed.setdefault(peer, set()).add(transport.name)
            self._by_peer.pop(peer, None)
            self._paths.pop(peer, None)
        for cb in list(self.on_path_failed):
            cb(peer, transport)

    def send(self, peer: int, tag: int, header: Dict[str, Any], payload: bytes = b"") -> None:
        # guard: serialize against the async progress thread when enabled
        with self.guard or _null_guard:
            self.for_peer(peer).send(peer, tag, header, payload)

    def add_peers(self, new_size: int) -> None:
        """Propagate a dynamic-spawn growth of the global rank space
        (serialized against the async progress thread like every other
        owner-thread transport mutation)."""
        with self.guard or _null_guard:
            for t in self.transports:
                if hasattr(t, "add_peers"):
                    t.add_peers(new_size)

    def transport_matrix(self) -> Dict[int, str]:
        """Which transport serves each wired peer (≙ hook/comm_method's
        transport matrix dump, hook_comm_method_fns.c:25)."""
        with self._lock:
            return {p: t.name for p, t in self._by_peer.items()}
