"""MPI message matching (≙ ompi/mca/pml/ob1/pml_ob1_recvfrag.c:453 matching
and the posted/unexpected queues in pml_ob1_recvreq.c).

Per (communicator-id) context: a list of posted receives and a list of
unexpected messages. Matching rules are MPI's: (source, tag) with
ANY_SOURCE/ANY_TAG wildcards, FIFO within a (src, cid) channel — enforced by
per-channel sequence numbers so multi-transport arrival can never reorder a
match (the reference relies on single-BTL ordering plus hdr_seq;
pml_ob1_hdr.h match header carries ctx/src/tag/seq).
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .request import ANY_SOURCE, ANY_TAG
from .. import peruse


def _tag_matches(posted_tag: int, msg_tag: int) -> bool:
    """ANY_TAG matches user tags (≥ 0) only — never the reserved negative
    internal tags (comm management, collectives), exactly like MPI where
    wildcards cannot match reserved-tag traffic."""
    if posted_tag == ANY_TAG:
        return msg_tag >= 0
    return posted_tag == msg_tag


class Posted:
    __slots__ = ("src", "tag", "on_match", "req")

    def __init__(self, src: int, tag: int, on_match: Callable,
                 req: Any = None) -> None:
        self.src = src
        self.tag = tag
        self.on_match = on_match
        self.req = req        # owning Request (FT: failed-peer completion)


class Unexpected:
    __slots__ = ("src", "tag", "seq", "kind", "header", "payload")

    def __init__(self, src: int, tag: int, seq: int, kind: str,
                 header: Dict[str, Any], payload: bytes) -> None:
        self.src = src
        self.tag = tag
        self.seq = seq
        self.kind = kind
        self.header = header
        self.payload = payload


class MatchingEngine:
    def __init__(self) -> None:
        self.spc = None     # optional Counters (set by the pml)
        # cid → posted receives in post order
        self._posted: Dict[int, List[Posted]] = defaultdict(list)
        # cid → src → ordered unexpected frames
        self._unexpected: Dict[int, Dict[int, deque]] = defaultdict(
            lambda: defaultdict(deque))
        # expected next sequence per (cid, src); frames out of order are held
        self._next_seq: Dict[Tuple[int, int], int] = defaultdict(int)
        self._held: Dict[Tuple[int, int], Dict[int, Unexpected]] = defaultdict(dict)

    # -- receive side -------------------------------------------------------

    def post_recv(self, cid: int, src: int, tag: int,
                  on_match: Callable, req: Any = None) -> Optional[Posted]:
        """Try to match an already-arrived message first; else enqueue.

        on_match(unexpected | None) is called immediately when an unexpected
        frame matches; returns the Posted entry if queued.
        """
        match = self._find_unexpected(cid, src, tag)
        if match is not None:
            if peruse.active:
                peruse.fire(peruse.REQ_MATCH_UNEX, cid=cid, src=match.src,
                            tag=match.tag, seq=match.seq)
            on_match(match)
            return None
        p = Posted(src, tag, on_match, req)
        self._posted[cid].append(p)
        if peruse.active:
            peruse.fire(peruse.REQ_INSERT_IN_POSTED_Q, cid=cid, src=src,
                        tag=tag)
        return p

    def fail_src(self, src: int, err: Exception,
                 any_source_cids=frozenset(),
                 pending_err: Exception | None = None) -> None:
        """Complete every posted receive naming ``src`` with ``err`` (ULFM:
        operations on a failed peer must not hang). ANY_SOURCE receives on
        the communicators in ``any_source_cids`` (those whose group contains
        the failed rank, minus already-acked failures — computed by the
        caller, which knows the cid→comm map) are NOT completed: they get
        ``pending_err`` as a one-shot MPIX_ERR_PROC_FAILED_PENDING and stay
        posted, still able to match survivors' messages after
        failure_ack."""
        for cid, lst in self._posted.items():
            for p in [p for p in lst if p.src == src]:
                lst.remove(p)
                if p.req is not None:
                    p.req.complete(err)
            if cid in any_source_cids:
                for p in lst:
                    if p.src == ANY_SOURCE and p.req is not None:
                        p.req.set_pending(pending_err or err)

    def cancel(self, cid: int, posted: Posted) -> bool:
        lst = self._posted.get(cid, [])
        if posted in lst:
            lst.remove(posted)
            return True
        return False

    def _find_unexpected(self, cid: int, src: int, tag: int) -> Optional[Unexpected]:
        buckets = self._unexpected.get(cid)
        if not buckets:
            return None
        sources = [src] if src != ANY_SOURCE else sorted(buckets.keys())
        for s in sources:
            q = buckets.get(s)
            if not q:
                continue
            for i, u in enumerate(q):
                if _tag_matches(tag, u.tag):
                    del q[i]
                    return u
            # only the head of each channel may match out of post order for
            # same-tag traffic; scanning deeper is fine because seq ordering
            # already serialized insertion
        return None

    # -- arrival side -------------------------------------------------------

    def arrived(self, cid: int, src: int, tag: int, seq: int, kind: str,
                header: Dict[str, Any], payload: bytes) -> None:
        """A MATCH/RNDV frame arrived; deliver in sequence order."""
        key = (cid, src)
        if seq != self._next_seq[key]:
            self._held[key][seq] = Unexpected(src, tag, seq, kind, header, payload)
            return
        self._deliver(cid, Unexpected(src, tag, seq, kind, header, payload))
        self._next_seq[key] += 1
        held = self._held.get(key)
        while held and self._next_seq[key] in held:
            u = held.pop(self._next_seq[key])
            self._deliver(cid, u)
            self._next_seq[key] += 1

    def _deliver(self, cid: int, u: Unexpected) -> None:
        for i, p in enumerate(self._posted.get(cid, [])):
            if (p.src == ANY_SOURCE or p.src == u.src) and \
               _tag_matches(p.tag, u.tag):
                del self._posted[cid][i]
                if self.spc is not None:
                    self.spc.inc("matches_posted")
                p.on_match(u)
                return
        if self.spc is not None:
            self.spc.inc("unexpected_arrivals")
        if peruse.active:
            peruse.fire(peruse.MSG_INSERT_IN_UNEX_Q, cid=cid, src=u.src,
                        tag=u.tag, seq=u.seq)
        self._unexpected[cid][u.src].append(u)

    # -- probe --------------------------------------------------------------

    def probe(self, cid: int, src: int, tag: int,
              remove: bool = False) -> Optional[Unexpected]:
        """Non-destructive lookup (MPI_Iprobe); with ``remove`` the matched
        message is DEQUEUED — the MPI_Mprobe discipline: once matched into a
        message handle it can no longer match any other receive
        (≙ ompi/message/message.h matched-message objects)."""
        if remove:   # one matching walk to maintain: reuse the dequeue path
            return self._find_unexpected(cid, src, tag)
        buckets = self._unexpected.get(cid)
        if not buckets:
            return None
        sources = [src] if src != ANY_SOURCE else sorted(buckets.keys())
        for s in sources:
            for u in buckets.get(s, ()):
                if _tag_matches(tag, u.tag):
                    return u
        return None
