"""Point-to-point protocol engine (≙ pml/ob1, ompi/mca/pml/ob1/).

Implements MPI send/recv semantics over the byte transports:
  * eager protocol for payloads ≤ the transport's eager_limit — one MATCH
    frame, sender completes locally (pml_ob1_isend.c:249,297 send_inline
    fast path);
  * rendezvous for large payloads — RNDV header, receiver matches and ACKs,
    sender streams FRAGs of max_send_size (wire protocol kinds mirror
    pml_ob1_hdr.h:43-52 MATCH/RNDV/ACK/FRAG);
  * matching with wildcards + per-channel sequence numbers (matching.py);
  * ``sync=True`` forces rendezvous regardless of size — MPI_Ssend semantics
    (completion implies the receive was matched).

Payloads are packed/unpacked through the datatype convertor; contiguous
numpy buffers take the single-copy fast path. Device (jax) arrays are staged
via numpy here — the ICI path for device data is the coll/xla component, not
host p2p (SURVEY.md §5.8).
"""

from __future__ import annotations

import itertools
from collections import defaultdict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..core import var as _var
from ..core.output import show_help
from ..core.progress import ProgressEngine
from ..datatype import Convertor, Datatype, from_numpy
from . import transport as T
from .matching import MatchingEngine, Unexpected
from .request import ANY_SOURCE, ANY_TAG, Request


class TruncateError(RuntimeError):
    pass


def _buffer_args(buf, datatype: Optional[Datatype], count: Optional[int]
                 ) -> Tuple[np.ndarray, Datatype, int]:
    arr = np.asarray(buf)
    if datatype is None:
        datatype = from_numpy(arr.dtype)
        if count is None:
            count = arr.size
    elif count is None:
        count = (arr.nbytes // datatype.size) if datatype.size else 0
    return arr, datatype, count


class _SendState:
    __slots__ = ("req", "data", "dst", "offset")

    def __init__(self, req: Request, data: bytes, dst: int) -> None:
        self.req = req
        self.data = data
        self.dst = dst
        self.offset = 0


class _RecvState:
    __slots__ = ("req", "conv", "received", "total")

    def __init__(self, req: Request, conv: Convertor, total: int) -> None:
        self.req = req
        self.conv = conv
        self.received = 0
        self.total = total


class P2P:
    """One instance per rank process."""

    def __init__(self, bootstrap, layer: T.TransportLayer,
                 engine: ProgressEngine, spc=None) -> None:
        from ..spc import Counters

        self.bootstrap = bootstrap
        self.rank = bootstrap.rank
        self.layer = layer
        self.engine = engine
        self.spc = spc if spc is not None else Counters()
        self.matching = MatchingEngine()
        self.matching.spc = self.spc
        self._send_seq: Dict[Tuple[int, int], int] = defaultdict(int)
        self._sreq = itertools.count(1)
        self._rreq = itertools.count(1)
        self._pending_send: Dict[int, _SendState] = {}
        self._pending_recv: Dict[int, _RecvState] = {}
        for t in layer.transports:
            t.dispatch[T.AM_P2P] = self._am_handler
            engine.register(t.progress)

    # -- send ---------------------------------------------------------------

    def isend(self, buf, dst: int, tag: int = 0, cid: int = 0,
              datatype: Optional[Datatype] = None, count: Optional[int] = None,
              sync: bool = False) -> Request:
        arr, dt, cnt = _buffer_args(buf, datatype, count)
        data = Convertor(arr, dt, cnt).pack() if cnt else b""
        req = Request()
        req.status.source = self.rank
        req.status.tag = tag
        req.status.count = len(data)
        seq = self._send_seq[(cid, dst)]
        self._send_seq[(cid, dst)] = seq + 1
        transport = self.layer.for_peer(dst)
        self.spc.inc("isends")
        self.spc.inc("bytes_sent", len(data))
        self.spc.peer_traffic("tx", dst, len(data))
        if not sync and len(data) <= transport.eager_limit:
            self.spc.inc("eager_sends")
            hdr = {"k": "match", "cid": cid, "tag": tag, "seq": seq,
                   "size": len(data)}
            transport.send(dst, T.AM_P2P, hdr, data)
            req.complete()   # eager: locally complete once buffered
            return req
        self.spc.inc("rndv_sends")
        sreq = next(self._sreq)
        self._pending_send[sreq] = _SendState(req, data, dst)
        hdr = {"k": "rndv", "cid": cid, "tag": tag, "seq": seq,
               "size": len(data), "sreq": sreq}
        transport.send(dst, T.AM_P2P, hdr, b"")
        return req

    def send(self, buf, dst: int, tag: int = 0, cid: int = 0,
             datatype: Optional[Datatype] = None, count: Optional[int] = None,
             sync: bool = False) -> None:
        self.spc.inc("sends")
        self.isend(buf, dst, tag, cid, datatype, count, sync).wait()

    # -- recv ---------------------------------------------------------------

    def irecv(self, buf, src: int = ANY_SOURCE, tag: int = ANY_TAG,
              cid: int = 0, datatype: Optional[Datatype] = None,
              count: Optional[int] = None) -> Request:
        arr, dt, cnt = _buffer_args(buf, datatype, count)
        req = Request()
        self.spc.inc("recvs")

        def on_match(u: Unexpected) -> None:
            self.spc.inc("bytes_recvd", u.header["size"])
            self.spc.peer_traffic("rx", u.src, u.header["size"])
            capacity = dt.size * cnt
            req.status.source = u.src
            req.status.tag = u.tag
            if u.header["size"] > capacity:
                show_help.show("truncate", capacity, u.header["size"],
                               u.tag, u.src)
                if u.kind == "rndv":
                    # NACK (rreq < 0) so the sender's request still completes
                    # — truncation is a receiver-side error in MPI
                    self.layer.send(u.src, T.AM_P2P,
                                    {"k": "ack", "sreq": u.header["sreq"],
                                     "rreq": -1}, b"")
                req.complete(TruncateError(
                    f"recv buffer {capacity}B < message {u.header['size']}B"))
                return
            if u.kind == "match":
                if u.payload:
                    Convertor(arr, dt, cnt).unpack(u.payload)
                req.status.count = len(u.payload)
                req.complete()
            else:  # rendezvous: ACK with a recv-request id, collect FRAGs
                rreq = next(self._rreq)
                conv = Convertor(arr, dt, cnt)
                self._pending_recv[rreq] = _RecvState(req, conv, u.header["size"])
                req.status.count = u.header["size"]
                if u.header["size"] == 0:
                    del self._pending_recv[rreq]
                    req.complete()
                    # still ACK so the sender's request completes
                self.layer.send(u.src, T.AM_P2P,
                                {"k": "ack", "sreq": u.header["sreq"],
                                 "rreq": rreq}, b"")

        if self.matching.post_recv(cid, src, tag, on_match, req=req) is None:
            self.spc.inc("matches_unexpected")
        return req

    def recv(self, buf, src: int = ANY_SOURCE, tag: int = ANY_TAG, cid: int = 0,
             datatype: Optional[Datatype] = None, count: Optional[int] = None):
        return self.irecv(buf, src, tag, cid, datatype, count).wait()

    def sendrecv(self, sendbuf, dst: int, recvbuf, src: int,
                 sendtag: int = 0, recvtag: int = ANY_TAG, cid: int = 0):
        rreq = self.irecv(recvbuf, src, recvtag, cid)
        sreq = self.isend(sendbuf, dst, sendtag, cid)
        status = rreq.wait()
        sreq.wait()
        return status

    # -- probe --------------------------------------------------------------

    def iprobe(self, src: int = ANY_SOURCE, tag: int = ANY_TAG, cid: int = 0):
        self.spc.inc("probes")
        self.engine.progress()
        u = self.matching.probe(cid, src, tag)
        if u is None:
            return None
        st = {"source": u.src, "tag": u.tag, "count": u.header["size"]}
        return st

    def probe(self, src: int = ANY_SOURCE, tag: int = ANY_TAG, cid: int = 0,
              timeout: Optional[float] = None):
        result = {}

        def check() -> bool:
            r = self.iprobe(src, tag, cid)
            if r:
                result.update(r)
                return True
            return False

        self.engine.wait_until(check, timeout=timeout)
        return result or None

    # -- active-message handler (≙ recv_frag callbacks) ---------------------

    def _am_handler(self, src: int, header: Dict[str, Any], payload: bytes) -> None:
        k = header["k"]
        if k in ("match", "rndv"):
            self.matching.arrived(header["cid"], src, header["tag"],
                                  header["seq"], k, header, payload)
        elif k == "ack":
            state = self._pending_send.pop(header["sreq"])
            if header["rreq"] < 0:   # receiver matched but discarded (truncate)
                state.req.complete()
            else:
                self._stream_frags(src, header["rreq"], state)
        elif k == "frag":
            state = self._pending_recv[header["rreq"]]
            state.conv.set_position(header["off"])
            state.conv.unpack(payload)
            state.received += len(payload)
            if state.received >= state.total:
                del self._pending_recv[header["rreq"]]
                state.req.complete()
        else:
            raise RuntimeError(f"unknown p2p frame kind {k!r}")

    def _stream_frags(self, dst: int, rreq: int, state: _SendState) -> None:
        transport = self.layer.for_peer(dst)
        chunk = transport.max_send_size
        data = state.data
        if not data:
            state.req.complete()
            return
        for off in range(0, len(data), chunk):
            transport.send(dst, T.AM_P2P,
                           {"k": "frag", "rreq": rreq, "off": off},
                           data[off:off + chunk])
        state.req.complete()   # sender side done once handed to transport
