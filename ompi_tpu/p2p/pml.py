"""Point-to-point protocol engine (≙ pml/ob1, ompi/mca/pml/ob1/).

Implements MPI send/recv semantics over the byte transports:
  * eager protocol for payloads ≤ the transport's eager_limit — one MATCH
    frame, sender completes locally (pml_ob1_isend.c:249,297 send_inline
    fast path);
  * rendezvous for large payloads — RNDV header, receiver matches and ACKs,
    sender streams FRAGs of max_send_size (wire protocol kinds mirror
    pml_ob1_hdr.h:43-52 MATCH/RNDV/ACK/FRAG);
  * matching with wildcards + per-channel sequence numbers (matching.py);
  * ``sync=True`` forces rendezvous regardless of size — MPI_Ssend semantics
    (completion implies the receive was matched).

Payloads are packed/unpacked through the datatype convertor; contiguous
numpy buffers take the single-copy fast path. Device (jax) arrays are
detected through the accelerator framework (``accelerator.check_addr``,
≙ accelerator.h:171 — not an implicit np.asarray) and staged explicitly:
sends pack on device where the datatype allows (XLA gather) then D2H in
bounded async chunks; receives land in a host staging buffer and are
uploaded once complete. Receiving *into* a device destination uses
``accelerator.DeviceBuffer`` (jax arrays are immutable); the received array
also lands on ``request.result``. The ICI path for bulk device data
remains the coll/xla component (SURVEY.md §5.8) — p2p staging is for the
control-scale messages MPI apps send between device computations
(≙ pml_ob1_accelerator.c's role).
"""

from __future__ import annotations

import itertools
from collections import defaultdict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import accelerator as _accel
from ..core import var as _var
from ..core.output import show_help
from ..core.progress import ProgressEngine
from ..datatype import Convertor, Datatype, from_numpy
from . import transport as T
from .matching import MatchingEngine, Unexpected
from .request import ANY_SOURCE, ANY_TAG, Request
from .. import peruse


class TruncateError(RuntimeError):
    pass


def _guarded(fn):
    """Serialize a pml entry point against the async progress thread when
    runtime_async_progress is on (engine.guard set); free when off — the
    default FUNNELED contract stays unlocked."""
    import functools

    @functools.wraps(fn)
    def wrapped(self, *a, **kw):
        g = self._g
        if g is None:
            return fn(self, *a, **kw)
        with g:
            return fn(self, *a, **kw)

    return wrapped


_var.register("bml", "r2", "striping", "auto", type=str, level=4,
              help="Stripe rendezvous fragment trains across every "
                   "transport that reaches the peer, weighted by bandwidth "
                   "class (bml.h:57-72 scheduling; failed paths retire and "
                   "their ranges replay on survivors either way). "
                   "auto = stripe only with >1 usable CPU: on a 1-core "
                   "host the paths serialize and striping measurably "
                   "loses (BASELINE.md); 1/0 force it on/off.")


def _striping_on() -> bool:
    v = str(_var.get("bml_r2_striping", "auto")).lower()
    if v in ("1", "true", "yes", "on"):
        return True
    if v in ("0", "false", "no", "off"):
        return False
    import os
    try:
        return len(os.sched_getaffinity(0)) > 1
    except (AttributeError, OSError):
        return (os.cpu_count() or 1) > 1
_var.register("smsc", "", "enabled", True, type=bool, level=4,
              help="Allow CMA single-copy rendezvous over shared memory "
                   "(≙ the smsc/cma component; disable to force the "
                   "fragment protocol).")


def _capacity_count(nbytes: int, dt: Datatype) -> int:
    """How many datatype elements fit in nbytes — extent-aware: element i
    occupies [i*extent, i*extent + span) where span is the used byte range
    (≙ opal_datatype true extent accounting). Using size here would
    overcount strided types and let the convertor write past the buffer."""
    if not dt.size:
        return 0
    if dt.is_contiguous:
        return nbytes // dt.size
    span = max(s.offset + s.nbytes for s in dt.segments)
    n = nbytes // dt.extent
    if nbytes - n * dt.extent >= span:
        n += 1
    return n


def _buffer_args(buf, datatype: Optional[Datatype], count: Optional[int]
                 ) -> Tuple[np.ndarray, Datatype, int]:
    arr = np.asarray(buf)
    if datatype is None:
        datatype = from_numpy(arr.dtype)
        if count is None:
            count = arr.size
    elif count is None:
        count = _capacity_count(arr.nbytes, datatype)
    return arr, datatype, count


class _SendState:
    __slots__ = ("req", "data", "dst", "offset", "keep")

    def __init__(self, req: Request, data: Optional[bytes], dst: int,
                 keep=None) -> None:
        self.req = req
        self.data = data      # packed bytes; None for CMA-exposed sends
        self.dst = dst
        self.offset = 0
        self.keep = keep      # pins the user array while CMA-readable


class _RecvState:
    __slots__ = ("req", "conv", "received", "total", "finish", "sink_buf",
                 "native_sink", "_ivals", "src")

    def __init__(self, req: Request, conv, total: int,
                 finish=None, src: int = -1) -> None:
        self.req = req
        self.conv = conv
        self.received = 0
        self.total = total
        self.finish = finish     # device staging upload, run at completion
        self.sink_buf = None     # contiguous target for the native frag sink
        self.native_sink = False
        self._ivals: list = []   # merged covered [start, end) intervals
        self.src = src           # streaming peer (ULFM mid-train failure)

    def cover(self, off: int, n: int) -> None:
        """Merge [off, off+n) into coverage; striping failover may replay
        fragments, so DEDUPLICATED coverage — not byte count — defines
        completion (≙ the reference's per-request range accounting)."""
        start, end = off, off + n
        merged = []
        for a, b in self._ivals:
            if b < start or a > end:
                merged.append((a, b))
            else:
                start, end = min(a, start), max(b, end)
        merged.append((start, end))
        merged.sort()
        self._ivals = merged
        self.received = sum(b - a for a, b in merged)


class _PackedSink:
    """Convertor-shaped accumulator for device receives: frags land in a
    host bytearray; the single H2D + device scatter happens at completion
    (pml device path, ≙ pml_ob1_accelerator.c staging protocol)."""

    def __init__(self, total: int) -> None:
        self.data = bytearray(total)
        self.position = 0

    def set_position(self, position: int) -> None:
        self.position = position

    def unpack(self, payload: bytes) -> int:
        self.data[self.position:self.position + len(payload)] = payload
        self.position += len(payload)
        return len(payload)


class Message:
    """A matched message handle (≙ ompi/message/message.h: MPI_Message).
    Holds the dequeued Unexpected until mrecv/imrecv consumes it exactly
    once."""

    __slots__ = ("status", "_u")

    def __init__(self, u: Unexpected) -> None:
        self._u = u
        self.status = {"source": u.src, "tag": u.tag,
                       "count": u.header["size"]}

    def consume(self) -> Unexpected:
        if self._u is None:
            raise RuntimeError("message already received (MPI_MESSAGE_NULL)")
        u, self._u = self._u, None
        return u


class P2P:
    """One instance per rank process."""

    def __init__(self, bootstrap, layer: T.TransportLayer,
                 engine: ProgressEngine, spc=None) -> None:
        from ..spc import Counters

        self.bootstrap = bootstrap
        self.rank = bootstrap.rank
        self.layer = layer
        self.engine = engine
        self.spc = spc if spc is not None else Counters()
        self.matching = MatchingEngine()
        self.matching.spc = self.spc
        self._g = engine.guard          # async-progress serialization
        self._send_seq: Dict[Tuple[int, int], int] = defaultdict(int)
        self._sreq = itertools.count(1)
        self._rreq = itertools.count(1)
        self._pending_send: Dict[int, _SendState] = {}
        self._pending_recv: Dict[int, _RecvState] = {}
        # comms with an attached device mesh (parallel.attach_mesh) — their
        # device payloads ride the ICI channel instead of staging
        self.device_cids: set = set()
        from . import devchan
        devchan.register(bootstrap.job_id, self.rank)
        for t in layer.transports:
            t.dispatch[T.AM_P2P] = self._am_handler
            engine.register(t.progress)

    def finalize(self) -> None:
        from . import devchan
        devchan.unregister(self.bootstrap.job_id, self.rank)

    @_guarded
    def fail_peer(self, peer: int, err: Exception) -> None:
        """ULFM: complete every IN-FLIGHT operation whose remote end is the
        failed rank — rendezvous sends awaiting ACK/FIN and mid-train
        fragment receives — so nothing blocks on a corpse. Complements
        matching.fail_src, which covers only still-POSTED receives
        (≙ the reference failing active requests from
        comm_ft_detector.c's error propagation)."""
        for sreq, state in list(self._pending_send.items()):
            if state.dst == peer:
                del self._pending_send[sreq]
                state.req.complete(err)
        for rreq, state in list(self._pending_recv.items()):
            if state.src == peer:
                del self._pending_recv[rreq]
                self._unregister_sink(rreq, state)
                state.req.complete(err)

    def _unregister_sink(self, rreq: int, state: "_RecvState") -> None:
        """Hook: the native pml detaches the C++ fragment sink so late
        ring frames from the corpse can't memcpy into a buffer the
        application reclaimed after seeing the error."""

    # -- send ---------------------------------------------------------------

    @_guarded
    def isend(self, buf, dst: int, tag: int = 0, cid: int = 0,
              datatype: Optional[Datatype] = None, count: Optional[int] = None,
              sync: bool = False) -> Request:
        info = _accel.check_addr(buf)
        raw = None            # contiguous host array: CMA single-copy donor
        if info is not None and cid in self.device_cids \
                and datatype is None and count is None and not sync:
            from . import devchan
            if devchan.same_process(self.bootstrap.job_id, dst):
                # ICI device channel: the payload never leaves HBM — park
                # the immutable array, ship a header-only match (≙ the
                # device-direct btl/smcuda path; staging below remains the
                # cross-process fallback, ≙ pml_ob1_accelerator.c)
                arr = buf.array if isinstance(buf, _accel.DeviceBuffer) \
                    else buf
                seq = self._send_seq[(cid, dst)]
                self._send_seq[(cid, dst)] = seq + 1
                devchan.offer(self.bootstrap.job_id, cid, self.rank, dst,
                              seq, arr)
                req = Request()
                req._ctx = self      # owner attribution (health registry)
                req.status.source = self.rank
                req.status.tag = tag
                req.status.count = info.nbytes
                if peruse.active:
                    peruse.fire(peruse.REQ_ACTIVATE, kind="send", peer=dst,
                                tag=tag, cid=cid, nbytes=info.nbytes)
                # rides as an EXTENDED RNDV header (like cma): the native
                # engine preserves those losslessly via its token path,
                # where plain-match headers are reconstructed from the wire
                # struct and would drop the flag
                self.layer.for_peer(dst).send(
                    dst, T.AM_P2P,
                    {"k": "rndv", "cid": cid, "tag": tag, "seq": seq,
                     "size": info.nbytes, "sreq": 0, "dev": 1}, b"")
                req.complete()   # array is immutable: complete at park time
                self.spc.inc("isends")
                self.spc.inc("bytes_sent", info.nbytes)  # tx/rx invariant
                self.spc.inc("device_channel_msgs")
                self.spc.inc("device_channel_bytes", info.nbytes)
                self.spc.peer_traffic("tx", dst, info.nbytes)
                return req
        if info is not None:   # explicit device staging, never np.asarray
            if datatype is not None and count is None:
                count = _capacity_count(info.nbytes, datatype)
            data = _accel.current().stage_out(buf, datatype, count)
            self.spc.inc("device_stage_out_bytes", len(data))
        else:
            arr, dt, cnt = _buffer_args(buf, datatype, count)
            if cnt and dt.is_contiguous and arr.flags["C_CONTIGUOUS"] \
                    and dt.size * cnt == arr.nbytes:
                raw = arr      # pack lazily; rendezvous may never copy it
                data = None
            else:
                data = Convertor(arr, dt, cnt).pack() if cnt else b""
        req = Request()
        req._ctx = self              # owner attribution (health registry)
        nbytes = raw.nbytes if raw is not None else len(data)
        req.status.source = self.rank
        req.status.tag = tag
        req.status.count = nbytes
        seq = self._send_seq[(cid, dst)]
        self._send_seq[(cid, dst)] = seq + 1
        transport = self.layer.for_peer(dst)
        if peruse.active:           # ≙ PERUSE_COMM_REQ_ACTIVATE from isend
            peruse.fire(peruse.REQ_ACTIVATE, kind="send", peer=dst,
                        tag=tag, cid=cid, nbytes=nbytes)
        self.spc.inc("isends")
        self.spc.inc("bytes_sent", nbytes)
        self.spc.peer_traffic("tx", dst, nbytes)
        if not sync and nbytes <= transport.eager_limit:
            self.spc.inc("eager_sends")
            hdr = {"k": "match", "cid": cid, "tag": tag, "seq": seq,
                   "size": nbytes}
            transport.send(dst, T.AM_P2P, hdr,
                           raw.tobytes() if raw is not None else data)
            req.complete()   # eager: locally complete once buffered
            return req
        self.spc.inc("rndv_sends")
        sreq = next(self._sreq)
        self._pending_send[sreq] = _SendState(req, data, dst, keep=raw)
        hdr = {"k": "rndv", "cid": cid, "tag": tag, "seq": seq,
               "size": nbytes, "sreq": sreq}
        if raw is not None and transport.name == "shm" and self._cma_ok():
            # single-copy rendezvous (≙ smsc/cma): advertise the user
            # buffer; the receiver pulls it with process_vm_readv and FINs.
            # MPI already forbids touching the buffer until completion, so
            # exposing it until FIN adds no new aliasing.
            import os as _os
            hdr["cma"] = (_os.getpid(), int(raw.ctypes.data))
        transport.send(dst, T.AM_P2P, hdr, b"")
        return req

    def _cma_ok(self) -> bool:
        ok = getattr(self, "_cma_usable", None)
        if ok is None:
            from .. import native
            ok = self._cma_usable = bool(
                _var.get("smsc_enabled", True) and native.cma_usable())
        return ok

    def send(self, buf, dst: int, tag: int = 0, cid: int = 0,
             datatype: Optional[Datatype] = None, count: Optional[int] = None,
             sync: bool = False) -> None:
        self.spc.inc("sends")
        self.isend(buf, dst, tag, cid, datatype, count, sync).wait()

    # -- recv ---------------------------------------------------------------

    @_guarded
    def irecv(self, buf, src: int = ANY_SOURCE, tag: int = ANY_TAG,
              cid: int = 0, datatype: Optional[Datatype] = None,
              count: Optional[int] = None) -> Request:
        req, on_match, _ = self._recv_handler(buf, datatype, count)
        if peruse.active:
            peruse.fire(peruse.REQ_ACTIVATE, kind="recv", peer=src,
                        tag=tag, cid=cid)
        posted = self.matching.post_recv(cid, src, tag, on_match, req=req)
        if posted is None:
            self.spc.inc("matches_unexpected")
        else:
            req._posted_ref = (self.matching, cid, posted)
        return req

    def _recv_handler(self, buf, datatype: Optional[Datatype],
                      count: Optional[int]):
        """(request, on_match, info) triple shared by irecv and imrecv —
        everything that happens once a message matches this receive.
        ``info`` = (arr, dt, cnt, dinfo) so the native pml can decide
        direct-buffer eligibility without re-deriving it."""
        dinfo = _accel.check_addr(buf)
        if dinfo is not None:
            # device destination: stage packed stream on host, upload once
            template = buf.array if isinstance(buf, _accel.DeviceBuffer) else buf
            dt = datatype if datatype is not None else from_numpy(dinfo.dtype)
            cnt = count if count is not None else (
                template.size if datatype is None
                else _capacity_count(dinfo.nbytes, dt))
            arr = None
        else:
            arr, dt, cnt = _buffer_args(buf, datatype, count)
        req = Request()
        req._ctx = self              # owner attribution (health registry)
        self.spc.inc("recvs")

        def deliver(data: bytes) -> None:
            result = _accel.current().stage_in(data, template, dt, cnt)
            if isinstance(buf, _accel.DeviceBuffer):
                buf.array = result
            req.result = result
            self.spc.inc("device_stage_in_bytes", len(data))

        def on_match(u: Unexpected) -> None:
            self.spc.inc("bytes_recvd", u.header["size"])
            self.spc.peer_traffic("rx", u.src, u.header["size"])
            capacity = dt.size * cnt
            req.status.source = u.src
            req.status.tag = u.tag
            if u.header.get("dev"):
                # ICI device channel: claim the parked HBM array — no wire
                # payload, no ACK (the sender completed at park time; its
                # sreq is a placeholder). Truncation completes in error
                # without the rndv NACK.
                from . import devchan
                darr = devchan.take(self.bootstrap.job_id, u.header["cid"],
                                    u.src, self.rank, u.header["seq"])
                if darr is None:
                    req.complete(RuntimeError(
                        "device-channel message lost: sender finalized "
                        "before the receive matched"))
                    return
                if u.header["size"] > capacity:
                    show_help.show("truncate", capacity, u.header["size"],
                                   u.tag, u.src)
                    req.complete(TruncateError(
                        f"recv buffer {capacity}B < device message "
                        f"{u.header['size']}B"))
                    return
                if dinfo is not None:
                    result, staged = devchan.deliver(darr, template)
                    if staged:
                        # shape/dtype-mismatched delivery reproduced the
                        # staged fill-front semantics via host — account it
                        self.spc.inc("device_stage_in_bytes", staged)
                    if isinstance(buf, _accel.DeviceBuffer):
                        buf.array = result
                    req.result = result
                else:
                    # receiver posted a host buffer: ONE explicit D2H (the
                    # asarray); unpack reads the view without re-copying
                    hostv = np.asarray(darr).reshape(-1).view(np.uint8)
                    Convertor(arr, dt, cnt).unpack(hostv)
                    self.spc.inc("device_stage_in_bytes", len(hostv))
                self.spc.inc("device_channel_msgs")
                req.status.count = u.header["size"]
                req.complete()
                return
            if u.header["size"] > capacity:
                show_help.show("truncate", capacity, u.header["size"],
                               u.tag, u.src)
                if u.kind == "rndv":
                    # NACK (rreq < 0) so the sender's request still completes
                    # — truncation is a receiver-side error in MPI
                    self.layer.send(u.src, T.AM_P2P,
                                    {"k": "ack", "sreq": u.header["sreq"],
                                     "rreq": -1}, b"")
                req.complete(TruncateError(
                    f"recv buffer {capacity}B < message {u.header['size']}B"))
                return
            if u.kind == "match":
                if dinfo is not None:
                    deliver(u.payload)
                elif u.payload:
                    Convertor(arr, dt, cnt).unpack(u.payload)
                req.status.count = len(u.payload)
                req.complete()
            else:  # rendezvous
                # single-copy fast path (≙ smsc/cma): pull the sender's
                # buffer directly, FIN instead of ACK+FRAGs
                cma = u.header.get("cma")
                if cma is not None and dinfo is None and dt.is_contiguous \
                        and arr.flags["C_CONTIGUOUS"] \
                        and u.header["size"] <= arr.nbytes \
                        and self._cma_pull(cma, arr, u.header["size"]):
                    req.status.count = u.header["size"]
                    self.layer.send(u.src, T.AM_P2P,
                                    {"k": "fin", "sreq": u.header["sreq"]},
                                    b"")
                    req.complete()
                    return
                # fragment path: ACK with a recv-request id, collect FRAGs
                rreq = next(self._rreq)
                if dinfo is not None:
                    sink = _PackedSink(u.header["size"])
                    state = _RecvState(req, sink, u.header["size"],
                                       finish=lambda: deliver(bytes(sink.data)),
                                       src=u.src)
                    state.sink_buf = sink.data       # native-sink candidate
                else:
                    state = _RecvState(req, Convertor(arr, dt, cnt),
                                       u.header["size"], src=u.src)
                    if dt.is_contiguous and arr.flags["C_CONTIGUOUS"]:
                        state.sink_buf = arr         # native-sink candidate
                self._pending_recv[rreq] = state
                self._register_sink(rreq, state, u.src)
                req.status.count = u.header["size"]
                if u.header["size"] == 0:
                    del self._pending_recv[rreq]
                    if state.finish is not None:
                        state.finish()
                    req.complete()
                    # still ACK so the sender's request completes
                self.layer.send(u.src, T.AM_P2P,
                                {"k": "ack", "sreq": u.header["sreq"],
                                 "rreq": rreq}, b"")

        return req, on_match, (arr if dinfo is None else None,
                               dt, cnt, dinfo)

    def _register_sink(self, rreq: int, state: "_RecvState",
                       src: int) -> None:
        """Hook: the native pml registers contiguous fragment sinks with the
        C++ engine here so frag payloads land by memcpy without Python."""

    # -- matched probe (≙ MPI_Mprobe/Mrecv, ompi/message/) ------------------

    @_guarded
    def improbe(self, src: int = ANY_SOURCE, tag: int = ANY_TAG,
                cid: int = 0) -> Optional["Message"]:
        """Match-and-dequeue: the returned Message can no longer match any
        other receive on this rank (MPI_Improbe)."""
        self.spc.inc("probes")
        self.engine.progress()
        u = self.matching.probe(cid, src, tag, remove=True)
        return None if u is None else Message(u)

    def mprobe(self, src: int = ANY_SOURCE, tag: int = ANY_TAG,
               cid: int = 0, timeout: Optional[float] = None) -> "Message":
        box: list = []

        def check() -> bool:
            m = self.improbe(src, tag, cid)
            if m is not None:
                box.append(m)
                return True
            return False

        self.engine.wait_until(check, timeout=timeout)
        if not box:
            raise TimeoutError("mprobe: no matching message")
        return box[0]

    @_guarded
    def imrecv(self, msg: "Message", buf,
               datatype: Optional[Datatype] = None,
               count: Optional[int] = None) -> Request:
        """Receive the matched message into ``buf`` (MPI_Imrecv)."""
        u = msg.consume()
        req, on_match, _ = self._recv_handler(buf, datatype, count)
        on_match(u)
        return req

    def mrecv(self, msg: "Message", buf,
              datatype: Optional[Datatype] = None,
              count: Optional[int] = None):
        return self.imrecv(msg, buf, datatype, count).wait()

    @_guarded
    def cancel_recv(self, req: Request) -> bool:
        """Withdraw a still-posted receive (MPI_Cancel for recvs; used by
        blocking ANY_SOURCE recv to avoid leaking a zombie post when it
        converts PROC_FAILED_PENDING to fail-stop)."""
        ref = req._posted_ref
        if ref is None or req.done:
            return False
        matching, cid, posted = ref
        ok = matching.cancel(cid, posted)
        if ok:
            req.status.cancelled = True
        return ok

    def recv(self, buf, src: int = ANY_SOURCE, tag: int = ANY_TAG, cid: int = 0,
             datatype: Optional[Datatype] = None, count: Optional[int] = None):
        if _accel.check_addr(buf) is not None and \
                not isinstance(buf, _accel.DeviceBuffer):
            # a raw jax array can't be written through (immutable) and
            # blocking recv discards the request that carries the result
            raise TypeError(
                "recv into a device array requires accelerator.DeviceBuffer "
                "(jax arrays are immutable); or use irecv and read "
                "request.result")
        return self.irecv(buf, src, tag, cid, datatype, count).wait()

    def sendrecv(self, sendbuf, dst: int, recvbuf, src: int,
                 sendtag: int = 0, recvtag: int = ANY_TAG, cid: int = 0):
        rreq = self.irecv(recvbuf, src, recvtag, cid)
        sreq = self.isend(sendbuf, dst, sendtag, cid)
        status = rreq.wait()
        sreq.wait()
        return status

    # -- probe --------------------------------------------------------------

    @_guarded
    def iprobe(self, src: int = ANY_SOURCE, tag: int = ANY_TAG, cid: int = 0):
        self.spc.inc("probes")
        self.engine.progress()
        u = self.matching.probe(cid, src, tag)
        if u is None:
            return None
        st = {"source": u.src, "tag": u.tag, "count": u.header["size"]}
        return st

    def probe(self, src: int = ANY_SOURCE, tag: int = ANY_TAG, cid: int = 0,
              timeout: Optional[float] = None):
        result = {}

        def check() -> bool:
            r = self.iprobe(src, tag, cid)
            if r:
                result.update(r)
                return True
            return False

        self.engine.wait_until(check, timeout=timeout)
        return result or None

    # -- active-message handler (≙ recv_frag callbacks) ---------------------

    def _am_handler(self, src: int, header: Dict[str, Any], payload: bytes) -> None:
        k = header["k"]
        if k in ("match", "rndv"):
            self.matching.arrived(header["cid"], src, header["tag"],
                                  header["seq"], k, header, payload)
        elif k == "ack":
            self._handle_ack(src, header["sreq"], header["rreq"])
        elif k == "fin":
            self._handle_fin(header["sreq"])
        elif k == "frag":
            self._handle_frag(header["rreq"], header["off"], payload)
        else:
            raise RuntimeError(f"unknown p2p frame kind {k!r}")

    # split out so the native pml's drained events reuse the exact protocol
    def _handle_ack(self, src: int, sreq: int, rreq: int) -> None:
        state = self._pending_send.pop(sreq, None)
        if state is None:
            # fail_peer already errored this send (the peer died after
            # acking): a late in-flight ACK must not crash the survivor
            return
        if rreq < 0:             # receiver matched but discarded (truncate)
            state.req.complete()
        else:
            self._stream_frags(src, rreq, state)

    def _handle_fin(self, sreq: int) -> None:
        """CMA single-copy done: nothing to stream."""
        state = self._pending_send.pop(sreq, None)
        if state is None:
            return               # errored by fail_peer; late FIN is benign
        state.keep = None
        state.req.complete()

    def _handle_frag(self, rreq: int, off: int, payload: bytes) -> None:
        state = self._pending_recv.get(rreq)
        if state is None:
            return               # late duplicate after completion (failover)
        state.conv.set_position(off)
        state.conv.unpack(payload)
        state.cover(off, len(payload))
        if state.received >= state.total:
            del self._pending_recv[rreq]
            if state.finish is not None:
                state.finish()
            state.req.complete()

    def _cma_pull(self, cma, arr: np.ndarray, size: int) -> bool:
        """Read the sender's exposed buffer via process_vm_readv; False
        falls back to the fragment protocol."""
        import ctypes

        from .. import native
        lib = native.load()
        if lib is None:
            return False
        if getattr(self, "_cma_recv_off", False):
            return False
        pid, addr = int(cma[0]), int(cma[1])
        dest = arr.reshape(-1).view(np.uint8)
        got = lib.cma_read(
            pid, addr,
            dest.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), size)
        if got == size:
            # (bytes_recvd/peer matrix already counted by on_match)
            self.spc.inc("cma_single_copies")
            return True
        import errno
        if got == -errno.EPERM:
            # ptrace policy forbids sibling reads here: latch off so later
            # messages skip the doomed syscall and go straight to frags
            self._cma_recv_off = True
        return False

    def _stream_frags(self, dst: int, rreq: int, state: _SendState) -> None:
        if state.data is None and state.keep is not None:
            state.data = state.keep.tobytes()   # CMA declined: pack now
        data = state.data
        if not data:
            state.req.complete()
            return
        # striping + failover (≙ bml/r2, bml.h:57-72): the fragment train
        # splits across every transport that reaches the peer, weighted by
        # bandwidth class; a transport error retires that path and its
        # range replays on a survivor (fragment replay is idempotent — the
        # receiver tracks covered intervals)
        primary = self.layer.for_peer(dst)
        paths = self.layer.paths_for_peer(dst) if _striping_on() \
            else [primary]
        plan = self._stripe_plan(len(data), paths, primary)
        self._run_with_failover(
            dst, state, plan,
            lambda t, base, n: self._send_range(dst, rreq, data, base, n,
                                                t))

    def _run_with_failover(self, dst: int, state: _SendState, plan,
                           send_range) -> None:
        """Execute a stripe plan with r2 failover: a failed range retires
        its transport and replays (idempotently) on the best survivor;
        no survivors → the send request carries the error. Shared by the
        python and native pmls — ONE copy of the retry policy."""
        work = list(plan)
        while work:
            t, base, n = work.pop(0)
            try:
                send_range(t, base, n)
                t.confirm(dst)    # surface async transport errors NOW
            except Exception as exc:
                self.layer.mark_failed(dst, t)
                survivors = self.layer.paths_for_peer(dst)
                if not survivors:
                    state.req.complete(exc)
                    return
                work.append((survivors[0], base, n))
        state.req.complete()   # sender side done once handed to transport

    def _stripe_plan(self, nbytes: int, paths, primary):
        """[(transport, base, length)] — contiguous ranges by bandwidth
        weight; short messages stay on the primary."""
        if len(paths) < 2 or nbytes < 4 * primary.max_send_size:
            return [(primary, 0, nbytes)]
        total_bw = sum(t.bandwidth for t in paths)
        plan, base = [], 0
        for i, t in enumerate(paths):
            if i == len(paths) - 1:
                share = nbytes - base
            else:
                share = (nbytes * t.bandwidth // total_bw) & ~0xFFF
            if share > 0:
                plan.append((t, base, share))
                base += share
        return plan

    def _send_range(self, dst: int, rreq: int, data, base: int, n: int,
                    transport, off_base: int = 0) -> None:
        """Stream one chunked range; ``off_base`` rebases receiver-side
        offsets when ``data`` is a copied sub-range of the message."""
        chunk = transport.max_send_size
        for off in range(base, base + n, chunk):
            m = min(chunk, base + n - off)
            transport.send(dst, T.AM_P2P,
                           {"k": "frag", "rreq": rreq,
                            "off": off_base + off},
                           data[off:off + m])
