"""Device-payload p2p channel — the ICI path for send/recv of HBM arrays.

≙ the role split of the reference's GPU p2p: a device-direct transport when
both endpoints share a fabric (opal/mca/btl/smcuda/btl_smcuda.c — GPU-IPC
transfers that never touch host) with host staging as the universal
fallback (ompi/mca/pml/ob1/pml_ob1_accelerator.c). Here the "fabric" is
the JAX runtime itself:

* **In-process ranks** (threaded run_ranks, single-controller drivers): the
  sender parks its immutable jax array in a process-local exchange table;
  the receiver claims it at match time and, if its posted template lives
  under a different sharding, moves it with ``jax.device_put`` — a PJRT
  buffer-to-buffer copy (D2D on real hardware), never a host round trip.
  Eligibility is advertised per (job, rank) at pml init, so a sender knows
  locally whether the destination shares its process.

* **Cross-process ranks**: the table misses at send time and the pml keeps
  the explicit staged path (stage_out → wire → stage_in), exactly the
  reference's accelerator-staging protocol. Rank-per-chip SPMD programs
  move rows with ``DeviceComm.push_row`` (one-hop collective-permute)
  instead of two-sided sends — the compilation-space shape of this
  channel (SURVEY.md §7 phase 4c).

The table holds strong references only between isend and the matching
recv; entries are keyed by the same (cid, src, dst, seq) tuple the
matching engine orders on, so MPI non-overtaking holds automatically.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

_lock = threading.Lock()
_procs: Dict[Tuple[str, int], int] = {}       # (job_id, rank) → pid/thread
_table: Dict[Tuple[str, int, int, int, int], Any] = {}


def register(job_id: str, rank: int) -> None:
    with _lock:
        _procs[(job_id, rank)] = 1


def unregister(job_id: str, rank: int) -> None:
    with _lock:
        _procs.pop((job_id, rank), None)
        stale = [k for k in _table if k[0] == job_id
                 and (k[2] == rank or k[3] == rank)]
        for k in stale:
            del _table[k]


def same_process(job_id: str, rank: int) -> bool:
    """True when ``rank`` of this job runs in this OS process (its pml
    registered here) — the eligibility gate for the in-process D2D hop."""
    return (job_id, rank) in _procs


def offer(job_id: str, cid: int, src: int, dst: int, seq: int,
          arr: Any) -> None:
    with _lock:
        _table[(job_id, cid, src, dst, seq)] = arr


def take(job_id: str, cid: int, src: int, dst: int,
         seq: int) -> Optional[Any]:
    with _lock:
        return _table.pop((job_id, cid, src, dst, seq), None)


def deliver(arr, template) -> Any:
    """Land a claimed device array on the receiver's side with the SAME
    result contract as the staged path (stage_in): the posted template's
    shape/dtype survive.

    Fast path — template matches the payload's shape and dtype (the normal
    case: receivers post like-shaped buffers): the immutable array is the
    result as-is, resharded with one PJRT copy only if the template pins a
    different sharding. Zero host transfers.

    Slow path — shape/dtype mismatch: reproduce stage_in's fill-front byte
    semantics exactly (front of the template overwritten by the payload
    bytes, tail preserved, dtype reinterpreted) via one host round trip.
    Returns (result, staged_bytes) where staged_bytes > 0 only on the slow
    path so the caller can account it."""
    import jax

    if template is None:
        return arr, 0
    t_shape = getattr(template, "shape", None)
    t_dtype = getattr(template, "dtype", None)
    if t_shape == arr.shape and t_dtype == arr.dtype:
        tgt = getattr(template, "sharding", None)
        if tgt is None or tgt == getattr(arr, "sharding", None):
            return arr, 0
        return jax.device_put(arr, tgt), 0
    import jax.numpy as jnp
    import numpy as np

    data = np.asarray(jax.device_get(arr)).reshape(-1).view(np.uint8)
    tmpl = np.array(jax.device_get(template))      # writable host copy
    flat = tmpl.reshape(-1).view(np.uint8)
    n = min(len(data), len(flat))
    flat[:n] = data[:n]
    return jnp.asarray(tmpl), len(data)
