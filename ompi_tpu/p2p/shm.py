"""Shared-memory transport over native SPSC rings (≙ opal/mca/btl/sm).

The reference's fastest intra-node byte transport is the shared-memory BTL:
per-peer mmap'd segments with lock-free "fast box" mailboxes
(btl_sm_fbox.h:31-35). Here the ring machinery is native C++
(native/shmbox.cpp) and this component owns the lifecycle:

  * at init each rank *creates* one directed ring per peer for its inbound
    side (peer→me) and publishes its host identity through the modex;
  * senders lazily open the (me→peer) ring after the startup fence;
  * per-channel FIFO gives the non-overtaking order p2p relies on;
  * a full ring parks frames on a pending queue flushed from progress() —
    ordering is preserved because new sends append behind pending ones.

Selection: priority 50 — above tcp (10) for same-host peers, below self
(100) for loopback. ``open()`` disqualifies the component when the native
library can't be built, the same way reference components disqualify
themselves in query (e.g. no /dev/shm → btl/sm out).
"""

from __future__ import annotations

import ctypes
import os
import socket
from collections import deque
from typing import Any, Dict, Optional

from .. import native
from ..core import var as _var
from ..core.component import component
from . import transport as T
from . import wire

_var.register("transport", "shm", "ring_size", 1 << 22, type=int, level=4,
              help="Bytes per directed shared-memory ring channel. 4 MiB "
                   "default: the fragment path then moves 1 MiB chunks "
                   "with few drain handoffs (bandwidth sweep, BASELINE.md).")


def _host_key() -> str:
    """Shared-memory host identity: hostname ALONE merges distinct
    containers/VMs that default to the same name (e.g. 'localhost'), so
    qualify with the kernel boot id — equal only for processes under one
    kernel, i.e. exactly the processes that can share /dev/shm."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as fh:
            boot = fh.read().strip()
    except OSError:
        boot = ""
    return f"{socket.gethostname()}#{boot}"


def _chan_name(job: str, src: int, dst: int) -> bytes:
    safe = "".join(c for c in str(job) if c.isalnum())[-24:]
    return f"/otpu_{safe}_{src}to{dst}".encode()


def _bell_name(job: str, rank: int) -> bytes:
    safe = "".join(c for c in str(job) if c.isalnum())[-24:]
    return f"/otpu_{safe}_bell{rank}".encode()


@component("transport", "shm", priority=50)
class ShmTransport(T.Transport):
    name = "shm"
    bandwidth = 100          # striping weight (measured ~3 GB/s class)

    def __init__(self) -> None:
        super().__init__()
        self.rank = -1
        self.size = 0
        self._bootstrap = None
        self._lib = None
        self._rx: Dict[int, int] = {}        # peer → handle (peer→me ring)
        self._tx: Dict[int, int] = {}        # peer → handle (me→peer ring)
        self._pending: Dict[int, deque] = {}  # peer → frames awaiting space
        self._hosts: Dict[int, Optional[str]] = {}
        self._ring = int(_var.get("transport_shm_ring_size", 1 << 22))
        self._bell = -1
        self._tx_bells: Dict[int, int] = {}
        # cap fragments so one frame can never exceed half a ring
        self.max_send_size = min(self.max_send_size, self._ring // 4)
        # reusable rx frame buffer sized to the ring: payloads are capped at
        # max_send_size but pickled control headers (osc/ft dict headers)
        # are unbounded, and any frame the writer accepted fits the ring —
        # so ring-sized is the provably-sufficient choice
        self._rxbuf = (ctypes.c_uint8 * self._ring)()
        # cast: a raw ctypes-array view carries format '<B', which
        # memoryview refuses to index/slice-read; 'B' is the plain bytes view
        self._rxview = memoryview(self._rxbuf).cast("B")
        self._rxbody = ctypes.c_uint32(0)
        # native-engine adoption (p2p/pmlx.py): when set, the C++ mx engine
        # owns this transport's rings — send() routes frames through the
        # engine's per-peer FIFO and progress() defers to mx_progress
        self._mx = None                       # (lib, engine handle)
        self._mx_tx_wired: set = set()

    def open(self) -> bool:
        return native.available()

    def init_job(self, bootstrap) -> None:
        self._lib = native.load()
        self.rank, self.size = bootstrap.rank, bootstrap.size
        self._bootstrap = bootstrap
        bootstrap.put("transport_shm_host", _host_key())
        for peer in range(self.size):
            if peer == self.rank:
                continue
            h = self._lib.shmbox_attach(
                _chan_name(bootstrap.job_id, peer, self.rank), self._ring, 1)
            if h < 0:
                # a create-attach can only fail for environmental reasons
                # (/dev/shm exhausted, name collision) — failing init is the
                # clean outcome; silently skipping would let senders crash
                # later and would falsify the ring-ready key's guarantee
                raise RuntimeError(
                    f"shm transport: cannot create rx ring from rank {peer}")
            self._rx[peer] = h
        # our doorbell: senders post it after writing into an empty ring so
        # an idle_wait()-blocked receiver wakes in µs, not a scheduler
        # quantum (≙ mpi_yield_when_idle for oversubscribed hosts)
        self._bell = self._lib.doorbell_open(
            _bell_name(bootstrap.job_id, self.rank), 1)
        # published AFTER the rx rings exist: dynamic spawn waits on this
        # key before letting anyone send to us (ring creator = receiver)
        bootstrap.put("transport_shm_rings", True)

    def add_peers(self, new_size: int) -> None:
        """Dynamic spawn grew the global rank space: create+attach rx rings
        for the new peers (the receiver is the ring creator, so this must
        run before a new peer's first send to us — dpm.spawn sequences it
        via the ready key)."""
        for peer in range(self.size, new_size):
            h = self._lib.shmbox_attach(
                _chan_name(self._bootstrap.job_id, peer, self.rank),
                self._ring, 1)
            if h < 0:
                raise RuntimeError(
                    f"shm transport: cannot create rx ring from rank {peer}")
            self._rx[peer] = h
            if self._mx is not None:
                self._mx[0].mx_add_rx(self._mx[1], peer, h)
        self.size = max(self.size, new_size)

    def reachable(self, peer: int) -> bool:
        if peer == self.rank or not (0 <= peer < self.size):
            return False
        host = self._hosts.get(peer, False)
        if host is False:
            try:
                host = self._bootstrap.get(peer, "transport_shm_host")
            except Exception:
                host = None
            self._hosts[peer] = host
        return host == _host_key()

    # -- tx -----------------------------------------------------------------

    def _tx_handle(self, peer: int) -> int:
        h = self._tx.get(peer)
        if h is None:
            h = self._lib.shmbox_attach(
                _chan_name(self._bootstrap.job_id, self.rank, peer), 0, 0)
            if h < 0:
                raise RuntimeError(
                    f"shm transport: cannot open channel to rank {peer}")
            self._tx[peer] = h
        return h

    def _try_write(self, peer: int, hdr: bytes, payload) -> bool:
        h = self._tx_handle(peer)
        # bytes pass straight through the c_char_p prototypes (zero copy);
        # other buffer shapes (memoryview/ndarray slices) convert once
        if not isinstance(payload, bytes):
            payload = bytes(payload)
        rc = self._lib.shmbox_write(h, hdr, len(hdr), payload, len(payload))
        if rc == -2:
            raise ValueError(
                f"frame of {len(hdr)}+{len(payload)} bytes exceeds shm ring "
                f"capacity {self._ring} (raise transport_shm_ring_size)")
        if rc == 1:      # ring was empty → peer may be blocked on its bell
            bell = self._tx_bells.get(peer)
            if bell is None:
                bell = self._lib.doorbell_open(
                    _bell_name(self._bootstrap.job_id, peer), 0)
                self._tx_bells[peer] = bell
            self._lib.doorbell_post(bell)
        return rc >= 0

    def adopt_mx(self, lib, eng: int) -> None:
        """Hand the rings to the native engine: rx rings registered for
        C++ draining; tx rings wired lazily at first send."""
        self._mx = (lib, eng)
        for peer, h in self._rx.items():
            lib.mx_add_rx(eng, peer, h)

    def _mx_wire_tx(self, peer: int) -> None:
        lib, eng = self._mx
        h = self._tx_handle(peer)
        bell = self._tx_bells.get(peer)
        if bell is None:
            bell = self._lib.doorbell_open(
                _bell_name(self._bootstrap.job_id, peer), 0)
            self._tx_bells[peer] = bell
        lib.mx_set_peer_tx(eng, peer, h, bell)
        self._mx_tx_wired.add(peer)

    def send(self, peer: int, tag: int, header: Dict[str, Any],
             payload: bytes) -> None:
        hdr = wire.encode(tag, header)
        if self._mx is not None:
            if peer not in self._mx_tx_wired:
                self._mx_wire_tx(peer)
            if not isinstance(payload, bytes):
                payload = bytes(payload)
            rc = self._mx[0].mx_tx(self._mx[1], peer, hdr, len(hdr),
                                   payload, len(payload))
            if rc == -2:
                raise ValueError(
                    f"frame of {len(hdr)}+{len(payload)} bytes exceeds shm "
                    f"ring capacity {self._ring} (raise "
                    f"transport_shm_ring_size)")
            if rc == -3:
                raise RuntimeError(
                    f"shm ring to rank {peer} is dead (handle closed)")
            return
        q = self._pending.get(peer)
        if q:
            q.append((hdr, payload))    # keep FIFO behind parked frames
            return
        if not self._try_write(peer, hdr, payload):
            self._pending.setdefault(peer, deque()).append((hdr, payload))

    # -- rx / progress ------------------------------------------------------

    def progress(self) -> int:
        if self._mx is not None:
            return 0        # the native pml's drain loop owns the rings
        n = 0
        for peer, q in list(self._pending.items()):
            while q:
                hdr, payload = q[0]
                if not self._try_write(peer, hdr, payload):
                    break
                q.popleft()
                n += 1
        rxbuf, rxview, body = self._rxbuf, self._rxview, self._rxbody
        read_frame = self._lib.shmbox_read_frame
        cap = len(rxbuf)
        for peer, h in self._rx.items():
            while True:
                # single-call pop into the reusable buffer (no peek
                # round-trip, no per-frame allocation)
                hlen = read_frame(h, rxbuf, cap, body)
                if hlen == -2:
                    # frame larger than rxbuf: tail did NOT advance, so
                    # breaking would re-hit it forever — a protocol bug
                    # (writers cap frames at max_send_size, headers at the
                    # rxbuf slack) must be loud, not a silent wedge
                    raise RuntimeError(
                        f"shm rx frame from rank {peer} exceeds the "
                        f"{cap}-byte frame buffer (protocol bug: writer "
                        f"must respect max_send_size)")
                if hlen < 0:
                    break
                total = body.value
                tag, header = wire.decode(rxview[:hlen])
                # the payload must outlive the reusable buffer (matching
                # may park it on the unexpected queue) → one owned copy
                self.deliver(peer, tag, header, rxview[hlen:total].tobytes())
                n += 1
        return n

    def pending_count(self, exclude: frozenset = frozenset()) -> int:
        if self._mx is not None:
            lib, eng = self._mx
            if not exclude:
                return lib.mx_pending_tx(eng, -1)
            return sum(lib.mx_pending_tx_peer(eng, p)
                       for p in self._mx_tx_wired if p not in exclude)
        return sum(len(q) for p, q in self._pending.items()
                   if p not in exclude)

    def _has_parked(self) -> bool:
        if self._mx is not None:
            return self._mx[0].mx_pending_tx(self._mx[1], -1) > 0
        return any(self._pending.values())

    def idle_wait(self, timeout: float) -> None:
        """Block until a sender rings our doorbell (or timeout) — called by
        the progress engine when a wait loop goes idle."""
        if self._has_parked():
            # Our own parked frames need progress, not sleep — but the
            # peer needs the core to drain its ring, so cede it instead of
            # hot-spinning (the caller's loop re-enters progress right away).
            import time
            time.sleep(0)
            return
        if self._bell < 0:      # no doorbell: plain sleep beats a hot spin
            import time
            time.sleep(timeout)
            return
        self._lib.doorbell_wait(self._bell, int(timeout * 1e6))

    def finalize(self) -> None:
        for h in list(self._tx.values()) + list(self._rx.values()):
            self._lib.shmbox_close(h)
        self._tx.clear()
        self._rx.clear()
        for bell in self._tx_bells.values():
            self._lib.doorbell_close(bell, None)
        self._tx_bells.clear()
        if self._bell >= 0:
            self._lib.doorbell_close(
                self._bell, _bell_name(self._bootstrap.job_id, self.rank))
            self._bell = -1
