"""Binary frame codec for the host transports (≙ the fixed wire headers of
pml_ob1_hdr.h:43-52 and btl_sm_fbox.h's packed fast-box header).

Round 1 pickled every frame header — convenient, but pickle encode+decode
dominated the per-hop cost on the shm ring (VERDICT r1 weak#6). The p2p
protocol's four frame kinds (MATCH/RNDV/ACK/FRAG) carry only small integers,
so they pack into one fixed little-endian struct, mirroring how the
reference gives every ob1 protocol header a packed C struct. Everything
else (osc/ft/coll control frames with arbitrary dict headers) falls back to
pickle behind a format byte — those are control-plane rare, not data-plane.

Frame layout (transport-independent):
    u8 fmt       0 = pickled (am_tag, header) tuple
                 1 = p2p fixed header
                 2 = hello (tcp connection identification)
    fmt 1: u8 am_tag | u8 kind | i64 cid | i64 tag | u32 seq |
           u64 size | i64 a | i64 b     (a/b: sreq/rreq/off per kind)
    fmt 2: u32 rank
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Dict, Tuple

_P2P = struct.Struct("<BBBqqIQqq")     # fmt, am_tag, kind, cid, tag, seq, size, a, b
_HELLO = struct.Struct("<BI")

_FMT_PICKLE = 0
_FMT_P2P = 1
_FMT_HELLO = 2

_K_MATCH, _K_RNDV, _K_ACK, _K_FRAG = 1, 2, 3, 4

HELLO = "HELLO"                        # sentinel am_tag for fmt-2 frames


def encode(am_tag: int, header: Dict[str, Any]) -> bytes:
    """Encode an active-message (tag, header) pair; payload rides separately.

    The struct fast path applies only to the p2p protocol's frames (AM tag
    1): other subsystems reuse kind names (osc also has an "ack") with
    different fields, so their headers take the generic pickle format.
    """
    if am_tag != 1:                    # transport.AM_P2P
        return b"\x00" + pickle.dumps((am_tag, header),
                                      protocol=pickle.HIGHEST_PROTOCOL)
    k = header.get("k")
    if k == "match":
        return _P2P.pack(_FMT_P2P, am_tag, _K_MATCH, header["cid"],
                         header["tag"], header["seq"], header["size"], 0, 0)
    if k == "rndv" and "cma" not in header and "dev" not in header:
        # a CMA-advertising rndv (and its fin reply) carries extra fields;
        # it rides the generic format — one frame per LARGE message, so
        # codec cost is irrelevant there, unlike the per-fragment fast path
        return _P2P.pack(_FMT_P2P, am_tag, _K_RNDV, header["cid"],
                         header["tag"], header["seq"], header["size"],
                         header["sreq"], 0)
    if k == "ack":
        return _P2P.pack(_FMT_P2P, am_tag, _K_ACK, 0, 0, 0, 0,
                         header["sreq"], header["rreq"])
    if k == "frag":
        return _P2P.pack(_FMT_P2P, am_tag, _K_FRAG, 0, 0, 0, 0,
                         header["rreq"], header["off"])
    return b"\x00" + pickle.dumps((am_tag, header),
                                  protocol=pickle.HIGHEST_PROTOCOL)


def encode_hello(rank: int) -> bytes:
    return _HELLO.pack(_FMT_HELLO, rank)


def decode(data) -> Tuple[Any, Dict[str, Any]]:
    """Decode to (am_tag, header); am_tag is HELLO for fmt-2 frames (header
    then carries {"rank": r})."""
    fmt = data[0]
    if fmt == _FMT_P2P:
        (_f, am_tag, kind, cid, tag, seq, size, a, b) = _P2P.unpack(
            bytes(data[:_P2P.size]))
        if kind == _K_MATCH:
            hdr = {"k": "match", "cid": cid, "tag": tag, "seq": seq,
                   "size": size}
        elif kind == _K_RNDV:
            hdr = {"k": "rndv", "cid": cid, "tag": tag, "seq": seq,
                   "size": size, "sreq": a}
        elif kind == _K_ACK:
            hdr = {"k": "ack", "sreq": a, "rreq": b}
        elif kind == _K_FRAG:
            hdr = {"k": "frag", "rreq": a, "off": b}
        else:
            raise ValueError(f"unknown p2p wire kind {kind}")
        return am_tag, hdr
    if fmt == _FMT_HELLO:
        (_f, rank) = _HELLO.unpack(bytes(data[:_HELLO.size]))
        return HELLO, {"rank": rank}
    return pickle.loads(bytes(data[1:]))
