"""TCP transport (≙ btl/tcp, opal/mca/btl/tcp/btl_tcp_component.c:1253).

Event-driven non-blocking sockets pumped from the progress engine. Design
points kept from the reference:
  * listen address published through the modex at init, lazy connect on first
    send (the reference creates endpoints connection-less at add_procs and
    connects on demand);
  * all I/O is non-blocking: sends append to a per-connection out-queue and
    drain when the socket is writable — two ranks blasting large fragments at
    each other can never deadlock in sendall;
  * per-direction ordering: the initiating side of a connection is the only
    sender on it (simplex pairs), so frames to a given peer arrive in send
    order — which the matching layer's non-overtaking guarantee rides on.

On TPU pods this is the DCN data plane for host-side traffic; device payloads
ride ICI via the coll/xla component instead (SURVEY.md §5.8).
"""

from __future__ import annotations

import selectors
import socket
import struct
from collections import deque
from typing import Any, Dict, Optional

from ..core.component import component
from ..core.output import output
from . import transport as T
from . import wire

# stream framing: [u32 frame_len][u32 hdr_len][wire header][payload]
_HDR = struct.Struct("!II")


def _advertised_host() -> str:
    """The address peers should dial: loopback for single-host jobs, the
    best-weighted interface toward the coordinator for multi-host (DCN)
    jobs (reachable.py ≙ opal/mca/reachable/weighted), falling back to a
    kernel routing probe when enumeration finds nothing."""
    import os

    coord = os.environ.get("OMPI_TPU_COORD", "")
    host = coord.rpartition(":")[0]
    if not host or host.startswith("127.") or host == "localhost":
        return "127.0.0.1"
    # the kernel routing table is authoritative when it has an answer: a
    # UDP connect names the source interface that actually routes toward
    # the coordinator (weighting must never override routing — a private
    # storage NIC may score high yet be unreachable from the peers)
    probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        probe.connect((host, 1))
        return probe.getsockname()[0]
    except OSError:
        pass
    finally:
        probe.close()
    # no route answer (resolver down, UDP filtered): fall back to the
    # weighted interface ladder, then the hostname
    from .reachable import best_address
    picked = best_address(host)
    if picked is not None and not picked.startswith("127."):
        return picked
    return socket.gethostbyname(socket.gethostname())


class _Conn:
    def __init__(self, sock: socket.socket) -> None:
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock = sock
        self.inbuf = bytearray()
        self.outbuf = deque()      # of memoryview
        self.out_bytes = 0
        self.peer: Optional[int] = None   # known for rx conns after HELLO


@component("transport", "tcp", priority=10)
class TcpTransport(T.Transport):
    name = "tcp"
    bandwidth = 20           # striping weight (loopback ~0.6 GB/s class)

    def __init__(self) -> None:
        super().__init__()
        self.rank = -1
        self.size = 0
        self._bootstrap = None
        self._sel = selectors.DefaultSelector()
        self._listener: Optional[socket.socket] = None
        self._tx: Dict[int, _Conn] = {}      # peer → conn I initiated
        self._rx: list[_Conn] = []           # conns initiated by peers
        self._addrs: Dict[int, tuple] = {}
        self._poll_skip = 0
        self.failed_peers: set = set()       # peers with dropped traffic (FT hook)

    # -- lifecycle ----------------------------------------------------------

    def init_job(self, bootstrap) -> None:
        self.rank, self.size = bootstrap.rank, bootstrap.size
        self._bootstrap = bootstrap
        self._listener = socket.create_server(("0.0.0.0", 0))
        self._listener.setblocking(False)
        self._sel.register(self._listener, selectors.EVENT_READ, ("accept", None))
        bootstrap.put("transport_tcp_addr",
                      (_advertised_host(), self._listener.getsockname()[1]))

    def reachable(self, peer: int) -> bool:
        return 0 <= peer < self.size

    def add_peers(self, new_size: int) -> None:
        """Dynamic spawn grew the global rank space: rx needs nothing (the
        listener accepts anyone), tx connects lazily via the modex."""
        self.size = max(self.size, new_size)

    def _addr_of(self, peer: int) -> tuple:
        addr = self._addrs.get(peer)
        if addr is None:
            addr = tuple(self._bootstrap.get(peer, "transport_tcp_addr"))
            self._addrs[peer] = addr
        return addr

    def _tx_conn(self, peer: int) -> _Conn:
        conn = self._tx.get(peer)
        if conn is None:
            sock = socket.create_connection(self._addr_of(peer))
            conn = _Conn(sock)
            conn.peer = peer
            self._tx[peer] = conn
            self._sel.register(sock, selectors.EVENT_READ, ("tx", conn))
            self._enqueue(conn, wire.encode_hello(self.rank), b"")
        return conn

    # -- tx -----------------------------------------------------------------

    def _enqueue(self, conn: _Conn, hdr: bytes, payload) -> None:
        n = len(hdr) + len(payload)
        conn.outbuf.append(memoryview(_HDR.pack(n, len(hdr)) + hdr))
        if len(payload):
            conn.outbuf.append(memoryview(payload) if not isinstance(
                payload, memoryview) else payload)
        conn.out_bytes += n + _HDR.size
        self._flush(conn)

    def send(self, peer: int, tag: int, header: Dict[str, Any], payload: bytes) -> None:
        # Failed peers keep the historical silent-drop semantics (AM reply
        # paths run inside the progress loop with no handler for a raise);
        # the striping path learns about failures through confirm().
        self._enqueue(self._tx_conn(peer), wire.encode(tag, header), payload)

    def _absorb_rx(self) -> None:
        """Pull bytes off every readable socket into its inbuf WITHOUT
        parsing or delivery. confirm()'s drain loop calls this so a peer
        in the same situation can empty ITS kernel tx window (mutual
        large sends would otherwise deadlock on full buffers) — and
        because nothing is dispatched, there is no re-entrant AM handling;
        the next progress() pass parses what landed here."""
        for key, _mask in self._sel.select(timeout=0):
            kind, conn = key.data
            if kind == "accept":
                continue               # leave accepts to progress()
            try:
                while True:
                    chunk = conn.sock.recv(1 << 18)
                    if not chunk:
                        break          # EOF — progress() will close it
                    conn.inbuf.extend(chunk)
            except (BlockingIOError, InterruptedError):
                pass
            except OSError:
                pass

    def confirm(self, peer: int) -> None:
        """Drain the peer's outbuf to the kernel, raising if the
        connection failed — the synchronous error surface striping needs:
        _flush swallows OSError asynchronously (send() only ENQUEUES), so
        a fragment range is only 'handed to the transport' once this
        returns (≙ the reference btl's des_cbfunc completion callback).

        The stall deadline is a NO-PROGRESS window, not a total cap: any
        bytes the kernel accepts push it out, so a slow-but-alive peer
        (small windows, congested loopback) is never misdiagnosed as
        failed and retired from the path set (ADVICE r3 item 3 — only a
        connection making zero forward progress for the full window
        raises, which failover then rightly treats as a dead path)."""
        import time
        conn = self._tx.get(peer)
        stall_window = 30.0
        deadline = time.monotonic() + stall_window
        last_out = conn.out_bytes if conn is not None else 0
        while conn is not None and conn.outbuf:
            if peer in self.failed_peers:
                break
            self._flush(conn)
            if conn.out_bytes < last_out:      # forward progress → extend
                last_out = conn.out_bytes
                deadline = time.monotonic() + stall_window
            if conn.outbuf:
                if time.monotonic() > deadline:
                    raise OSError(
                        f"tcp to rank {peer}: no forward progress for "
                        f"{stall_window:.0f}s ({conn.out_bytes} bytes "
                        "stuck)")
                self._absorb_rx()      # keep rx moving: no mutual-send
                time.sleep(0.0002)     # deadlock on full kernel buffers
        if peer in self.failed_peers:
            raise OSError(f"tcp connection to rank {peer} has failed")

    def _flush(self, conn: _Conn) -> int:
        sent = 0
        while conn.outbuf:
            mv = conn.outbuf[0]
            try:
                n = conn.sock.send(mv)
            except (BlockingIOError, InterruptedError):
                break
            except OSError as exc:
                output.error("transport",
                             f"tcp send to rank {conn.peer} failed, dropping "
                             f"{conn.out_bytes} queued bytes: {exc}")
                conn.outbuf.clear()
                conn.out_bytes = 0
                self.failed_peers.add(conn.peer)
                return sent
            sent += n
            conn.out_bytes -= n
            if n == len(mv):
                conn.outbuf.popleft()
            else:
                conn.outbuf[0] = mv[n:]
        return sent

    # -- rx / progress ------------------------------------------------------

    def progress(self) -> int:
        # A rank whose traffic all rides shm still pays this select()
        # syscall every poll. With zero established connections the only
        # thing to catch is a first accept — check that every 8th poll.
        # (connect() itself succeeds against the listen backlog, so this
        # only delays processing of the first frames; kept small because
        # idle polls can each block ~0.5 ms in the shm doorbell.)
        if not self._tx and not self._rx:
            self._poll_skip = (self._poll_skip + 1) % 8
            if self._poll_skip:
                return 0
        events = 0
        for key, _mask in self._sel.select(timeout=0):
            kind, conn = key.data
            if kind == "accept":
                try:
                    sock, _ = self._listener.accept()
                except OSError:
                    continue
                c = _Conn(sock)
                self._rx.append(c)
                self._sel.register(sock, selectors.EVENT_READ, ("rx", c))
                continue
            events += self._drain(conn)
        # frames absorbed during confirm() sit in inbufs with no further
        # socket readability to re-trigger select — parse them now
        for conn in list(self._rx) + list(self._tx.values()):
            if conn.inbuf:
                events += self._parse(conn)
        # drain pending sends even when sockets never became readable
        for conn in self._tx.values():
            if conn.outbuf:
                self._flush(conn)
        return events

    def _drain(self, conn: _Conn) -> int:
        eof = False
        try:
            while True:
                chunk = conn.sock.recv(1 << 18)
                if not chunk:
                    # peer closed — frames already buffered (sent just before
                    # the close) must still be parsed and delivered below
                    eof = True
                    break
                conn.inbuf.extend(chunk)
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            eof = True
        delivered = self._parse(conn)
        if eof:
            self._close(conn)
        return delivered

    def _parse(self, conn: _Conn) -> int:
        delivered = 0
        buf = conn.inbuf
        while len(buf) >= _HDR.size:
            n, hlen = _HDR.unpack_from(buf)
            if len(buf) < _HDR.size + n:
                break
            tag, header = wire.decode(
                memoryview(buf)[_HDR.size:_HDR.size + hlen])
            payload = bytes(buf[_HDR.size + hlen:_HDR.size + n])
            del buf[:_HDR.size + n]
            if tag is wire.HELLO:
                conn.peer = header["rank"]
            else:
                self.deliver(conn.peer, tag, header, payload)
                delivered += 1
        return delivered

    def _close(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn in self._rx:
            self._rx.remove(conn)
        for peer, c in list(self._tx.items()):
            if c is conn:
                del self._tx[peer]

    def pending_count(self, exclude: frozenset = frozenset()) -> int:
        return sum(1 for p, c in self._tx.items()
                   if c.outbuf and p not in exclude)

    def has_activity(self) -> bool:
        """True when live connections exist — the runtime caps doorbell
        blocking then, since tcp peers cannot ring a local semaphore."""
        return bool(self._tx or self._rx)

    def finalize(self) -> None:
        for conn in list(self._tx.values()) + list(self._rx):
            if conn.sock.fileno() < 0:
                continue
            # best-effort flush of queued frames before teardown
            conn.sock.setblocking(True)
            try:
                while conn.outbuf:
                    self._flush(conn)
            except OSError:
                pass
            self._close(conn)
        if self._listener is not None:
            try:
                self._sel.unregister(self._listener)
            except (KeyError, ValueError):
                pass
            self._listener.close()
