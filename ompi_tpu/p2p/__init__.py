"""Point-to-point stack: transports (≙ btl), matching + protocol (≙ pml/ob1),
requests (≙ ompi/request)."""

from .request import (  # noqa: F401
    ANY_SOURCE,
    ANY_TAG,
    CompletedRequest,
    Request,
    Status,
    wait_all,
    wait_any,
)
from .transport import AM_COLL, AM_FT, AM_OSC, AM_P2P, Transport, TransportLayer  # noqa: F401
from .pml import P2P, TruncateError  # noqa: F401
from .part import precv_init, psend_init  # noqa: F401  (MPI-4 partitioned)
