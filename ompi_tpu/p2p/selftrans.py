"""Loopback transport (≙ btl/self): immediate in-process delivery."""

from __future__ import annotations

from collections import deque
from typing import Any, Dict

from ..core.component import component
from . import transport as T


@component("transport", "self", priority=100)  # bandwidth default unused:
# TransportLayer.paths_for_peer makes loopback sole-PATH whenever it is
# the primary, so self-sends never stripe through the kernel tcp stack
class SelfTransport(T.Transport):
    name = "self"

    def __init__(self) -> None:
        super().__init__()
        self.rank = -1
        self._queue: deque = deque()

    def init_job(self, bootstrap) -> None:
        self.rank = bootstrap.rank

    def reachable(self, peer: int) -> bool:
        return peer == self.rank

    def send(self, peer: int, tag: int, header: Dict[str, Any], payload: bytes) -> None:
        assert peer == self.rank
        # queued (not delivered inline) so send() never re-enters matching
        self._queue.append((tag, header, payload))

    def progress(self) -> int:
        n = 0
        while self._queue:
            tag, header, payload = self._queue.popleft()
            self.deliver(self.rank, tag, header, payload)
            n += 1
        return n
