"""NIC enumeration + weighted reachability (≙ opal/mca/if + reachable).

The reference enumerates interfaces (opal/mca/if, SURVEY.md §2.2) and
scores (local interface, remote peer) pairs so every process dials a peer
over the best mutually-routable link (opal/mca/reachable/weighted — kind/
bandwidth-based weights). TPU hosts usually expose one DCN NIC plus
loopback, but multi-NIC hosts (separate storage / control networks) need
the same discipline: advertise the address of the interface most likely to
carry job traffic, not whatever the hostname resolves to.

``interfaces()``    — up IPv4 interfaces from /sys/class/net + SIOCGIFADDR
``weight(i, host)`` — weighted score: link state, address kind (private
                      beats public beats loopback for DCN traffic),
                      same-subnet-as-target bonus, /sys speed bonus
``best_address(host)`` — the address to advertise for traffic toward
                      ``host`` (tcp transport's modex entry)
"""

from __future__ import annotations

import os
import socket
import struct
from dataclasses import dataclass

from ..core.hwtopo import _read  # shared /sys reader
from typing import List, Optional

SIOCGIFADDR = 0x8915
SIOCGIFNETMASK = 0x891B


def _ioctl_addr(sock: socket.socket, name: str, req: int) -> Optional[str]:
    import fcntl
    try:
        res = fcntl.ioctl(sock.fileno(), req,
                          struct.pack("256s", name[:15].encode()))
        return socket.inet_ntoa(res[20:24])
    except OSError:
        return None


@dataclass
class Iface:
    name: str
    addr: str
    netmask: str
    up: bool
    loopback: bool
    speed_mbps: int       # -1 = unknown


def interfaces() -> List[Iface]:
    """Enumerate IPv4-configured interfaces (up or not)."""
    out: List[Iface] = []
    try:
        names = sorted(os.listdir("/sys/class/net"))
    except OSError:
        names = [n for _i, n in socket.if_nameindex()]
    probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for name in names:
            addr = _ioctl_addr(probe, name, SIOCGIFADDR)
            if addr is None:
                continue
            mask = _ioctl_addr(probe, name, SIOCGIFNETMASK) or "255.255.255.255"
            state = _read(f"/sys/class/net/{name}/operstate") or "unknown"
            # loopback reports state "unknown" but is always usable
            lo = addr.startswith("127.")
            speed = _read(f"/sys/class/net/{name}/speed")
            out.append(Iface(
                name=name, addr=addr, netmask=mask,
                up=lo or state in ("up", "unknown"),
                loopback=lo,
                speed_mbps=int(speed) if speed and speed.lstrip("-").isdigit()
                else -1))
    finally:
        probe.close()
    return out


def _ip_u32(addr: str) -> int:
    return struct.unpack("!I", socket.inet_aton(addr))[0]


def _is_private(addr: str) -> bool:
    u = _ip_u32(addr)
    return ((u >> 24) == 10 or
            (u >> 20) == (172 << 4 | 1) or       # 172.16/12
            (u >> 16) == (192 << 8 | 168))       # 192.168/16


def _resolve(target: Optional[str]) -> Optional[str]:
    if not target:
        return None
    try:
        return socket.gethostbyname(target)
    except OSError:
        return None


def weight(iface: Iface, target: Optional[str] = None) -> int:
    """Score an interface for carrying traffic toward ``target`` (a
    hostname or IP; resolved here — callers scoring many interfaces should
    resolve once and pass the IP, as best_address does). Ladder
    (reachable/weighted's CQ kinds, adapted): down links are unusable;
    same-subnet beats kind; private beats public beats loopback-for-remote;
    link speed breaks ties."""
    if not iface.up:
        return -1
    target_ip = _resolve(target)
    if target_ip is not None and target_ip.startswith("127."):
        # single-host job: loopback is THE right link
        return 1000 if iface.loopback else 10
    score = 0
    if target_ip is not None and not iface.loopback:
        mask = _ip_u32(iface.netmask)
        if (_ip_u32(iface.addr) & mask) == (_ip_u32(target_ip) & mask):
            score += 500                     # same subnet: directly routable
    if iface.loopback:
        score += 1                           # useless for remote targets
    elif _is_private(iface.addr):
        score += 100                         # cluster/DCN fabric address
    else:
        score += 50                          # public/other
    if iface.speed_mbps > 0:
        # log-ish bonus: 1G→+9, 10G→+13, 100G→+16 (breaks kind ties only)
        score += max(0, iface.speed_mbps.bit_length())
    return score


def best_address(target: Optional[str] = None) -> Optional[str]:
    """Address to advertise for traffic toward ``target`` (None = any
    remote peer); None when nothing scores positive. Resolves the target
    once, not per interface."""
    target = _resolve(target)
    cands = [(weight(i, target), i) for i in interfaces()]
    cands = [(w, i) for w, i in cands if w > 0]
    if not cands:
        return None
    cands.sort(key=lambda wi: (-wi[0], wi[1].name))
    return cands[0][1].addr
