"""Completion objects (≙ ompi/request/request.h:129 + wait/test engines)."""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from ..core.progress import get_engine
from .. import health, peruse

ANY_SOURCE = -1
ANY_TAG = -1


class Status:
    __slots__ = ("source", "tag", "count", "error", "cancelled")

    def __init__(self) -> None:
        self.source = ANY_SOURCE
        self.tag = ANY_TAG
        self.count = 0
        self.error = 0
        self.cancelled = False


class Request:
    """A pending communication. Completion is driven by the progress engine."""

    __slots__ = ("done", "status", "error", "result", "_on_complete", "_ctx",
                 "pending_error", "_posted_ref")

    def __init__(self) -> None:
        self.done = False
        self.status = Status()
        self.error: Optional[Exception] = None
        self.result: Any = None       # collective/value-carrying completions
        self._on_complete: List[Callable[["Request"], None]] = []
        self._ctx: Any = None
        self._posted_ref: Any = None  # (matching, cid, Posted) while queued
        # ULFM MPIX_ERR_PROC_FAILED_PENDING: raised once by wait/test while
        # the request STAYS active (an ANY_SOURCE recv interrupted by a peer
        # failure can still complete from survivors after failure_ack)
        self.pending_error: Optional[Exception] = None

    def set_pending(self, err: Exception) -> None:
        if not self.done:
            self.pending_error = err

    def add_completion_callback(self, cb: Callable[["Request"], None]) -> None:
        if self.done:
            cb(self)
        else:
            self._on_complete.append(cb)

    def complete(self, error: Optional[Exception] = None) -> None:
        if self.done:
            return
        self.error = error
        self.done = True
        if peruse.active:       # ≙ PERUSE_COMM_REQ_COMPLETE
            peruse.fire(peruse.REQ_COMPLETE, count=self.status.count,
                        error=error is not None)
        for cb in self._on_complete:
            cb(self)
        self._on_complete.clear()

    def test(self) -> bool:
        if not self.done:
            get_engine().progress()
        if not self.done and self.pending_error is not None:
            err, self.pending_error = self.pending_error, None
            raise err
        return self.done

    def wait(self, timeout: Optional[float] = None) -> Status:
        # flight recorder: a blocked p2p wait is watchdog-visible too
        # (health.enabled is ONE attribute read on the disabled path)
        htok = health.wait_begin(self) if health.enabled \
            and not self.done else 0
        try:
            get_engine().wait_until(
                lambda: self.done or self.pending_error is not None,
                timeout=timeout)
        finally:
            if htok:
                health.op_end(htok)
        if not self.done and self.pending_error is not None:
            # request remains active; the caller acks the failure and may
            # wait again (ULFM PROC_FAILED_PENDING discipline)
            err, self.pending_error = self.pending_error, None
            raise err
        if not self.done:
            raise TimeoutError("request did not complete")
        if self.error is not None:
            raise self.error
        return self.status


class CompletedRequest(Request):
    def __init__(self, count: int = 0, result: Any = None) -> None:
        super().__init__()
        self.done = True
        self.status.count = count
        self.result = result


def _settled(r: Request) -> bool:
    return r.done or r.pending_error is not None


def wait_all(requests: List[Request], timeout: Optional[float] = None) -> List[Status]:
    htok = health.waitset_begin(requests, "p2p_wait_all") \
        if health.enabled and requests else 0
    try:
        get_engine().wait_until(lambda: all(_settled(r) for r in requests),
                                timeout=timeout)
    finally:
        if htok:
            health.op_end(htok)
    out = []
    for r in requests:
        if not r.done and r.pending_error is not None:
            # PROC_FAILED_PENDING must surface here too — an ANY_SOURCE recv
            # interrupted by a peer failure would otherwise hang waitall
            err, r.pending_error = r.pending_error, None
            raise err
        if not r.done:
            raise TimeoutError("waitall: request did not complete")
        if r.error is not None:
            raise r.error
        out.append(r.status)
    return out


def wait_any(requests: List[Request], timeout: Optional[float] = None) -> int:
    htok = health.waitset_begin(requests, "p2p_wait_any") \
        if health.enabled and requests else 0
    try:
        get_engine().wait_until(lambda: any(_settled(r) for r in requests),
                                timeout=timeout)
    finally:
        if htok:
            health.op_end(htok)
    for i, r in enumerate(requests):
        if r.done:
            if r.error is not None:
                raise r.error
            return i
    for r in requests:
        if r.pending_error is not None:
            err, r.pending_error = r.pending_error, None
            raise err
    raise TimeoutError("waitany: no request completed")


class GeneralizedRequest(Request):
    """MPI_Grequest_start/complete (MPI-4 §3.9): user-level operations that
    complete through the MPI request machinery. The user marks completion
    with ``grequest_complete()``; the query callback then fills the status
    (exactly once — hooked at the completion layer so EVERY wait flavor,
    wait/test/wait_all/wait_any, observes it) and the free callback
    releases the user's resources. Cancellation routes to the user's
    cancel function; per MPI, whether a cancel succeeded is reported by
    the USER's query_fn setting ``status.cancelled``."""

    __slots__ = ("_query_fn", "_free_fn", "_cancel_fn", "_queried")

    def __init__(self, query_fn=None, free_fn=None, cancel_fn=None) -> None:
        super().__init__()
        self._query_fn = query_fn
        self._free_fn = free_fn
        self._cancel_fn = cancel_fn
        self._queried = False
        self.add_completion_callback(self._grequest_collect)

    def _grequest_collect(self, _req) -> None:
        if self._queried:
            return
        self._queried = True
        if self._query_fn is not None:
            self._query_fn(self.status)
        if self._free_fn is not None:
            self._free_fn()

    def grequest_complete(self) -> None:
        """The user's operation finished (MPI_Grequest_complete)."""
        self.complete()

    def cancel(self) -> None:
        if self._cancel_fn is not None:
            self._cancel_fn(self.done)


def grequest_start(query_fn=None, free_fn=None,
                   cancel_fn=None) -> GeneralizedRequest:
    """MPI_Grequest_start."""
    return GeneralizedRequest(query_fn, free_fn, cancel_fn)
