"""Native-engine pml: the per-message host data path in C++ (≙ pml/ob1's C
matching engine, pml_ob1_recvfrag.c:453, and btl/sm's fbox send path,
btl_sm_fbox.h:31-35).

Round-2 profiling put 60-80 µs of Python in every host message.  Here the
hot path is ONE ctypes call each way into native/mx.cpp:

  * eager send → ``mx_send_eager`` (header pack + ring write + doorbell);
  * arrivals   → ``mx_progress`` drains every shm ring in C++, matches in
    C++, memcpys eager payloads into posted user buffers and fragment
    payloads into registered sinks, then queues fixed-size records that
    ``_mx_progress`` turns into Request completions.

Python keeps the *protocol* (rendezvous decisions, CMA, device staging,
truncation, errors) — those are per-*message* for large transfers, not
per-byte.  The C++ engine holds the matching state for ALL transports:
tcp/self arrivals are fed through ``mx_arrived`` so ANY_SOURCE sees one
unified queue, exactly ob1's single-matching-engine property.

Selection: ``runtime.Context`` instantiates ``NativeP2P`` when the native
library builds, the shm transport was selected, and
``OMPI_TPU_pml_base_native`` (default true) allows it; otherwise the pure
Python ``P2P`` remains in charge (no-toolchain hosts lose speed, not
features).  Both speak the identical wire format, so native and pure
ranks interoperate within one job.
"""

from __future__ import annotations

import ctypes
import itertools
from typing import Any, Dict, Optional

import numpy as np

from .. import peruse
from ..core import var as _var
from ..datatype import Datatype
from . import transport as T
from . import wire
from .matching import Unexpected
from .pml import P2P, _guarded
from .request import ANY_SOURCE, ANY_TAG, Request

_var.register("pml", "base", "native", True, type=bool, level=3,
              help="Use the native (C++) matching + frame engine when the "
                   "shm transport and toolchain are available.")

_U8P = ctypes.POINTER(ctypes.c_uint8)

_EV_RECV_DONE = 1
_EV_RECV_DATA = 2
_EV_RECV_RNDV = 3
_EV_PY_FRAME = 4
_EV_ACK = 5
_EV_SINK_DONE = 6
_EV_RECV_FAILED = 7
_EV_RECV_PENDING = 8
_EV_UNEX = 9

_K_MATCH, _K_RNDV = 1, 2


class _MxEv(ctypes.Structure):
    _pack_ = 1
    _fields_ = [("type", ctypes.c_int32), ("peer", ctypes.c_int32),
                ("a", ctypes.c_int64), ("b", ctypes.c_int64),
                ("c", ctypes.c_int64), ("d", ctypes.c_int64),
                ("e", ctypes.c_int64), ("f", ctypes.c_int32),
                ("blob", ctypes.c_void_p), ("blen", ctypes.c_uint64)]


class _MxImm(ctypes.Structure):
    _pack_ = 1
    _fields_ = [("kind", ctypes.c_int32), ("src", ctypes.c_int32),
                ("tag", ctypes.c_int64), ("seq", ctypes.c_uint32),
                ("size", ctypes.c_uint64),
                ("sreq_or_token", ctypes.c_int64),
                ("blob", ctypes.c_void_p), ("blen", ctypes.c_uint64)]


class _Slot:
    """Python side of a posted receive living in the C++ engine."""
    __slots__ = ("req", "on_match", "arr", "cap")

    def __init__(self, req, on_match, arr, cap) -> None:
        self.req = req
        self.on_match = on_match   # full protocol closure (pml._recv_handler)
        self.arr = arr             # direct-mode destination (host contiguous)
        self.cap = cap


class NativeMatching:
    """Facade over the C++ queues with the classic engine's external
    surface — ULFM ``fail_src``, probe, cancel, and the debugger snapshot —
    so ft/ulfm.py and debuggers.py work unchanged."""

    def __init__(self, pml: "NativeP2P") -> None:
        self._pml = pml
        self.spc = None

    # -- probe (≙ matching.probe) ------------------------------------------

    def probe(self, cid: int, src: int, tag: int,
              remove: bool = False) -> Optional[Unexpected]:
        p = self._pml
        imm = _MxImm()
        if not p._lib.mx_probe(p._mxh, cid, src, tag, int(remove),
                               ctypes.byref(imm)):
            return None
        # a peek (iprobe poll loop) only reads src/tag/size — skip the
        # payload copy; only a dequeue (mprobe) materializes the bytes
        return p._imm_to_unexpected(cid, imm, owned=remove,
                                    want_payload=remove)

    def cancel(self, cid: int, slot_id: int) -> bool:
        p = self._pml
        ok = bool(p._lib.mx_cancel(p._mxh, cid, slot_id))
        if ok:
            p._slots.pop(slot_id, None)
        return ok

    def fail_src(self, src: int, err: Exception,
                 any_source_cids=frozenset(),
                 pending_err: Exception | None = None) -> None:
        p = self._pml
        cids = list(any_source_cids)
        arr = (ctypes.c_int64 * max(len(cids), 1))(*cids)
        p._fail_err = err
        p._fail_pending_err = pending_err or err
        p._lib.mx_fail_src(p._mxh, src, arr, len(cids))
        p._drain()            # the failure records are queued synchronously

    # feed from python-side transports (tcp/self) — same unified queues
    def arrived(self, cid: int, src: int, tag: int, seq: int, kind: str,
                header: Dict[str, Any], payload: bytes) -> None:
        p = self._pml
        if kind == "match":
            p._lib.mx_arrived(p._mxh, src, cid, tag, seq,
                              header["size"], _K_MATCH, 0, -1,
                              payload, len(payload))
        else:
            token = -1
            if "cma" in header or "dev" in header:
                # only extended headers need a token (cma advertisement,
                # device-channel flag); a plain rndv reconstructs
                # losslessly from the event fields
                token = next(p._token_ids)
                p._tokens[token] = header
            p._lib.mx_arrived(p._mxh, src, cid, tag, seq, header["size"],
                              _K_RNDV, header.get("sreq", 0), token, b"", 0)
        p._drain()
        p._sync_stats()          # an eventless unexpected still counts

    # -- debugger snapshot (debuggers.message_queues) ----------------------

    def snapshot(self):
        p = self._pml
        need = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(need)
            n = p._lib.mx_dump(p._mxh, buf, need)
            if n <= need:
                break
            need = n + 1
        posted, unexpected = [], []
        for line in buf.raw[:n].decode().splitlines():
            parts = line.split()
            if parts[0] == "P":
                posted.append({"cid": int(parts[1]), "src": int(parts[2]),
                               "tag": int(parts[3])})
            else:
                unexpected.append({
                    "cid": int(parts[1]), "src": int(parts[2]),
                    "tag": int(parts[3]), "seq": int(parts[4]),
                    "kind": "match" if parts[5] == "1" else "rndv",
                    "nbytes": int(parts[6])})
        return posted, unexpected


class NativeP2P(P2P):
    """P2P with the per-message path in C++ — see module docstring."""

    def __init__(self, bootstrap, layer, engine, spc=None) -> None:
        from .. import native

        super().__init__(bootstrap, layer, engine, spc=spc)
        self._lib = native.load()
        shm = next(t for t in layer.transports if t.name == "shm")
        self._shm = shm
        self._mxh = self._lib.mx_new(shm._ring)
        if self._mxh < 0:
            raise RuntimeError("mx engine table exhausted")
        shm.adopt_mx(self._lib, self._mxh)
        # replace the classic matching engine; external consumers
        # (ulfm, debuggers, inherited probe/mprobe paths) use the facade
        self.matching = NativeMatching(self)
        self.matching.spc = self.spc
        self._slots: Dict[int, _Slot] = {}
        self._slot_ids = itertools.count(1)
        self._tokens: Dict[int, Dict[str, Any]] = {}
        self._token_ids = itertools.count(1)
        self._mx_peers: Dict[int, bool] = {}
        self._fail_err: Optional[Exception] = None
        self._fail_pending_err: Optional[Exception] = None
        self._evbuf = (_MxEv * 64)()
        self._in_drain = False
        self._mx_peruse = False
        # failover: a retired path must also leave the fast-path routing
        # cache, or eager sends keep hitting the dead shm ring
        layer.on_path_failed.append(self._path_failed)
        self._stat_base = [0, 0]      # matches_posted, unexpected_arrivals
        engine.register(self._mx_progress)

    def finalize(self) -> None:
        super().finalize()
        if self._mxh >= 0:
            self._lib.mx_destroy(self._mxh)
            self._mxh = -1

    # -- helpers ------------------------------------------------------------

    def _path_failed(self, peer: int, transport) -> None:
        if transport is self._shm:
            self._mx_peers[peer] = False

    def _is_mx_peer(self, peer: int) -> bool:
        v = self._mx_peers.get(peer)
        if v is None:
            v = self.layer.for_peer(peer) is self._shm
            self._mx_peers[peer] = v
        return v

    def _imm_to_unexpected(self, cid: int, imm: _MxImm, owned: bool,
                           want_payload: bool = True) -> Unexpected:
        """Rebuild the classic Unexpected view from an immediate-match /
        probe result (Message/mprobe and the python-mode recv paths)."""
        if imm.kind == 2:        # match payload
            payload = ctypes.string_at(imm.blob, imm.blen) \
                if imm.blob and want_payload else b""
            if owned and imm.blob:
                self._lib.mx_free_blob(imm.blob)
            header = {"k": "match", "cid": cid, "tag": imm.tag,
                      "seq": imm.seq, "size": imm.size}
            return Unexpected(imm.src, imm.tag, imm.seq, "match", header,
                              payload)
        if imm.kind == 4:        # rndv with python-held header (cma etc.)
            header = self._tokens.pop(imm.sreq_or_token) if owned else \
                self._tokens[imm.sreq_or_token]
        else:                    # fmt-1 rndv
            header = {"k": "rndv", "cid": cid, "tag": imm.tag,
                      "seq": imm.seq, "size": imm.size,
                      "sreq": imm.sreq_or_token}
        return Unexpected(imm.src, imm.tag, imm.seq, "rndv", header, b"")

    def _unregister_sink(self, rreq: int, state) -> None:
        if state.native_sink:
            self._lib.mx_remove_sink(self._mxh, rreq)
            state.native_sink = False

    def _register_sink(self, rreq: int, state, src: int) -> None:
        """Contiguous sinks land by C++ memcpy when the peer's frags come
        over an mx-owned ring (pml hook)."""
        buf = state.sink_buf
        if buf is None or state.total == 0 or not self._is_mx_peer(src):
            return
        if isinstance(buf, np.ndarray):
            ptr = buf.reshape(-1).view(np.uint8).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8))
        else:                    # bytearray (_PackedSink staging buffer)
            ptr = ctypes.cast(
                (ctypes.c_char * len(buf)).from_buffer(buf),
                ctypes.POINTER(ctypes.c_uint8))
        self._lib.mx_add_sink(self._mxh, rreq, ptr, state.total)
        state.native_sink = True
        # state.conv stays: striped fragments arriving on python-side
        # transports (tcp share) unpack through it and credit the C++
        # sink's coverage (_handle_frag override)

    # -- send ---------------------------------------------------------------

    @_guarded
    def isend(self, buf, dst: int, tag: int = 0, cid: int = 0,
              datatype: Optional[Datatype] = None,
              count: Optional[int] = None, sync: bool = False) -> Request:
        # fast path: host-contiguous eager to an mx peer — ONE native call.
        # Size gate FIRST: the ndarray branch copies (tobytes), which must
        # never happen for rendezvous-bound payloads.
        if not sync and datatype is None and count is None:
            data = None
            if type(buf) is bytes:
                if len(buf) <= self._shm.eager_limit:
                    data = buf
            elif isinstance(buf, np.ndarray) and \
                    buf.nbytes <= self._shm.eager_limit and \
                    buf.flags["C_CONTIGUOUS"] and buf.dtype != object:
                data = buf.tobytes()
            if data is not None and self._is_mx_peer(dst):
                key = (cid, dst)
                seq = self._send_seq[key]
                self._send_seq[key] = seq + 1
                if dst not in self._shm._mx_tx_wired:
                    self._shm._mx_wire_tx(dst)
                rc = self._lib.mx_send_eager(self._mxh, dst, cid, tag, seq,
                                             data, len(data))
                if rc == -2:
                    raise ValueError(
                        f"eager frame of {len(data)} bytes exceeds the shm "
                        f"ring capacity (raise transport_shm_ring_size)")
                if rc == -3:
                    raise RuntimeError(
                        f"shm ring to rank {dst} is dead (handle closed)")
                n = len(data)
                if peruse.active:    # activate BEFORE complete (PERUSE
                    # pairing discipline — classic isend order)
                    peruse.fire(peruse.REQ_ACTIVATE, kind="send", peer=dst,
                                tag=tag, cid=cid, nbytes=n)
                req = Request()
                req.status.source = self.rank
                req.status.tag = tag
                req.status.count = n
                req.complete()       # eager: complete once buffered
                self.spc.inc("isends")
                self.spc.inc("eager_sends")
                self.spc.inc("bytes_sent", n)
                self.spc.peer_traffic("tx", dst, n)
                return req
        return super().isend(buf, dst, tag, cid, datatype, count, sync)

    def _stream_frags(self, dst: int, rreq: int, state) -> None:
        if not self._is_mx_peer(dst):
            return super()._stream_frags(dst, rreq, state)
        # zero-copy source: the pinned user array (CMA declined) streams
        # straight from its own memory — no tobytes() staging copy. The
        # native call parks copies only if the receiver stops draining, so
        # the buffer is never referenced after return (MPI completion ok).
        if state.data is not None:
            src = state.data
            addr = ctypes.cast(ctypes.c_char_p(src), ctypes.c_void_p).value
            n = len(src)
        elif state.keep is not None:
            src = state.keep.reshape(-1).view(np.uint8)
            addr = src.ctypes.data
            n = src.nbytes
        else:
            src, addr, n = b"", 0, 0
        if not n:
            state.req.complete()
            return
        from .pml import _striping_on
        primary = self._shm
        paths = self.layer.paths_for_peer(dst) if _striping_on() \
            else [primary]
        plan = self._stripe_plan(n, paths, primary)

        def send_range(t, base, ln):
            if t is self._shm:
                ptr = ctypes.cast(ctypes.c_void_p(addr + base), _U8P)
                rc = self._lib.mx_send_frags(
                    self._mxh, dst, rreq, ptr, ln,
                    self._shm.max_send_size, base)
                if rc < 0:
                    raise RuntimeError(
                        "dead shm ring" if rc == -3
                        else "frame cannot fit the shm ring")
            else:
                # secondary share (tcp): one owned copy of ITS range
                if isinstance(src, np.ndarray):
                    rng = src[base:base + ln].tobytes()
                else:
                    rng = src[base:base + ln]
                self._send_range(dst, rreq, rng, 0, ln, t, off_base=base)

        self._run_with_failover(dst, state, plan, send_range)

    def _handle_frag(self, rreq: int, off: int, payload: bytes) -> None:
        """A fragment that arrived on a python-side transport while the
        C++ engine holds the sink (striping): unpack here, credit the
        shared coverage, complete when the union covers the message."""
        state = self._pending_recv.get(rreq)
        if state is None:
            return               # late duplicate after completion
        if not state.native_sink:
            return super()._handle_frag(rreq, off, payload)
        if off + len(payload) > state.total:
            # corrupt offset: fail the request with a diagnostic instead
            # of letting a sink-extending unpack mask missing real bytes.
            # The C++ sink must go too — in-flight shm fragments for this
            # rreq would otherwise keep landing in a buffer the
            # application may reclaim after seeing the error.
            del self._pending_recv[rreq]
            self._lib.mx_remove_sink(self._mxh, rreq)
            state.req.complete(RuntimeError(
                f"fragment [{off}, {off + len(payload)}) outside the "
                f"{state.total}-byte message"))
            return
        state.conv.set_position(off)
        state.conv.unpack(payload)
        if self._lib.mx_sink_credit(self._mxh, rreq, off,
                                    len(payload)) == 1:
            del self._pending_recv[rreq]
            if state.finish is not None:
                state.finish()
            state.req.complete()

    # -- recv ---------------------------------------------------------------

    @_guarded
    def irecv(self, buf, src: int = ANY_SOURCE, tag: int = ANY_TAG,
              cid: int = 0, datatype: Optional[Datatype] = None,
              count: Optional[int] = None) -> Request:
        req, on_match, (arr, dt, cnt, dinfo) = \
            self._recv_handler(buf, datatype, count)
        if peruse.active:
            peruse.fire(peruse.REQ_ACTIVATE, kind="recv", peer=src,
                        tag=tag, cid=cid)
        direct = (dinfo is None and arr is not None and cnt is not None
                  and dt.is_contiguous and arr.flags["C_CONTIGUOUS"])
        cap = dt.size * cnt if cnt is not None else 0
        slot_id = next(self._slot_ids)
        imm = _MxImm()
        if direct:
            ptr = arr.reshape(-1).view(np.uint8).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8))
        else:
            ptr = None
        rc = self._lib.mx_post_recv(self._mxh, cid, src, tag, ptr, cap,
                                    slot_id, ctypes.byref(imm))
        if rc == 1:
            if peruse.active:
                peruse.fire(peruse.REQ_MATCH_UNEX, cid=cid, src=imm.src,
                            tag=imm.tag, seq=imm.seq)
            if imm.kind == 1:    # payload already memcpy'd into arr
                # ("recvs" was already counted by _recv_handler)
                self.spc.inc("bytes_recvd", imm.blen)
                self.spc.peer_traffic("rx", imm.src, imm.blen)
                req.status.source = imm.src
                req.status.tag = imm.tag
                req.status.count = imm.blen
                req.complete()
            else:                # python protocol (rndv / size>cap / ...)
                on_match(self._imm_to_unexpected(cid, imm, owned=True))
            self.spc.inc("matches_unexpected")
            return req
        if peruse.active:
            peruse.fire(peruse.REQ_INSERT_IN_POSTED_Q, cid=cid, src=src,
                        tag=tag)
        self._slots[slot_id] = _Slot(req, on_match, arr if direct else None,
                                     cap)
        req._posted_ref = (self.matching, cid, slot_id)
        return req

    # -- progress: drain native completion records --------------------------

    def _mx_progress(self) -> int:
        if peruse.active != self._mx_peruse:
            self._mx_peruse = peruse.active
            self._lib.mx_set_peruse(self._mxh, int(peruse.active))
        n = self._lib.mx_progress(self._mxh)
        if n == -2:
            raise RuntimeError(
                "shm rx frame exceeds the ring frame budget (protocol "
                "bug: writer must respect max_send_size)")
        drained = self._drain()
        if n and not drained:
            # frames moved without producing events (eager→unexpected with
            # peruse off is eventless): still mirror the C++ counters so
            # SPC/mpit never under-report unexpected_arrivals
            self._sync_stats()
        return n + drained

    def _drain(self) -> int:
        # re-entrancy guard: an event handler can feed the engine again
        # (tcp rndv → matching.arrived → _drain); the records it queues are
        # picked up by THIS loop's next pass — never by a nested one that
        # would clobber the shared event buffer mid-iteration
        if self._in_drain:
            return 0
        self._in_drain = True
        lib, evbuf = self._lib, self._evbuf
        total = 0
        try:
            while True:
                k = lib.mx_drain(self._mxh, evbuf, len(evbuf))
                for i in range(k):
                    self._handle_event(evbuf[i])
                total += k
                if k == 0:
                    break
        finally:
            self._in_drain = False
        if total:
            self._sync_stats()
        return total

    def _handle_event(self, ev: _MxEv) -> None:
        t = ev.type
        if t == _EV_RECV_DONE:
            slot = self._slots.pop(ev.a, None)
            if slot is None:
                return
            # ("recvs" was counted at post time by _recv_handler)
            self.spc.inc("bytes_recvd", ev.d)
            self.spc.peer_traffic("rx", ev.b, ev.d)
            slot.req.status.source = ev.b
            slot.req.status.tag = ev.c
            slot.req.status.count = ev.d
            slot.req.complete()
        elif t == _EV_RECV_DATA:
            slot = self._slots.pop(ev.a, None)
            payload = ctypes.string_at(ev.blob, ev.blen) if ev.blob else b""
            if ev.blob:
                lib_free = self._lib.mx_free_blob
                lib_free(ev.blob)
            if slot is None:
                return
            header = {"k": "match", "cid": 0, "tag": ev.c, "seq": 0,
                      "size": ev.d}
            slot.on_match(Unexpected(ev.b, ev.c, 0, "match", header,
                                     payload))
        elif t == _EV_RECV_RNDV:
            slot = self._slots.pop(ev.a, None)
            if ev.f:             # python-held header token (cma rndv)
                header = self._tokens.pop(ev.e)
            else:
                header = {"k": "rndv", "tag": ev.c, "size": ev.d,
                          "sreq": ev.e}
            if slot is None:
                return
            slot.on_match(Unexpected(ev.b, ev.c, 0, "rndv", header, b""))
        elif t == _EV_PY_FRAME:
            frame = ctypes.string_at(ev.blob, ev.blen) if ev.blob else b""
            if ev.blob:
                self._lib.mx_free_blob(ev.blob)
            hlen = ev.a
            tag, header = wire.decode(frame[:hlen])
            self._shm.deliver(ev.peer, tag, header, frame[hlen:])
        elif t == _EV_ACK:
            self._handle_ack(ev.peer, ev.a, ev.b)
        elif t == _EV_SINK_DONE:
            state = self._pending_recv.pop(ev.a, None)
            if state is None:
                return
            state.received = ev.b
            if state.finish is not None:
                state.finish()
            state.req.complete()
        elif t == _EV_RECV_FAILED:
            slot = self._slots.pop(ev.a, None)
            if slot is not None:
                slot.req.complete(self._fail_err)
        elif t == _EV_RECV_PENDING:
            slot = self._slots.get(ev.a)
            if slot is not None:
                slot.req.set_pending(self._fail_pending_err)
        elif t == _EV_UNEX:
            if peruse.active:
                peruse.fire(peruse.MSG_INSERT_IN_UNEX_Q, cid=ev.a,
                            src=ev.b, tag=ev.c, seq=ev.e)

    def _sync_stats(self) -> None:
        """Mirror the C++ matching counters into SPC (mpit/finalize dump)."""
        lib = self._lib
        mp = lib.mx_stat(self._mxh, 0)
        ua = lib.mx_stat(self._mxh, 1)
        if mp > self._stat_base[0]:
            self.spc.inc("matches_posted", mp - self._stat_base[0])
            self._stat_base[0] = mp
        if ua > self._stat_base[1]:
            self.spc.inc("unexpected_arrivals", ua - self._stat_base[1])
            self._stat_base[1] = ua


def maybe_native(bootstrap, layer, engine, spc=None) -> Optional[NativeP2P]:
    """NativeP2P when the toolchain + shm transport + var allow it."""
    from .. import native

    if not _var.get("pml_base_native", True):
        return None
    if not native.available():
        return None
    if not any(t.name == "shm" for t in layer.transports):
        return None
    return NativeP2P(bootstrap, layer, engine, spc=spc)
