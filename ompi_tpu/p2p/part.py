"""Partitioned point-to-point (MPI-4, ≙ ompi/mca/part — part.h:30,124,150
and the `persist` component).

Partitioned communication lets a sender mark sub-ranges ("partitions") of
one buffer ready independently — the fine-grained pipelining primitive
pipeline-parallel training uses to overlap microbatch compute with
transfers (SURVEY.md §2.6 maps PP onto partitioned sends).

Design (persist component semantics, TPU-host flavored):
  * ``psend_init``/``precv_init`` create persistent requests; ``start()``
    arms one round, ``pready(i)`` releases sender partition i as its own
    internal message, ``parrived(j)`` tests receiver partition j.
  * The two sides may partition differently (MPI allows it; only the total
    element count must match). Sender partition messages land at their
    global element offset; receiver partition j is "arrived" when every
    overlapping sender partition has landed.
  * A one-time handshake on the user-visible (src, tag) channel carries the
    sender's partitioning and a session id that scopes the internal
    per-partition tags — the persistent-init matching the reference does
    once per request pair (part.h setup exchange).

Internal tags live in the -300000 band (user tags ≥ 0; coll/nbc bands are
documented in coll/nbc.py).
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from ..datatype import Datatype
from .request import Request

_TAG_PART_SETUP = -3000         # handshake rides (this - user_tag) channel
_TAG_PART_BASE = -300000        # per-partition data tags
_MAX_PARTS = 4096

_sess_lock = threading.Lock()
_sess_counter = 0


def _new_session(rank: int) -> int:
    global _sess_counter
    with _sess_lock:
        _sess_counter += 1
        return rank * 100_000 + (_sess_counter % 100_000)


def _part_tag(session: int, index: int) -> int:
    return _TAG_PART_BASE - session * _MAX_PARTS - index


class PartitionedRequest(Request):
    """Base for both directions; inactive between rounds like persistent
    requests (MPI_Start semantics)."""

    def __init__(self, comm, buf, partitions: int, peer: int, tag: int) -> None:
        super().__init__()
        arr = np.asarray(buf)
        if arr.size % partitions:
            raise ValueError(
                f"count {arr.size} not divisible into {partitions} partitions")
        self.comm = comm
        self.buf = arr
        self.partitions = partitions
        self.part_elems = arr.size // partitions
        self.peer = peer
        self.tag = tag
        self.active = False
        self.done = True          # inactive requests test as complete

    def _flat(self) -> np.ndarray:
        return self.buf.reshape(-1)


class PsendRequest(PartitionedRequest):
    def __init__(self, comm, buf, partitions: int, dst: int, tag: int) -> None:
        super().__init__(comm, buf, partitions, dst, tag)
        self.session = _new_session(comm.ctx.rank)
        self._handshook = False
        self._ready: List[bool] = []
        self._reqs: List[Optional[Request]] = []

    def start(self) -> "PsendRequest":
        if self.active:
            raise RuntimeError("partitioned request already active")
        self.active = True
        self.done = False
        self.error = None
        self._ready = [False] * self.partitions
        self._reqs = [None] * self.partitions
        if not self._handshook:
            # one-time setup on the user tag channel: [session, nparts, total]
            setup = np.array([self.session, self.partitions, self.buf.size],
                             np.int64)
            self.comm.isend(setup, self.peer, _TAG_PART_SETUP - max(self.tag, 0))
            self._handshook = True
        return self

    def pready(self, index) -> None:
        """MPI_Pready / MPI_Pready_range: release partition(s)."""
        idxs = [index] if np.isscalar(index) else list(index)
        flat = self._flat()
        for i in idxs:
            if not self.active:
                raise RuntimeError("pready on inactive request")
            if self._ready[i]:
                raise RuntimeError(f"partition {i} already marked ready")
            self._ready[i] = True
            lo = i * self.part_elems
            seg = flat[lo:lo + self.part_elems]
            self._reqs[i] = self.comm.isend(
                seg, self.peer, _part_tag(self.session, i))
        if all(self._ready):
            def _check(_req=None):
                if all(r is not None and r.done for r in self._reqs):
                    self.active = False
                    self.complete()
            for r in self._reqs:
                r.add_completion_callback(lambda _r: _check())
            _check()


class PrecvRequest(PartitionedRequest):
    def __init__(self, comm, buf, partitions: int, src: int, tag: int) -> None:
        super().__init__(comm, buf, partitions, src, tag)
        self._setup: Optional[np.ndarray] = None
        self._arrived_elems = 0
        self._landed: List[bool] = []     # per SENDER partition
        self._sender_parts = 0
        self._sender_elems = 0

    def start(self) -> "PrecvRequest":
        if self.active:
            raise RuntimeError("partitioned request already active")
        self.active = True
        self.done = False
        self.error = None
        if self._setup is None:
            setup = np.zeros(3, np.int64)
            self.comm.recv(setup, self.peer,
                           _TAG_PART_SETUP - max(self.tag, 0))
            if int(setup[2]) != self.buf.size:
                raise ValueError(
                    f"partitioned total mismatch: sender {int(setup[2])} "
                    f"elements, receiver {self.buf.size}")
            self._setup = setup
            self._sender_parts = int(setup[1])
            self._sender_elems = self.buf.size // self._sender_parts
        self._landed = [False] * self._sender_parts
        session = int(self._setup[0])
        flat = self._flat()
        for i in range(self._sender_parts):
            lo = i * self._sender_elems
            seg = flat[lo:lo + self._sender_elems]
            req = self.comm.irecv(seg, self.peer, _part_tag(session, i))
            req.add_completion_callback(
                lambda _r, i=i: self._on_landed(i, _r))
        return self

    def _on_landed(self, i: int, req: Request) -> None:
        if req.error is not None:
            self.active = False
            self.complete(req.error)
            return
        self._landed[i] = True
        if all(self._landed):
            self.active = False
            self.complete()

    def parrived(self, index: int) -> bool:
        """MPI_Parrived: has receiver partition ``index`` fully arrived?"""
        lo = index * self.part_elems
        hi = lo + self.part_elems
        s0 = lo // self._sender_elems if self._sender_elems else 0
        s1 = (hi - 1) // self._sender_elems if self._sender_elems else 0
        self.comm.ctx.engine.progress()
        return all(self._landed[s] for s in range(s0, s1 + 1))


def psend_init(comm, buf, partitions: int, dst: int, tag: int = 0,
               datatype: Optional[Datatype] = None) -> PsendRequest:
    """MPI_Psend_init (contiguous numpy buffers; derived datatypes go
    through the convertor at the pml layer as usual)."""
    if partitions < 1 or partitions > _MAX_PARTS:
        raise ValueError(f"partitions must be in [1, {_MAX_PARTS}]")
    return PsendRequest(comm, buf, partitions, dst, tag)


def precv_init(comm, buf, partitions: int, src: int, tag: int = 0,
               datatype: Optional[Datatype] = None) -> PrecvRequest:
    """MPI_Precv_init."""
    if partitions < 1 or partitions > _MAX_PARTS:
        raise ValueError(f"partitions must be in [1, {_MAX_PARTS}]")
    return PrecvRequest(comm, buf, partitions, src, tag)
