"""Classic persistent point-to-point requests.

≙ MPI_Send_init / MPI_Recv_init / MPI_Start / MPI_Startall (the reference
implements them in pml/ob1 as pre-built request templates re-armed by
start). A persistent request captures the call's arguments once; start()
re-activates it (posting a fresh underlying operation), wait()/test()
complete the CURRENT activation, and the request stays allocated for the
next start — the classic halo-exchange pattern:

    sreq = comm.send_init(sbuf, right, tag=7)
    rreq = comm.recv_init(rbuf, left, tag=7)
    for _ in range(iters):
        start_all([sreq, rreq])
        ...overlap compute...
        sreq.wait(); rreq.wait()
    sreq.free(); rreq.free()

Buffers are captured by REFERENCE (MPI semantics): refill the send buffer
/ read the recv buffer between activations. Not to be confused with
partitioned p2p (part.py — MPI-4 Psend/Precv) or persistent collectives
(coll/nbc.py persistent()).
"""

from __future__ import annotations

from typing import List, Optional

from .request import Request, Status


class PersistentRequest:
    """An inactive request template; start() arms it."""

    __slots__ = ("_comm", "_kind", "_buf", "_peer", "_tag", "_kw",
                 "_active", "_freed", "_last_status", "_last_result")

    def __init__(self, comm, kind: str, buf, peer: int, tag: int,
                 **kw) -> None:
        self._comm = comm
        self._kind = kind          # "send" | "ssend" | "recv"
        self._buf = buf
        self._peer = peer
        self._tag = tag
        self._kw = kw
        self._active: Optional[Request] = None
        self._freed = False
        self._last_status: Optional[Status] = None   # most recent collection
        self._last_result = None                     # e.g. device recv array

    @property
    def active(self) -> bool:
        """MPI-active: started and not yet COLLECTED by wait/test —
        transport-level completion alone does not deactivate it."""
        return self._active is not None

    def start(self) -> "PersistentRequest":
        """Arm the request (MPI_Start). Starting while the previous
        activation is still in flight is erroneous in MPI; enforced."""
        if self._freed:
            raise RuntimeError("persistent request used after free")
        if self.active:
            raise RuntimeError(
                "MPI_Start on an ACTIVE persistent request (the previous "
                "activation has not completed)")
        if self._kind == "recv":
            self._active = self._comm.irecv(self._buf, self._peer,
                                            self._tag, **self._kw)
        else:
            kw = dict(self._kw)
            if self._kind == "ssend":
                kw["sync"] = True
            self._active = self._comm.isend(self._buf, self._peer,
                                            self._tag, **kw)
        return self

    def _collect(self) -> None:
        """The current activation completed: keep its status/result so
        they survive re-arming (device recvs deliver ONLY via .result —
        see pml.py's device-destination contract)."""
        self._last_status = self._active.status
        self._last_result = self._active.result
        self._active = None

    def wait(self, timeout: Optional[float] = None) -> Status:
        """Complete the current activation; the request stays allocated
        (inactive) for the next start. Waiting on an INACTIVE request whose
        last activation was already collected (e.g. via test()) is MPI's
        no-op wait: the last status returns again."""
        if self._freed:
            raise RuntimeError("persistent request used after free")
        if self._active is None:
            if self._last_status is not None:
                return self._last_status
            raise RuntimeError("wait on a never-started persistent request")
        self._active.wait(timeout=timeout)
        self._collect()
        return self._last_status

    def test(self) -> bool:
        if self._freed:
            raise RuntimeError("persistent request used after free")
        if self._active is None:
            return True
        if self._active.test():
            self._collect()
            return True
        return False

    @property
    def status(self) -> Optional[Status]:
        """Status of the most recently collected activation."""
        return self._last_status

    @property
    def result(self):
        """Result of the current (if collected-able) or most recently
        collected activation — where device-array recvs deliver."""
        if self._active is not None:
            return self._active.result
        return self._last_result

    def free(self) -> None:
        """MPI_Request_free on an inactive persistent request."""
        if self.active:
            raise RuntimeError("free of an ACTIVE persistent request")
        self._freed = True
        self._active = None


def start_all(requests: List[PersistentRequest]) -> None:
    """MPI_Startall."""
    for r in requests:
        r.start()


def wait_all_persistent(requests: List[PersistentRequest],
                        timeout: Optional[float] = None) -> List[Status]:
    """MPI_Waitall over persistent requests: ONE overall deadline (the
    per-request remainder shrinks as earlier ones complete), matching
    request.wait_all's discipline rather than compounding n×timeout."""
    import time
    deadline = None if timeout is None else time.monotonic() + timeout
    out = []
    for r in requests:
        left = None if deadline is None else \
            max(0.0, deadline - time.monotonic())
        out.append(r.wait(timeout=left))
    return out
