"""Typed memory-layout descriptions (datatypes).

TPU-native re-design of the reference's two-level datatype engine
(opal/datatype/ — ~14 kLoC — plus MPI semantics in ompi/datatype/):

  * predefined types map onto numpy dtypes (including bfloat16, the TPU-native
    compute type, via ml_dtypes — something the reference has no equivalent of);
  * derived types (contiguous / vector / indexed / hindexed / struct / subarray /
    resized: reference ompi/datatype/ompi_datatype_create_*.c) are normalized at
    commit() into a flat list of (byte_offset, numpy dtype, count) segments per
    element — the analog of the reference's optimized description
    (opal_datatype_optimize.c);
  * size vs extent vs lb/ub semantics follow MPI: ``size`` is bytes of actual
    data, ``extent`` the span a consecutive element advances by (resized can
    change it).

Device notes: contiguous datatypes are the fast path and map 1:1 onto device
buffers (jax arrays) with zero reshaping; non-contiguous layouts are packed on
host by the convertor (reference packs on host too: opal_convertor.c:245), with
a Pallas gather/scatter device-pack path as a later optimization (SURVEY.md §7
hard parts).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

try:  # bfloat16 & friends: TPU-native types
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
    _FLOAT8_E4M3 = np.dtype(ml_dtypes.float8_e4m3fn)
    _FLOAT8_E5M2 = np.dtype(ml_dtypes.float8_e5m2)
except ImportError:  # pragma: no cover
    _BFLOAT16 = _FLOAT8_E4M3 = _FLOAT8_E5M2 = None


@dataclass(frozen=True)
class Segment:
    """One contiguous typed run within a single datatype element."""

    offset: int          # byte offset from element start
    dtype: np.dtype      # numpy dtype of the run
    count: int           # number of dtype items in the run

    @property
    def nbytes(self) -> int:
        return self.dtype.itemsize * self.count


class Datatype:
    """An MPI-style datatype: committed layout + size/extent bookkeeping."""

    def __init__(
        self,
        segments: Sequence[Segment],
        extent: int,
        name: str = "derived",
        lb: int = 0,
        predefined_np: Optional[np.dtype] = None,
    ) -> None:
        self.segments: List[Segment] = sorted(segments, key=lambda s: s.offset)
        self.extent = extent
        self.lb = lb
        self.name = name
        self.committed = predefined_np is not None
        self.np_dtype = predefined_np  # set for predefined/contiguous-homogeneous
        self.size = sum(s.nbytes for s in self.segments)

    # -- predicates ---------------------------------------------------------

    @property
    def is_contiguous(self) -> bool:
        """True when one packed element is a single run exactly filling extent."""
        if not self.segments or self.lb != 0:
            return False
        off = self.lb
        for s in self.segments:
            if s.offset != off:
                return False
            off += s.nbytes
        return off - self.lb == self.size and self.extent == self.size

    @property
    def is_homogeneous(self) -> bool:
        return len({s.dtype for s in self.segments}) == 1

    def base_np_dtype(self) -> np.dtype:
        """The numpy dtype for homogeneous types (needed by reductions)."""
        if self.np_dtype is not None:
            return self.np_dtype
        if not self.is_homogeneous:
            raise TypeError(f"datatype {self.name} is not homogeneous")
        return self.segments[0].dtype

    def commit(self) -> "Datatype":
        """Coalesce adjacent same-dtype segments (opal_datatype_optimize.c)."""
        if self.committed:
            return self
        merged: List[Segment] = []
        for s in self.segments:
            if (
                merged
                and merged[-1].dtype == s.dtype
                and merged[-1].offset + merged[-1].nbytes == s.offset
            ):
                prev = merged.pop()
                merged.append(Segment(prev.offset, prev.dtype, prev.count + s.count))
            else:
                merged.append(s)
        self.segments = merged
        self.committed = True
        return self

    def __repr__(self) -> str:
        return f"Datatype({self.name}, size={self.size}, extent={self.extent})"

    # -- derived-type constructors (ompi/datatype/ompi_datatype_create_*.c) --

    def dup(self, name: Optional[str] = None) -> "Datatype":
        d = Datatype(list(self.segments), self.extent, name or self.name, self.lb,
                     self.np_dtype)
        d.committed = self.committed
        return d

    @staticmethod
    def contiguous(count: int, base: "Datatype", name: str = "contig") -> "Datatype":
        segs = []
        for i in range(count):
            for s in base.segments:
                segs.append(Segment(i * base.extent + s.offset, s.dtype, s.count))
        np_dt = base.np_dtype if base.is_contiguous else None
        return Datatype(segs, count * base.extent, name, base.lb, None if count != 1 else np_dt).commit()

    @staticmethod
    def vector(count: int, blocklength: int, stride: int, base: "Datatype",
               name: str = "vector", stride_in_bytes: bool = False) -> "Datatype":
        """count blocks of blocklength base-elements, start-to-start stride
        (in base extents, or bytes for hvector)."""
        sb = stride if stride_in_bytes else stride * base.extent
        segs = []
        for i in range(count):
            for j in range(blocklength):
                for s in base.segments:
                    segs.append(Segment(i * sb + j * base.extent + s.offset,
                                        s.dtype, s.count))
        # MPI extent of vector: from lb to ub of the laid-out blocks
        last_block_end = (count - 1) * sb + blocklength * base.extent
        return Datatype(segs, last_block_end, name).commit()

    @staticmethod
    def indexed(blocklengths: Sequence[int], displacements: Sequence[int],
                base: "Datatype", name: str = "indexed",
                disp_in_bytes: bool = False) -> "Datatype":
        segs = []
        ub = 0
        for blen, disp in zip(blocklengths, displacements):
            db = disp if disp_in_bytes else disp * base.extent
            for j in range(blen):
                for s in base.segments:
                    segs.append(Segment(db + j * base.extent + s.offset,
                                        s.dtype, s.count))
            ub = max(ub, db + blen * base.extent)
        return Datatype(segs, ub, name).commit()

    @staticmethod
    def struct(blocklengths: Sequence[int], displacements: Sequence[int],
               types: Sequence["Datatype"], name: str = "struct") -> "Datatype":
        segs = []
        ub = 0
        for blen, disp, t in zip(blocklengths, displacements, types):
            for j in range(blen):
                for s in t.segments:
                    segs.append(Segment(disp + j * t.extent + s.offset,
                                        s.dtype, s.count))
            ub = max(ub, disp + blen * t.extent)
        return Datatype(segs, ub, name).commit()

    @staticmethod
    def subarray(sizes: Sequence[int], subsizes: Sequence[int],
                 starts: Sequence[int], base: "Datatype",
                 order_c: bool = True, name: str = "subarray") -> "Datatype":
        """n-dim subarray of a larger array (ompi_datatype_create_darray/subarray)."""
        if not order_c:
            sizes, subsizes, starts = sizes[::-1], subsizes[::-1], starts[::-1]
        ndim = len(sizes)
        strides = [0] * ndim           # byte stride per dim (C order)
        stride = base.extent
        for d in range(ndim - 1, -1, -1):
            strides[d] = stride
            stride *= sizes[d]
        segs: List[Segment] = []

        def rec(dim: int, off: int) -> None:
            if dim == ndim - 1:
                start = off + starts[dim] * strides[dim]
                for j in range(subsizes[dim]):
                    for s in base.segments:
                        segs.append(Segment(start + j * base.extent + s.offset,
                                            s.dtype, s.count))
                return
            for i in range(subsizes[dim]):
                rec(dim + 1, off + (starts[dim] + i) * strides[dim])

        rec(0, 0)
        full_extent = int(np.prod(sizes)) * base.extent
        return Datatype(segs, full_extent, name).commit()

    @staticmethod
    def resized(base: "Datatype", lb: int, extent: int,
                name: str = "resized") -> "Datatype":
        d = Datatype(list(base.segments), extent, name, lb, base.np_dtype)
        d.committed = base.committed
        return d


def _predef(np_dtype, name: str) -> Datatype:
    dt = np.dtype(np_dtype)
    return Datatype([Segment(0, dt, 1)], dt.itemsize, name, predefined_np=dt)


# Predefined types (reference: ompi/datatype/ompi_datatype_module.c tables).
INT8 = _predef(np.int8, "int8")
UINT8 = _predef(np.uint8, "uint8")
INT16 = _predef(np.int16, "int16")
UINT16 = _predef(np.uint16, "uint16")
INT32 = _predef(np.int32, "int32")
UINT32 = _predef(np.uint32, "uint32")
INT64 = _predef(np.int64, "int64")
UINT64 = _predef(np.uint64, "uint64")
FLOAT16 = _predef(np.float16, "float16")
FLOAT32 = _predef(np.float32, "float32")
FLOAT64 = _predef(np.float64, "float64")
COMPLEX64 = _predef(np.complex64, "complex64")
COMPLEX128 = _predef(np.complex128, "complex128")
BYTE = _predef(np.uint8, "byte")
BOOL = _predef(np.bool_, "bool")
if _BFLOAT16 is not None:
    BFLOAT16 = _predef(_BFLOAT16, "bfloat16")
    FLOAT8_E4M3 = _predef(_FLOAT8_E4M3, "float8_e4m3")
    FLOAT8_E5M2 = _predef(_FLOAT8_E5M2, "float8_e5m2")

# Aliases with MPI spellings
INT = INT32
LONG = INT64
FLOAT = FLOAT32
DOUBLE = FLOAT64

_BY_NP: dict = {}
for _t in (INT8, UINT8, INT16, UINT16, INT32, UINT32, INT64, UINT64, FLOAT16,
           FLOAT32, FLOAT64, COMPLEX64, COMPLEX128, BOOL):
    _BY_NP[_t.np_dtype] = _t
if _BFLOAT16 is not None:
    _BY_NP[_BFLOAT16] = BFLOAT16
    _BY_NP[_FLOAT8_E4M3] = FLOAT8_E4M3
    _BY_NP[_FLOAT8_E5M2] = FLOAT8_E5M2


def from_numpy(dtype) -> Datatype:
    """Map a numpy dtype (incl. bfloat16/fp8) to the predefined Datatype.
    Structured dtypes (e.g. MAXLOC value/index pairs, ≙ MPI_DOUBLE_INT) map
    to an on-the-fly struct datatype."""
    dt = np.dtype(dtype)
    try:
        return _BY_NP[dt]
    except KeyError:
        pass
    if dt.fields:
        segs = []
        for fname, (fdt, off) in dt.fields.items():
            if fdt.subdtype is not None:
                base, shape = fdt.subdtype
                segs.append(Segment(off, base, int(np.prod(shape))))
            else:
                segs.append(Segment(off, fdt, 1))
        d = Datatype(segs, dt.itemsize, f"struct:{dt}")
        d.np_dtype = dt
        return d.commit()
    raise TypeError(f"no predefined datatype for numpy dtype {dt}")
