"""Datatype engine: typed layouts + pack/unpack convertor (≙ opal/datatype +
ompi/datatype in the reference)."""

from .datatype import (  # noqa: F401
    BOOL,
    BYTE,
    COMPLEX64,
    COMPLEX128,
    DOUBLE,
    FLOAT,
    FLOAT16,
    FLOAT32,
    FLOAT64,
    INT,
    INT8,
    INT16,
    INT32,
    INT64,
    LONG,
    UINT8,
    UINT16,
    UINT32,
    UINT64,
    Datatype,
    Segment,
    from_numpy,
)

try:
    from .datatype import BFLOAT16, FLOAT8_E4M3, FLOAT8_E5M2  # noqa: F401
except ImportError:  # pragma: no cover
    pass

from .convertor import Convertor, pack, unpack  # noqa: F401
