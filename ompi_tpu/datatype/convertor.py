"""Pack/unpack convertor.

Re-design of opal/datatype/opal_convertor.c (pack entry :245): turns
(buffer, datatype, count) into a contiguous packed byte stream and back,
with support for *partial* (positioned) packing — the property segmented /
pipelined collectives and the rendezvous protocol rely on — and external32
(big-endian canonical) representation for heterogeneous peers.

Differences from the reference, by design:
  * the unit of user data is a numpy array (or anything exposing the buffer
    protocol). Jax DEVICE arrays never reach this layer for the common
    case: the accelerator component packs/unpacks homogeneous item-aligned
    datatypes ON DEVICE as one jitted XLA gather/scatter with a
    device-cached index map (accelerator/jaxacc.py pack_device/stage_in —
    the device half of opal_convertor.c:245's role), and only the packed
    contiguous stream crosses the PCIe/host bridge. Heterogeneous or
    misaligned datatypes fall back to full staging plus this convertor;
  * contiguous fast path is a single memoryview copy (no per-segment loop).
"""

from __future__ import annotations

import ctypes
from typing import List, Tuple

import numpy as np

from .. import native
from .datatype import Datatype

_U8P = ctypes.POINTER(ctypes.c_uint8)
_I64P = ctypes.POINTER(ctypes.c_int64)


def _native_segs(dt: Datatype) -> np.ndarray:
    """Flattened (offset, nbytes) table handed to the C++ loops, cached on
    the datatype."""
    segs = getattr(dt, "_native_segs", None)
    if segs is None:
        segs = np.array([(s.offset, s.nbytes) for s in dt.segments],
                        np.int64).ravel()
        dt._native_segs = segs
    return segs


def _as_bytes_view(buf) -> memoryview:
    """A writable flat uint8 view of the user buffer."""
    if isinstance(buf, np.ndarray):
        if not buf.flags["C_CONTIGUOUS"]:
            raise ValueError("user buffers must be C-contiguous numpy arrays")
        return buf.reshape(-1).view(np.uint8).data
    return memoryview(buf).cast("B")


class Convertor:
    """Positioned pack/unpack over (buf, datatype, count).

    The packed stream layout is: for element e in [0, count), for segment s in
    datatype.segments, the s.nbytes bytes at ``e*extent + s.offset``.
    ``position`` indexes into that stream, enabling arbitrary-boundary
    segmentation (reference: opal_convertor_set_position).
    """

    def __init__(self, buf, datatype: Datatype, count: int,
                 external32: bool = False) -> None:
        self.buf = buf
        self.dt = datatype
        self.count = count
        self.external32 = external32
        self.packed_size = datatype.size * count
        self.position = 0
        # per-element cumulative packed offsets of each segment
        self._cum: List[int] = [0]
        for s in datatype.segments:
            self._cum.append(self._cum[-1] + s.nbytes)

    # -- internals ----------------------------------------------------------

    def _iter_ranges(self, position: int, size: int):
        """Yield (raw_byte_offset, packed_offset, nbytes, dtype) runs covering
        [position, position+size) of the packed stream."""
        dt = self.dt
        esize = dt.size
        end = min(position + size, self.packed_size)
        pos = position
        import bisect
        while pos < end:
            elem, rem = divmod(pos, esize)
            si = bisect.bisect_right(self._cum, rem) - 1
            s = dt.segments[si]
            intra = rem - self._cum[si]
            n = min(s.nbytes - intra, end - pos)
            raw = elem * dt.extent + s.offset + intra
            yield raw, pos, n, s.dtype
            pos += n

    def _swap(self, arr: np.ndarray, dtype: np.dtype) -> np.ndarray:
        """external32 byte order is big-endian (MPI 'external32')."""
        if dtype.itemsize == 1 or not self.external32:
            return arr
        return arr.reshape(-1, dtype.itemsize)[:, ::-1].reshape(-1)

    # -- API ----------------------------------------------------------------

    def pack(self, max_bytes: int | None = None) -> bytes:
        """Pack from the current position, advancing it; returns ≤ max_bytes."""
        if max_bytes is None:
            max_bytes = self.packed_size - self.position
        src = _as_bytes_view(self.buf)
        out = np.empty(min(max_bytes, self.packed_size - self.position), np.uint8)
        if self.dt.is_contiguous and not self.external32:
            n = len(out)
            out[:] = np.frombuffer(src, np.uint8,
                                   count=n, offset=self.position)
            self.position += n
            return out.tobytes()
        lib = None if self.external32 else native.load()
        if lib is not None:
            # native segment walker (native/convertor.cpp ≙ the reference's
            # compiled-description pack loop, opal_convertor.c:245)
            n = len(out)
            segs = _native_segs(self.dt)
            lib.conv_pack_partial(
                out.ctypes.data_as(_U8P),
                np.frombuffer(src, np.uint8).ctypes.data_as(_U8P),
                self.dt.extent, segs.ctypes.data_as(_I64P),
                len(self.dt.segments), self.dt.size, self.position, n)
            self.position += n
            return out.tobytes()
        written = 0
        for raw, pos, n, sdt in self._iter_ranges(self.position, len(out)):
            chunk = np.frombuffer(src, np.uint8, count=n, offset=raw)
            if self.external32 and n % sdt.itemsize == 0:
                chunk = self._swap(chunk, sdt)
            out[written:written + n] = chunk
            written += n
        self.position += written
        return out[:written].tobytes()

    def unpack(self, data: bytes) -> int:
        """Unpack bytes at the current position, advancing it; returns consumed."""
        dst = _as_bytes_view(self.buf)
        src = np.frombuffer(data, np.uint8)
        if self.dt.is_contiguous and not self.external32:
            n = min(len(src), self.packed_size - self.position)
            dst[self.position:self.position + n] = src[:n]
            self.position += n
            return n
        lib = None if self.external32 else native.load()
        if lib is not None:
            n = min(len(src), self.packed_size - self.position)
            segs = _native_segs(self.dt)
            lib.conv_unpack_partial(
                np.frombuffer(dst, np.uint8).ctypes.data_as(_U8P),
                src.ctypes.data_as(_U8P),
                self.dt.extent, segs.ctypes.data_as(_I64P),
                len(self.dt.segments), self.dt.size, self.position, n)
            self.position += n
            return n
        consumed = 0
        for raw, pos, n, sdt in self._iter_ranges(self.position, len(src)):
            chunk = src[consumed:consumed + n]
            if self.external32 and n % sdt.itemsize == 0:
                chunk = self._swap(chunk, sdt)
            np.frombuffer(dst, np.uint8)[raw:raw + n] = chunk
            consumed += n
        self.position += consumed
        return consumed

    def set_position(self, position: int) -> None:
        if not 0 <= position <= self.packed_size:
            raise ValueError(f"position {position} outside [0, {self.packed_size}]")
        self.position = position


def pack(buf, datatype: Datatype, count: int, external32: bool = False) -> bytes:
    """One-shot full pack."""
    return Convertor(buf, datatype, count, external32).pack()


def unpack(data: bytes, buf, datatype: Datatype, count: int,
           external32: bool = False) -> int:
    """One-shot full unpack."""
    return Convertor(buf, datatype, count, external32).unpack(data)
