"""Memchecker — communication buffer-safety checking.

≙ the reference's memchecker framework (opal/mca/memchecker/valgrind/,
SURVEY.md §5.2): under Valgrind it marks user buffers defined/undefined
around point-to-point so read-before-receive and modify-while-in-flight
bugs surface. Without a Valgrind dependency the same two bug classes are
caught directly:

  * **modify-while-in-flight**: MPI forbids touching a send buffer while a
    nonblocking send is pending. The send buffer is checksummed at post
    and re-checked at completion — a mismatch is reported with the
    peer/tag. Eager sends are exempt by construction here: the payload is
    snapshotted into an immutable frame before isend returns, so
    post-return reuse (legal — the request is already complete) can never
    corrupt the message.
  * **read-before-receive**: the receive buffer is poisoned with a
    recognizable byte pattern at post; any value the application reads
    before completion is loudly garbage rather than stale plausible data,
    and a short message leaves the tail poisoned — exactly the undefined
    bytes Valgrind would flag.

Debug-build tool, like the reference's --enable-memchecker: interpose with
``memchecker.install(ctx)`` (or the ``memchecker_enabled`` var) in tests
and repro runs; the data path stays unchanged when not installed.
"""

from __future__ import annotations

import zlib
from typing import List

import numpy as np

from .core import var as _var
from .core.output import output

_var.register("memchecker", "", "enabled", False, type=bool, level=4,
              help="Interpose buffer-safety checks on p2p "
                   "(≙ --enable-memchecker builds).")

POISON = 0xCB


class Report:
    """Collected findings (also logged through output.verbose)."""

    def __init__(self) -> None:
        self.findings: List[str] = []

    def add(self, msg: str) -> None:
        self.findings.append(msg)
        output.verbose(0, "memchecker", msg)


def _crc(buf) -> int:
    arr = np.asarray(buf)
    return zlib.crc32(arr.reshape(-1).view(np.uint8).tobytes())


def install(ctx) -> Report:
    """Wrap the context's pml with the two checks. Idempotent."""
    rep = getattr(ctx, "_memchecker", None)
    if rep is not None:
        return rep
    rep = Report()
    ctx._memchecker = rep
    p2p = ctx.p2p
    orig_isend, orig_irecv = p2p.isend, p2p.irecv

    def isend(buf, dst, *a, **kw):
        try:
            before = _crc(buf)
        except Exception:
            return orig_isend(buf, dst, *a, **kw)   # device buffers etc.
        req = orig_isend(buf, dst, *a, **kw)
        tag = a[0] if a else kw.get("tag", 0)

        def check(_r):
            if _crc(buf) != before:
                rep.add(f"send buffer to rank {dst} (tag {tag}) was "
                        f"MODIFIED while the send was in flight — MPI "
                        f"forbids touching it before completion")
        if not req.done:
            # pending (rendezvous/CMA) sends only: an eager request is
            # complete at return and its payload was snapshotted into an
            # immutable frame before isend returned, so later buffer reuse
            # is legal AND harmless — flagging it would cry wolf on
            # conforming programs
            req.add_completion_callback(check)
        return req

    def irecv(buf, src=-1, *a, **kw):
        try:
            arr = np.asarray(buf)
            flat = arr.reshape(-1).view(np.uint8)
            flat[...] = POISON       # read-before-receive shows as garbage
        except Exception:
            pass
        return orig_irecv(buf, src, *a, **kw)

    p2p.isend, p2p.irecv = isend, irecv
    ctx._memchecker_orig = (orig_isend, orig_irecv)
    return rep


def uninstall(ctx) -> None:
    orig = getattr(ctx, "_memchecker_orig", None)
    if orig is not None:
        ctx.p2p.isend, ctx.p2p.irecv = orig
        del ctx._memchecker_orig
    if getattr(ctx, "_memchecker", None) is not None:
        del ctx._memchecker


def poisoned_fraction(buf) -> float:
    """Diagnostic: fraction of the buffer still carrying the poison pattern
    (≈1.0 for a buffer read before its receive completed)."""
    arr = np.asarray(buf).reshape(-1).view(np.uint8)
    return float(np.mean(arr == POISON)) if arr.size else 0.0
