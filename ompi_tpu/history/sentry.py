"""History sentry — trajectory changepoints onto the policy bus.

``HistorySentry.scan(store)`` walks every banked (platform, probe,
metric) trajectory plus each row's within-run step series through the
deterministic changepoint kernel and publishes ONE
``history_regression`` verdict per new episode onto the policy bus
(plane/kind/severity/evidence envelope — the PR 17 grammar), so the
pre-verified action vocabulary (arm demotion, route_weight,
quant-block resize) can answer a *trend*, not just a spike.

Scanning is idempotent: the same ledger scanned twice publishes
nothing new (episodes are keyed by platform/probe/metric/onset
run_id/direction).  A changepoint only becomes a verdict when it
points in the metric's *bad* direction — latency/byte/time gauges
regress upward, throughput/quality gauges regress downward; the
improvement direction is still reported (comm_doctor --history) but
never raises policy.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from . import changepoint as _cp
from .store import HistoryStore

# suffix/substring cues for gauges where HIGHER is worse (latency,
# wire bytes, recovery time, regression counters); everything else —
# tokens/s, busbw, goodput, SNR, acceptance — regresses DOWN
_HIGHER_IS_BAD = ("_ms", "_s", "_us", "bytes", "time_to", "latency",
                  "regressions", "violations", "stall", "itl", "ttft",
                  "p99", "p50")
# overrides where a cue substring would misclassify
_LOWER_IS_BAD = ("tokens_per_s", "busbw", "goodput", "mfu", "snr",
                 "accept", "speedup", "hit", "recovered_MBps")


def bad_direction(metric: str) -> str:
    m = metric.lower()
    for cue in _LOWER_IS_BAD:
        if cue in m:
            return "down"
    for cue in _HIGHER_IS_BAD:
        if cue in m:
            return "up"
    return "down"


class HistorySentry:
    """Idempotent trajectory judge; one verdict per episode."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._published: set = set()     # episode keys already raised
        self._verdicts: List[Dict[str, Any]] = []
        self._changepoints = 0

    # ---- scanning --------------------------------------------------

    def scan(self, store: HistoryStore,
             platform: Optional[str] = None) -> List[Dict[str, Any]]:
        """Judge every trajectory (and step series) in the store;
        returns the verdicts newly published by THIS scan."""
        fresh: List[Dict[str, Any]] = []
        combos = sorted({(r["platform"], r["probe"], r["metric"])
                         for r in store.rows()
                         if platform is None
                         or r["platform"] == platform})
        for plat, probe, metric in combos:
            traj = store.trajectory(probe, metric, plat)
            if not traj:
                continue
            run_ids = [rid for rid, _ in traj]
            values = [val for _, val in traj]
            for cp in _cp.detect(values):
                v = self._admit(plat, probe, metric,
                                run_ids[cp["index"]], cp,
                                scope="runs", runs=len(values))
                if v:
                    fresh.append(v)
            # within-run drift: the newest run's step series through
            # the same kernel; index maps to a step offset, the
            # changepoint still attributes to (metric, run_id)
            rid = run_ids[-1]
            series = store.series_of(rid, plat, probe, metric)
            for cp in _cp.detect(series):
                v = self._admit(plat, probe, metric, rid, cp,
                                scope="series", runs=len(series),
                                step_index=cp["index"])
                if v:
                    fresh.append(v)
        return fresh

    def _admit(self, platform: str, probe: str, metric: str,
               run_id: int, cp: Dict[str, Any], scope: str,
               runs: int, step_index: Optional[int] = None
               ) -> Optional[Dict[str, Any]]:
        key = (platform, probe, metric, scope, int(run_id),
               cp["direction"],
               step_index if step_index is not None else -1)
        with self._lock:
            if key in self._published:
                return None
            self._published.add(key)
            self._changepoints += 1
        if cp["direction"] != bad_direction(metric):
            return None                  # improvement: count, no raise
        mag_pct = round(100.0 * cp["magnitude"], 2)
        severity = "error" if abs(cp["magnitude"]) >= 0.25 else "warn"
        verdict = {"plane": "history", "kind": "history_regression",
                   "severity": severity, "probe": probe,
                   "metric": metric, "platform": platform,
                   "run_id": int(run_id), "direction": cp["direction"],
                   "magnitude_pct": mag_pct, "scope": scope,
                   "stat": cp["stat"], "runs": int(runs)}
        if step_index is not None:
            verdict["step_index"] = int(step_index)
        with self._lock:
            self._verdicts.append(verdict)
            if len(self._verdicts) > 64:
                del self._verdicts[:len(self._verdicts) - 64]
        from .. import trace
        if trace.enabled:
            trace.instant("history_changepoint", "history", args=verdict)
        from .. import policy
        if policy.enabled:
            policy.publish("history", "history_regression", severity,
                           evidence=verdict)
        return verdict

    # ---- queries ---------------------------------------------------

    def changepoints(self) -> int:
        with self._lock:
            return self._changepoints

    def verdicts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._verdicts)

    def rearm(self, platform: str, probe: str, metric: str) -> int:
        """Forget published episodes for one gauge — the explicit
        re-arm hook tests and the bench probe use to model 'episode
        over after a recovered run' across repeated scans."""
        with self._lock:
            drop = [k for k in self._published
                    if k[0] == platform and k[1] == probe
                    and k[2] == metric]
            for k in drop:
                self._published.discard(k)
            return len(drop)

    def reset(self) -> None:
        with self._lock:
            self._published.clear()
            self._verdicts.clear()
            self._changepoints = 0
