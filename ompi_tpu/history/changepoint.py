"""Deterministic changepoint kernel — Page-Hinkley / CUSUM over
MAD-normalized residuals.

One kernel judges both time axes: the run-over-run trajectory of a
banked gauge and the downsampled within-run step series.  The design
constraints come straight from the sentry grammar the other planes
already speak:

* **deterministic** — no wall clock, no randomness; an identical value
  sequence always yields an identical changepoint list.
* **min-run-count gate** — the first ``history_cp_min_runs`` points
  form the baseline (median + MAD); shorter inputs never judge, the
  same bar as ``perf_sentry_min_samples``.
* **sustain gate** — a trip needs ``history_cp_sustain`` consecutive
  out-of-band points; single outliers are noise.
* **episode semantics** — one trip per degradation episode; a
  recovered point (residual back inside the delta dead-band) re-arms
  the side, so a second regression later is a second episode.

The statistic is the classic one-sided CUSUM pair with drift term
``delta`` (in MAD-normalized units): for the "down" side

    g_t = -r_t - delta        r_t = (x_t - median) / (1.4826 * MAD)
    S_t = max(S_{t-1} + g_t, 0)

tripping when ``S_t > lambda`` with the sustain gate satisfied.  Onset
attribution is the standard CUSUM estimate: the first index after the
statistic last left zero — for a step injected at run k with a shift
large against the noise floor, that is exactly k.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..core import var as _var

_var.register("history", "cp", "min_runs", 5, type=int, level=3,
              help="Baseline length for the changepoint kernel; "
                   "trajectories shorter than this never judge (a "
                   "two-run ledger cannot define a regression).")
_var.register("history", "cp", "lambda", 8.0, type=float, level=3,
              help="CUSUM trip threshold in MAD-normalized units "
                   "(Page-Hinkley lambda).")
_var.register("history", "cp", "delta", 0.5, type=float, level=3,
              help="CUSUM drift dead-band in MAD-normalized units; "
                   "residuals inside +/-delta count as recovered and "
                   "re-arm the episode.")
_var.register("history", "cp", "sustain", 2, type=int, level=3,
              help="Consecutive out-of-band points required to trip "
                   "(single outliers are noise).")
_var.register("history", "cp", "rel_floor", 0.005, type=float, level=4,
              help="Noise-scale floor as a fraction of |baseline "
                   "median| — the minimum detectable effect size. A "
                   "near-constant baseline has a near-zero MAD, which "
                   "would otherwise inflate sub-noise wiggles into "
                   "changepoints (and a truly constant one would "
                   "divide by zero).")


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _mad(xs: List[float], med: float) -> float:
    return _median([abs(x - med) for x in xs])


def detect(values: List[float],
           min_runs: Optional[int] = None,
           lam: Optional[float] = None,
           delta: Optional[float] = None,
           sustain: Optional[int] = None) -> List[Dict[str, Any]]:
    """Scan one value sequence; return changepoints in onset order.

    Each changepoint: ``{"index", "confirm_index", "direction"
    ("down"/"up"), "magnitude" (relative shift vs baseline median),
    "stat"}``.  Indices are positions in ``values`` — the caller maps
    them back to run_ids (trajectory) or step offsets (series).
    """
    xs = [float(v) for v in values]
    n = len(xs)
    min_runs = max(int(_var.get("history_cp_min_runs", 5)
                       if min_runs is None else min_runs), 2)
    lam = float(_var.get("history_cp_lambda", 8.0)
                if lam is None else lam)
    delta = float(_var.get("history_cp_delta", 0.5)
                  if delta is None else delta)
    sustain = max(int(_var.get("history_cp_sustain", 2)
                      if sustain is None else sustain), 1)
    if n < min_runs + sustain:
        return []
    base = xs[:min_runs]
    med = _median(base)
    mad = _mad(base, med)
    rel = float(_var.get("history_cp_rel_floor", 0.005))
    scale = max(1.4826 * mad, abs(med) * rel)
    if scale <= 0.0:
        scale = 1.0                      # all-zero baseline
    out: List[Dict[str, Any]] = []
    # one-sided CUSUM per direction; each side carries its own episode
    # state so an up-shift never masks a later down-shift.  A single
    # in-band point fully re-arms the side (S, streak, trip) — the
    # same "good sample ends the episode" grammar as perf's sentry.
    sides = {"down": {"S": 0.0, "gs": [], "tripped": False},
             "up": {"S": 0.0, "gs": [], "tripped": False}}
    for t in range(min_runs, n):
        r = (xs[t] - med) / scale
        for direction, st in sides.items():
            g = (-r - delta) if direction == "down" else (r - delta)
            if g <= 0.0:
                st["S"] = 0.0
                st["gs"] = []
                st["tripped"] = False    # recovered point: re-arm
                continue
            st["S"] += g
            st["gs"].append(g)
            if (not st["tripped"] and st["S"] > lam
                    and len(st["gs"]) >= sustain):
                st["tripped"] = True
                # onset attribution: within the bad streak, the first
                # point whose increment reaches half the streak max —
                # for a step shift large against the noise floor that
                # is exactly the injection point even when a mildly
                # low pre-step point opened the streak early
                gmax = max(st["gs"])
                lead = next(i for i, gv in enumerate(st["gs"])
                            if gv >= 0.5 * gmax)
                onset = t - (len(st["gs"]) - 1) + lead
                seg = xs[onset:t + 1]
                mag = ((_median(seg) - med) / abs(med)
                       if med else _median(seg) - med)
                out.append({"index": onset, "confirm_index": t,
                            "direction": direction,
                            "magnitude": round(mag, 6),
                            "stat": round(st["S"], 3)})
    return sorted(out, key=lambda c: (c["index"], c["direction"]))
