"""Append-only, schema-versioned run ledger for the history plane.

One row per (run_id, platform, probe, metric): the headline gauge a
bench probe banked for that run — goodput/MFU, per-plane busbw and
bytes, serve tokens/s + ITL quantiles, spec-decode acceptance, quant
SNR dB, ft time-to-recover, verdict/decision counts.  Rows optionally
carry a deterministically downsampled ``series`` chunk (per-step
values within the run) so within-run drift is judged by the same
changepoint kernel as the run-over-run trajectory.

The on-disk form is JSONL (``BENCH_HISTORY.jsonl``): one JSON object
per line, append-only, tolerant of hand-edited or foreign lines on
load (same contract as ``perf.model.load_json``).  ``run_id`` is
supplied by the caller — the store never reads a wall clock; bench
derives the next id from ledger content (``next_run_id``), so an
identical ledger always yields an identical id sequence.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

SCHEMA = 1

Key = Tuple[int, str, str, str]          # (run_id, platform, probe, metric)


def downsample(series: List[float], cap: int) -> List[float]:
    """Deterministic bucket-mean downsample to at most ``cap`` points.

    Equal-width index buckets, mean per bucket — preserves slow drift
    (the thing the changepoint kernel judges) rather than extremes.
    """
    vals = [float(v) for v in series]
    n = len(vals)
    cap = max(int(cap), 2)
    if n <= cap:
        return vals
    out: List[float] = []
    for b in range(cap):
        lo = b * n // cap
        hi = max((b + 1) * n // cap, lo + 1)
        chunk = vals[lo:hi]
        out.append(sum(chunk) / len(chunk))
    return out


class HistoryStore:
    """In-memory mirror of the JSONL ledger; last row per key wins."""

    def __init__(self, series_cap: int = 64) -> None:
        self._lock = threading.Lock()
        self.series_cap = int(series_cap)
        self._rows: Dict[Key, Dict[str, Any]] = {}
        self._order: List[Key] = []      # first-append order per key
        self._appended = 0               # monotonic; survives dedup

    # ---- writes ----------------------------------------------------

    def record(self, run_id: int, platform: str, probe: str, metric: str,
               value: float, unit: str = "",
               series: Optional[List[float]] = None,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        row: Dict[str, Any] = {
            "schema": SCHEMA, "run_id": int(run_id),
            "platform": str(platform), "probe": str(probe),
            "metric": str(metric), "value": float(value),
            "unit": str(unit),
        }
        if series:
            row["series"] = downsample(series, self.series_cap)
        if extra:
            for k, v in extra.items():
                row.setdefault(k, v)
        key: Key = (row["run_id"], row["platform"], row["probe"],
                    row["metric"])
        with self._lock:
            if key not in self._rows:
                self._order.append(key)
            self._rows[key] = row
            self._appended += 1
        return row

    # ---- queries ---------------------------------------------------

    def rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(self._rows[k]) for k in self._order]

    def sample_count(self) -> int:
        """Monotonic count of record() calls (dedup never decrements)."""
        with self._lock:
            return self._appended

    def run_count(self) -> int:
        """Distinct (platform, probe, run_id) triples banked."""
        with self._lock:
            return len({(k[1], k[2], k[0]) for k in self._rows})

    def next_run_id(self, platform: str, probe: str) -> int:
        """1 + the highest banked run_id for (platform, probe) — the
        caller-supplied id bench uses; pure ledger content, no clock."""
        with self._lock:
            ids = [k[0] for k in self._rows
                   if k[1] == platform and k[2] == probe]
        return (max(ids) + 1) if ids else 1

    def metrics(self, probe: Optional[str] = None
                ) -> List[Tuple[str, str]]:
        """Sorted distinct (probe, metric) pairs."""
        with self._lock:
            got = {(k[2], k[3]) for k in self._rows
                   if probe is None or k[2] == probe}
        return sorted(got)

    def trajectory(self, probe: str, metric: str,
                   platform: Optional[str] = None
                   ) -> List[Tuple[int, float]]:
        """Chronological (run_id, value) for one gauge, sorted by
        run_id (the ledger's only notion of time)."""
        with self._lock:
            rows = [self._rows[k] for k in self._order
                    if k[2] == probe and k[3] == metric
                    and (platform is None or k[1] == platform)]
        return sorted(((r["run_id"], r["value"]) for r in rows),
                      key=lambda rv: rv[0])

    def series_of(self, run_id: int, platform: str, probe: str,
                  metric: str) -> List[float]:
        with self._lock:
            row = self._rows.get((int(run_id), platform, probe, metric))
        return list(row.get("series", [])) if row else []

    def latest(self, probe: str, metric: str,
               platform: Optional[str] = None
               ) -> Optional[Tuple[int, float]]:
        traj = self.trajectory(probe, metric, platform)
        return traj[-1] if traj else None

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self._order.clear()
            self._appended = 0

    # ---- persistence (JSONL) ---------------------------------------

    def load_jsonl(self, path: str) -> int:
        """Merge a JSONL ledger in; returns rows accepted.  Bad or
        foreign lines are skipped, not fatal — the ledger is meant to
        survive hand edits and version skew."""
        n = 0
        try:
            with open(path) as fh:
                lines = fh.readlines()
        except OSError:
            return 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
                self.record(row["run_id"], row["platform"], row["probe"],
                            row["metric"], row["value"],
                            unit=row.get("unit", ""),
                            series=row.get("series"),
                            extra={k: v for k, v in row.items()
                                   if k not in ("schema", "run_id",
                                                "platform", "probe",
                                                "metric", "value", "unit",
                                                "series")})
                n += 1
            except (KeyError, TypeError, ValueError):
                continue
        return n

    def save_jsonl(self, path: str) -> int:
        """Rewrite the full ledger atomically (tmp + os.replace) —
        used by the backfill tool; bench appends via append_jsonl."""
        rows = self.rows()
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            for row in rows:
                fh.write(json.dumps(row, sort_keys=True) + "\n")
        os.replace(tmp, path)
        return len(rows)


def append_jsonl(path: str, row: Dict[str, Any]) -> None:
    """Append one row to the on-disk ledger (the bench-probe path)."""
    with open(path, "a") as fh:
        fh.write(json.dumps(row, sort_keys=True) + "\n")
