"""History plane — fleet-lifetime telemetry with deterministic
changepoint detection (the ninth plane; docs/observability.md,
"History plane").

Three coupled pieces:

* ``store``       — append-only, schema-versioned run ledger
  (``BENCH_HISTORY.jsonl`` + per-run downsampled step-series chunks)
  keyed by (run_id, platform, probe, metric).  ``run_id`` is supplied
  by the caller — bench derives it from ledger content
  (``store.next_run_id``); the plane itself never reads a wall clock.
* ``changepoint`` — deterministic Page-Hinkley/CUSUM kernel over
  MAD-normalized residuals with min-run-count and sustain gates;
  identical trajectory in, identical changepoint list out.
* ``sentry``      — ``HistorySentry`` publishing one
  ``history_regression`` verdict per episode onto the policy bus so
  the pre-verified action vocabulary can answer a trend.

Disabled path (the default): ONE module attribute read
(``history.enabled``) per instrumented call site — the same bar as
every other plane, asserted in tests/test_history.py.  ``enable()``
rehydrates the store from the ``history_path`` ledger when it exists
(perf's ledger-autoload contract).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from ..core import var as _var
from .changepoint import detect  # noqa: F401
from .sentry import HistorySentry, bad_direction  # noqa: F401
from .store import HistoryStore, append_jsonl, downsample  # noqa: F401

_var.register("history", "", "enabled", False, type=bool, level=3,
              help="Master switch for the history plane (run ledger, "
                   "changepoint sentry). Off by default; the disabled "
                   "path is one attribute read per call site.")
_var.register("history", "", "path", "", type=str, level=3,
              help="Path of the BENCH_HISTORY.jsonl ledger to "
                   "rehydrate at enable() time and to append each "
                   "banked row to (empty: in-memory only).")
_var.register("history", "", "series_cap", 64, type=int, level=4,
              help="Deterministic bucket-mean downsample cap for "
                   "per-run step-series chunks banked with a row.")

enabled: bool = bool(_var.get("history_enabled", False))

store = HistoryStore(series_cap=int(_var.get("history_series_cap", 64)))
sentry = HistorySentry()

PVARS = ("history_runs", "history_samples", "history_changepoints")


def enable() -> None:
    global enabled
    path = str(_var.get("history_path", "") or "")
    if path and os.path.exists(path):
        store.load_jsonl(path)
    enabled = True


def disable() -> None:
    global enabled
    enabled = False


def _on_enabled_var(v: Any) -> None:
    # mid-run OMPI_TPU_HISTORY_ENABLED / set_cli writes take effect;
    # the watcher fires on CHANGE only so enable()/disable() stay in
    # charge
    global enabled
    enabled = bool(v)


_var.watch("history_enabled", _on_enabled_var)


# ---- the bench-probe write path --------------------------------------

def record_run(run_id: int, platform: str, probe: str, metric: str,
               value: float, unit: str = "",
               series: Optional[List[float]] = None,
               extra: Optional[Dict[str, Any]] = None
               ) -> Optional[Dict[str, Any]]:
    """Bank one headline gauge for one run: into the in-memory store
    AND appended to the on-disk ledger when ``history_path`` is set.
    No-op while the plane is disabled (probes call unconditionally
    behind the one-attribute-read gate)."""
    if not enabled:
        return None
    row = store.record(run_id, platform, probe, metric, value,
                       unit=unit, series=series, extra=extra)
    path = str(_var.get("history_path", "") or "")
    if path:
        append_jsonl(path, row)
    return row


def next_run_id(platform: str, probe: str) -> int:
    """The caller-supplied run id: 1 + highest banked for this
    (platform, probe) — ledger content only, never a clock."""
    return store.next_run_id(platform, probe)


def scan(platform: Optional[str] = None) -> List[Dict[str, Any]]:
    """Run the changepoint sentry over every banked trajectory;
    returns verdicts newly published by this scan."""
    return sentry.scan(store, platform)


# ---- the bench artifact schema ---------------------------------------

# one entry per wired bench probe: (banked artifact stem, dotted paths
# of the extra headline gauges recorded beside the doc's own
# metric/value row).  The SAME map drives the live probe append in
# bench.py and the tools/history_backfill.py one-shot, so the two can
# never disagree about what a probe's trajectory contains.
PROBE_GAUGES: Dict[str, Any] = {
    "goodput":   ("GOODPUT", ("mfu_pct", "overlap_efficiency")),
    "traffic":   ("TRAFFIC", ("hot_edge.ratio", "planes.ici")),
    "pod":       ("BENCH_POD", ()),
    "reshard":   ("RESHARD", ("busbw_GBps", "peak_bytes")),
    "elastic":   ("ELASTIC", ("steps_lost", "wire_bytes")),
    "moe":       ("MOE", ("skew.trips",)),
    "numerics":  ("NUMERICS", ("snr_db_last",)),
    "serve":     ("SERVE", ("speculative.acceptance_rate",
                            "fused.tokens_per_s",
                            "quant.quant_wire_bytes")),
    "fleet":     ("FLEET", ("itl_p99_ms_colocated",
                            "itl_p99_ms_disaggregated",
                            "migration.bytes")),
    "slo":       ("REQUESTS", ("report.slo_breaches",
                               "report.completed")),
    "selfdrive": ("POLICY", ("time_to_retune_steps", "recovered_MBps",
                             "report.verdicts_published",
                             "report.decisions_applied")),
}


def _dig(doc: Dict[str, Any], path: str) -> Any:
    cur: Any = doc
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def headline_rows(probe: str, doc: Dict[str, Any]
                  ) -> List[Any]:
    """The (metric, value, unit) rows one banked probe doc yields:
    the doc's own metric/value pair plus the probe's extra headline
    gauges from ``PROBE_GAUGES`` (non-numeric/missing paths skipped)."""
    rows: List[Any] = []
    metric, value = doc.get("metric"), doc.get("value")
    if metric is not None and isinstance(value, (int, float)):
        rows.append((str(metric), float(value),
                     str(doc.get("unit", ""))))
    _, extras = PROBE_GAUGES.get(probe, ("", ()))
    for path in extras:
        v = _dig(doc, path)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        rows.append((path.replace(".", "_"), float(v), ""))
    return rows


# ---- pvars + Prometheus ----------------------------------------------

def pvar_value(name: str) -> float:
    if name == "history_runs":
        return float(store.run_count())
    if name == "history_samples":
        return float(store.sample_count())
    if name == "history_changepoints":
        return float(sentry.changepoints())
    raise KeyError(name)


def prometheus_rows(rank: int = 0, comm: str = "world",
                    prefix: str = "ompi_tpu") -> List[str]:
    """Latest banked value per gauge for the Prometheus exporter:
    ``<prefix>_history_metric{probe,metric}``."""
    pairs = store.metrics()
    if not pairs:
        return []
    name = f"{prefix}_history_metric"
    rows = [f"# HELP {name} Latest banked run value per history-plane "
            "gauge (run trajectory head).",
            f"# TYPE {name} gauge"]
    for probe, metric in pairs:
        got = store.latest(probe, metric)
        if got is None:
            continue
        _, val = got
        rows.append(f'{name}{{rank="{int(rank)}",comm="{comm}",'
                    f'probe="{probe}",metric="{metric}"}} {val:.9g}')
    return rows


# ---- report / reset --------------------------------------------------

def report() -> Dict[str, Any]:
    """Structured snapshot for comm_doctor --history."""
    gauges = []
    for probe, metric in store.metrics():
        traj = store.trajectory(probe, metric)
        values = [v for _, v in traj]
        gauges.append({"probe": probe, "metric": metric,
                       "runs": len(traj),
                       "first_run_id": traj[0][0] if traj else None,
                       "last_run_id": traj[-1][0] if traj else None,
                       "latest": values[-1] if values else None,
                       "values": values})
    return {"runs": store.run_count(),
            "samples": store.sample_count(),
            "changepoints": sentry.changepoints(),
            "gauges": gauges,
            "verdicts": sentry.verdicts()}


def reset() -> None:
    store.clear()
    sentry.reset()
