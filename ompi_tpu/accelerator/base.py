"""Accelerator framework contract.

TPU-native re-design of the reference's accelerator framework interface
(opal/mca/accelerator/accelerator.h):
  * ``check_addr`` — buffer-type interrogation (accelerator.h:171): is this
    memory device-resident, and on which device(s)?  Here the unit is a
    framework-level array object (jax.Array), not a raw pointer — PJRT never
    exposes raw device pointers to clients.
  * streams/events (accelerator.h:184-243) — PJRT executions are ordered per
    device; the observable completion object is the array's ready-future,
    wrapped as :class:`Event` (record/query/wait).
  * async memcpy (accelerator.h:265) — ``memcpy_d2h_async`` returns an Event
    per bounded chunk so large device payloads stage without a monolithic
    blocking transfer; H2D goes through ``device_put`` (asynchronous by PJRT
    semantics — it returns before the copy lands).
  * mem alloc (accelerator.h:324) — ``mem_alloc`` creates an HBM buffer.
  * IPC handles (accelerator.h:395-481) are deliberately absent: TPU device
    memory moves between processes over ICI via compiled collectives (the
    device plane), never by exporting HBM handles — SURVEY.md §5.8.

Device-side non-contiguous pack/unpack (the reference packs on host,
opal_convertor.c:245) is implemented with XLA gather/scatter over a cached
element-index map — see ``JaxAccelerator.pack_device``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class AddrInfo:
    """Result of check_addr for device-resident memory (accelerator.h:171
    flags + device id out-params)."""

    platform: str                 # "tpu" | "cpu" | "gpu" (PJRT platform name)
    device_ids: List[int]         # addressable device ids holding shards
    nbytes: int
    dtype: np.dtype
    shape: Tuple[int, ...]
    sharded: bool = False         # True when the array spans >1 device


class Event:
    """Completion object (accelerator.h:184-243 record/query/wait/sync).

    ``query()`` is non-blocking; ``wait()`` blocks until the recorded work
    (device compute producing the arrays, or their host copies) is done.
    """

    def query(self) -> bool:  # pragma: no cover - interface
        return True

    def wait(self) -> None:  # pragma: no cover - interface
        pass


class CompletedEvent(Event):
    pass


@dataclass
class StagingJob:
    """An in-flight chunked D2H staging transfer: one Event per chunk plus
    the host-side chunk destinations, joined by :meth:`wait`."""

    chunks: List[object] = field(default_factory=list)   # per-chunk handles
    events: List[Event] = field(default_factory=list)

    def query(self) -> bool:
        return all(e.query() for e in self.events)

    def wait(self) -> bytes:
        raise NotImplementedError


class AcceleratorModule:
    """Component module contract. ``null`` declines everything (host-only);
    ``jax`` implements the PJRT-backed paths."""

    name = "base"

    # -- interrogation ------------------------------------------------------
    def check_addr(self, buf) -> Optional[AddrInfo]:
        return None

    # -- memory -------------------------------------------------------------
    def mem_alloc(self, shape: Sequence[int], dtype, device=None):
        raise NotImplementedError

    # -- transfers ----------------------------------------------------------
    def memcpy_d2h_async(self, arr, chunk_bytes: int) -> "StagingJob":
        raise NotImplementedError

    def memcpy_h2d(self, host: np.ndarray, like=None):
        raise NotImplementedError

    # -- datatype staging (pml entry points) --------------------------------
    def stage_out(self, buf, datatype, count) -> bytes:
        """Device buffer → packed host bytes (send side)."""
        raise NotImplementedError

    def stage_in(self, data: bytes, template, datatype, count):
        """Packed host bytes → new device array shaped like ``template``
        (recv side); gap bytes of non-contiguous datatypes keep the
        template's values, matching receive semantics on host buffers."""
        raise NotImplementedError


class DeviceBuffer:
    """Mutable holder for a device array used as a *receive* destination.

    jax arrays are immutable, so a receive cannot scribble into the caller's
    array the way the reference writes through a raw pointer; receiving into
    a DeviceBuffer replaces ``.array`` with the received contents instead.
    """

    __slots__ = ("array",)

    def __init__(self, array) -> None:
        self.array = array

    def __repr__(self) -> str:  # pragma: no cover
        return f"DeviceBuffer({self.array!r})"
