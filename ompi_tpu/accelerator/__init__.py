"""Accelerator framework: device-memory interrogation + staging contract.

≙ the reference's ``accelerator`` MCA framework (opal/mca/accelerator/
accelerator.h:171-557) with components cuda/rocm/ze/null; here the components
are ``jax`` (PJRT-backed, jaxacc.py) and ``null`` (host-only). Selection is
the standard priority query through the component registry — ``jax`` wins
whenever jax imports; ``--mca accelerator null`` forces the host-only path
exactly like ``--mca accelerator null`` does in the reference.

Consumers (pml, coll/xla) call :func:`current` / :func:`check_addr` instead
of type-sniffing jax at the call site.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..core.component import Component, component, frameworks
from .base import (AcceleratorModule, AddrInfo, CompletedEvent, DeviceBuffer,
                   Event, StagingJob)

__all__ = ["AcceleratorModule", "AddrInfo", "CompletedEvent", "DeviceBuffer",
           "Event", "StagingJob", "current", "check_addr"]


class NullAccelerator(AcceleratorModule):
    """Host-only module (≙ accelerator/null): check_addr always says host."""

    name = "null"

    def check_addr(self, buf) -> Optional[AddrInfo]:
        return None


@component("accelerator", "null", priority=1)
class NullComponent(Component):
    def query(self, scope):
        return self.priority, NullAccelerator()


@component("accelerator", "jax", priority=50)
class JaxComponent(Component):
    def open(self) -> bool:
        try:
            import jax  # noqa: F401
        except ImportError:  # pragma: no cover
            return False
        return True

    def query(self, scope):
        from .jaxacc import JaxAccelerator

        return self.priority, JaxAccelerator()


_lock = threading.Lock()
_current: Optional[AcceleratorModule] = None


def current() -> AcceleratorModule:
    """The selected accelerator module (process-wide, selected once)."""
    global _current
    if _current is None:
        with _lock:
            if _current is None:
                try:
                    _, mod = frameworks.framework("accelerator").select(None)
                except RuntimeError:
                    mod = NullAccelerator()
                _current = mod
    return _current


def check_addr(buf) -> Optional[AddrInfo]:
    import sys

    # Fast path: if jax was never imported in this process, no buffer can be
    # device-resident — don't drag the jax runtime into host-only ranks
    # (the reference's check_addr is likewise a cheap pointer interrogation,
    # accelerator.h:171).
    if "jax" not in sys.modules and not isinstance(buf, DeviceBuffer):
        return None
    return current().check_addr(buf)
