"""accelerator/jax — the PJRT-backed accelerator component.

Plays the role of accelerator/cuda (opal/mca/accelerator/cuda/
accelerator_cuda.c:26,74) for the TPU stack: buffer interrogation, device
allocation, chunked asynchronous device↔host staging with completion events,
and device-side pack/unpack of non-contiguous datatypes.

Device pack design (vs the reference's host-only convertor,
opal/datatype/opal_convertor.c:245): for a homogeneous derived datatype whose
segment offsets and extent are item-aligned, build the element-index map once
(cached on the datatype), then a single XLA ``take`` gathers the packed
element stream *on device* — one fused gather kernel on the MXU-adjacent
vector units — and only the packed (smaller) result crosses HBM→host.
Unpack is the mirrored ``.at[idx].set`` scatter after one H2D of the packed
stream. Datatypes that don't satisfy the alignment constraints fall back to
full staging + the host convertor.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core import var as _var
from .base import (AcceleratorModule, AddrInfo, CompletedEvent, DeviceBuffer,
                   Event, StagingJob)

_var.register("accelerator", "jax", "stage_chunk", default=4 << 20, type=int,
              level=4, help="Bound (bytes) on each async D2H staging chunk "
              "used when pml stages device payloads to host.")


class JaxEvent(Event):
    """Readiness of a set of jax arrays (device compute or host copies)."""

    def __init__(self, arrays: Sequence) -> None:
        self._arrays = list(arrays)

    def query(self) -> bool:
        return all(a.is_ready() for a in self._arrays)

    def wait(self) -> None:
        for a in self._arrays:
            a.block_until_ready()


class _D2HJob(StagingJob):
    def wait(self) -> bytes:
        for e in self.events:
            e.wait()
        return b"".join(np.asarray(c).tobytes() for c in self.chunks)


def _device_index_map(dt, count: int, device) -> Optional["object"]:
    """The gather map as a DEVICE-RESIDENT array, cached per (count,
    device): without this every pack/unpack re-uploads the host index
    array — a hidden H2D on the supposedly device-only path."""
    import jax

    idx = _index_map(dt, count)
    if idx is None:
        return None
    cache = getattr(dt, "_dev_idx_on", None)
    if cache is None:
        cache = dt._dev_idx_on = {}
    # device ids are per-backend: key on platform too, or a cpu-committed
    # map could be handed to a tpu gather in a dual-backend process
    key = (count, getattr(device, "platform", None),
           getattr(device, "id", device))
    hit = cache.get(key)
    if hit is None:
        hit = cache[key] = jax.device_put(idx, device)
    return hit


def _gather_packed(flat, idx):
    """jitted: one fused device gather — the whole pack program."""
    return flat[idx]


def _scatter_unpacked(flat, idx, vals):
    """jitted: one fused device scatter — the whole unpack program."""
    return flat.at[idx].set(vals)


def _index_map(dt, count: int) -> Optional[np.ndarray]:
    """Item-index gather map for (datatype, count), or None when the type
    isn't expressible as an item-aligned gather. Cached on the datatype the
    same way the convertor caches its native segment table."""
    cache = getattr(dt, "_dev_idx", None)
    if cache is None:
        cache = dt._dev_idx = {}
    if count in cache:
        return cache[count]
    if not dt.is_homogeneous:
        cache[count] = None
        return None
    item = dt.segments[0].dtype.itemsize
    if dt.extent % item:
        cache[count] = None
        return None
    one: List[int] = []
    for s in dt.segments:
        if s.offset % item:
            cache[count] = None
            return None
        start = s.offset // item
        one.extend(range(start, start + s.count))
    stride = dt.extent // item
    idx = (np.asarray(one, np.int32)[None, :]
           + (np.arange(count, dtype=np.int32) * stride)[:, None]).ravel()
    cache[count] = idx
    return idx


class JaxAccelerator(AcceleratorModule):
    name = "jax"

    # -- interrogation (accelerator.h:171 check_addr) -----------------------
    def check_addr(self, buf) -> Optional[AddrInfo]:
        import jax

        if isinstance(buf, DeviceBuffer):
            buf = buf.array
        if not isinstance(buf, jax.Array):
            return None
        devs = sorted(buf.devices(), key=lambda d: d.id)
        return AddrInfo(platform=devs[0].platform,
                        device_ids=[d.id for d in devs],
                        nbytes=buf.nbytes, dtype=np.dtype(buf.dtype),
                        shape=tuple(buf.shape), sharded=len(devs) > 1)

    # -- memory (accelerator.h:324 mem_alloc) -------------------------------
    def mem_alloc(self, shape: Sequence[int], dtype, device=None):
        import jax
        import jax.numpy as jnp

        arr = jnp.zeros(tuple(shape), dtype=dtype)
        if device is not None:
            arr = jax.device_put(arr, device)
        return arr

    # -- transfers (accelerator.h:265 async memcpy) -------------------------
    def memcpy_d2h_async(self, arr, chunk_bytes: int) -> _D2HJob:
        """Start D2H of ``arr`` in ≤chunk_bytes slices; each slice's
        ``copy_to_host_async`` overlaps with the next slice kernel."""
        flat = arr.reshape(-1)
        item = np.dtype(arr.dtype).itemsize
        per = max(1, chunk_bytes // item)
        job = _D2HJob()
        for off in range(0, flat.size, per):
            c = flat[off:off + per]
            c.copy_to_host_async()
            job.chunks.append(c)
            job.events.append(JaxEvent([c]))
        if not job.chunks:
            job.events.append(CompletedEvent())
        return job

    def memcpy_h2d(self, host: np.ndarray, like=None):
        import jax

        if like is not None:
            return jax.device_put(host, list(like.devices())[0])
        return jax.device_put(host)

    # -- device pack/unpack + pml staging -----------------------------------
    def pack_device(self, arr, datatype, count):
        """Gather the packed element stream on device; None if the datatype
        can't be expressed as an item-aligned gather. The gather runs as
        ONE jitted program with a device-cached index map — no host
        transfer anywhere in the pack (HLO-checked in tests)."""
        import jax

        idx = _index_map(datatype, count)
        if idx is None:
            return None
        flat = arr.reshape(-1)
        if idx.size and idx[-1] >= flat.size:
            return None   # datatype describes more extent than the array has
        dev = sorted(arr.devices(), key=lambda d: d.id)[0] \
            if isinstance(arr, jax.Array) else None
        idx_dev = _device_index_map(datatype, count, dev)
        return jax.jit(_gather_packed)(flat, idx_dev)

    def stage_out(self, buf, datatype, count) -> bytes:
        from ..datatype import Convertor

        if isinstance(buf, DeviceBuffer):
            buf = buf.array
        chunk = int(_var.get("accelerator_jax_stage_chunk", 4 << 20))
        if datatype is None or datatype.is_contiguous:
            flat = buf.reshape(-1)
            if count is not None:
                item = np.dtype(buf.dtype).itemsize
                esize = datatype.size if datatype is not None else item
                flat = flat[:(esize * count) // item]
            return self.memcpy_d2h_async(flat, chunk).wait()
        packed = self.pack_device(buf, datatype, count)
        if packed is not None:
            return self.memcpy_d2h_async(packed, chunk).wait()
        host = np.asarray(buf)          # full staging fallback
        return Convertor(host, datatype, count).pack()

    def stage_in(self, data: bytes, template, datatype, count):
        from ..datatype import Convertor

        if datatype is None or datatype.is_contiguous:
            host = np.frombuffer(data, np.dtype(template.dtype))
            if host.size == template.size:
                host = host.reshape(template.shape)
                return self.memcpy_h2d(host, like=template)
            # short message: fill the front, keep the template's tail
            full = np.asarray(template).reshape(-1).copy()
            full[:host.size] = host
            return self.memcpy_h2d(full.reshape(template.shape),
                                   like=template)
        import jax
        idx = _index_map(datatype, count)
        if idx is not None and (not idx.size or idx[-1] < template.size):
            vals = np.frombuffer(data, datatype.base_np_dtype())
            dev = sorted(template.devices(), key=lambda d: d.id)[0] \
                if isinstance(template, jax.Array) else None
            if vals.size == idx.size:
                idx_dev = _device_index_map(datatype, count, dev)
            else:                      # short message: front of the stream
                idx_dev = self.memcpy_h2d(idx[:vals.size], like=template)
            dev_vals = self.memcpy_h2d(vals, like=template)
            flat = jax.jit(_scatter_unpacked)(
                template.reshape(-1), idx_dev, dev_vals)
            return flat.reshape(template.shape)
        host = np.asarray(template).copy()   # full staging fallback
        Convertor(host, datatype, count).unpack(data)
        return self.memcpy_h2d(host, like=template)
