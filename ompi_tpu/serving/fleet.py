"""Serving fleet — goodput-routed replicas + prefill/decode split.

The layer above ``ServingEngine``: one serving config where replica
count (R) and tp degree are the ONLY knobs.  ``ServingFleet`` carves
R disjoint tp-meshes out of the device list (``make_mesh`` over device
subsets — the GSPMD "same application code from 8 to 6 000 chips"
shape), builds one engine per replica against a SHARED spc counter
pool, and admits one Poisson stream through a deterministic
goodput-weighted front-end router (``scheduler.FleetRouter``).

Two topologies over the same chips:

* **colocated** (``prefill_replicas=0``) — every replica runs its own
  continuous-batching loop, prefill and decode serialized on the same
  engine: a long prompt's prefill-bucket call blocks every in-flight
  sequence on that replica for its full duration (the head-of-line ITL
  spike the bench measures).
* **disaggregated** — the first ``prefill_replicas`` replicas ONLY
  prefill; the rest ONLY decode.  A finished prompt's KV pages migrate
  prefill→decode through :func:`ServingFleet.migrate`: a KV-page
  migration IS a source-mesh→dest-mesh transition, so it rides
  ``parallel.reshard.cross_reshard`` unchanged — a 2×tp bridge mesh
  over the union of both replicas' devices, the real pages on the
  prefill half and a zero half resident on the decode devices, dest
  spec replicated over ``fleet`` so the plan emits exactly tp
  cross-device pieces (prefill j → decode j, wire == page payload
  bytes) plus tp zero-wire local pieces.  The move inherits the whole
  reshard contract for free: ``reshard_peak_factor`` peak bound
  (peak == 4·shard == the 2.0× default bound exactly), ONE audited
  ``decide:reshard`` event, per-pair ``traffic.note_reshard_step``
  attribution (fleet-wide edge-sum == wire-pvar conservation), and the
  ``reshard_*`` pvars.  On top of that the fleet charges ``simdcn``
  for the hop whenever the bridge's ``fleet`` axis classifies as DCN
  (``topo_sim_dcn_axes=fleet`` makes the cross-replica topology
  CI-drivable on 8 CPU devices) and emits a ``serve:migrate`` span +
  the fleet ledger row (``serving.note_migration``).

Time is the same virtual-clock model the single-replica scheduler
uses, with one clock per replica on a common global axis: the prefill
replica works ahead on its own timeline, and a migrated sequence joins
the decode batch only once the decode clock reaches the handoff time —
so the decode loop NEVER idles through a prefill, which is exactly the
p99-ITL win the bench gates on.  Prefill capacity is modeled per
prefill↔decode pairing (decode replica i prefills on prefill replica
``i % n_prefill``'s lane).

The ``hot_replica`` sentry (p99-ITL skew vs the fleet median, episode
semantics) publishes on the PR 17 policy bus; the pre-verified
``route_weight`` action (policy/engine builtin) shifts admission
weight through ``serving.apply_route_weight`` with one audited
``decide:fleet_route`` naming its verdict — the router reads the bias
on every assignment.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import importlib

from .. import serving, trace
from ..core import var as _var
from ..parallel import simdcn

# The ``parallel`` package re-exports the ``reshard`` *function*, which
# shadows the module attribute of the same name — import the module
# explicitly (same trick as ft/elastic.py).
_reshard = importlib.import_module("ompi_tpu.parallel.reshard")
from ..parallel.collectives import DeviceComm
from ..parallel.hierarchy import classify_axes
from ..parallel.mesh import make_mesh
from . import requests as _requests
from .engine import ServingEngine
from .scheduler import (ContinuousBatchingScheduler, FleetRouter,
                        Request, _Active)


def _j_page_import_build():
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def _imp(pool, pages, idx):
        return pool.at[:, idx].set(pages)
    return _imp


_j_page_import = _j_page_import_build()


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    k = min(int(round(q * (len(s) - 1))), len(s) - 1)
    return s[k]


class _Replica:
    """One fleet member: its mesh/DeviceComm/engine plus the role and
    the prefill-lane clock the disaggregated scheduler advances."""

    def __init__(self, idx: int, role: str, devices: List,
                 dc: DeviceComm, engine: ServingEngine) -> None:
        self.idx = idx
        self.role = role                   # "serve" | "prefill" | "decode"
        self.devices = devices
        self.dc = dc
        self.engine = engine
        self.prefills = 0
        self.prefill_s = 0.0
        self.clock = 0.0                   # prefill-lane virtual time


class _ReplicaScheduler(ContinuousBatchingScheduler):
    """The base continuous loop plus per-replica ITL attribution."""

    def __init__(self, replica: _Replica, requests: List[Request],
                 **kw: Any) -> None:
        super().__init__(replica.engine, requests, **kw)
        self.replica = replica
        self.rank = replica.idx            # request-plane lane
        self.itl: List[float] = []
        self._last_t: Dict[Any, float] = {}

    def _on_token(self, st: _Active) -> None:
        rid = st.req.rid
        last = self._last_t.get(rid)
        if last is not None:
            self.itl.append(self.clock - last)
        self._last_t[rid] = self.clock


class _DisaggScheduler(_ReplicaScheduler):
    """Decode-replica loop with prefill+migration on a separate lane.

    The prefill replica runs on its own virtual clock (it may work
    AHEAD of the decode clock — it is a different machine), gated only
    by request arrival and decode-cache admission backpressure.  A
    prefilled sequence's pages migrate immediately (reserving the
    decode slot), then the sequence joins the decode batch once the
    decode clock reaches the handoff time — decode steps for other
    in-flight sequences keep running throughout, so prefill duration
    never lands in their inter-token gaps."""

    def __init__(self, fleet: "ServingFleet", pre: _Replica,
                 dec: _Replica, requests: List[Request],
                 **kw: Any) -> None:
        super().__init__(dec, requests, **kw)
        self.fleet = fleet
        self.pre = pre
        self.ready: List[Tuple[float, Request, int, int]] = []

    def _admissible(self) -> bool:
        return False                       # admission goes via the pump

    def _pump_prefill(self) -> None:
        pre, dec = self.pre, self.replica
        pcache = pre.engine.cache
        while self.pending:
            req = self.pending[0]
            if req.arrival > max(self.clock, pre.clock):
                break
            if not dec.engine.cache.can_admit(len(req.prompt),
                                              req.max_new):
                break                      # decode-cache backpressure
            self.pending.pop(0)
            pre.clock = max(pre.clock, req.arrival)
            if serving.enabled:
                serving.note_admit(req.rid, len(req.prompt),
                                   req.max_new, req.arrival, pre.clock)
            if _requests.enabled:
                _requests.note_admit(req.rid, req.arrival, pre.clock,
                                     len(req.prompt), req.max_new,
                                     replica=dec.idx, rank=pre.idx)
            pslot = pcache.admit(len(req.prompt), req.max_new)
            t0 = time.perf_counter()
            first, _ = pre.engine.prefill(pslot, req.prompt,
                                          rid=req.rid)
            pdur = time.perf_counter() - t0
            # bench --slo fault injection: a slowed prefill replica is
            # a multiplier on the VIRTUAL prefill duration, so the lane
            # clock, the goodput split and the request plane's prefill
            # stage all degrade consistently
            scale = float(_var.get("serve_req_chaos_prefill_scale", 1.0))
            if scale != 1.0:
                pdur *= max(scale, 0.0)
            pre.clock += pdur
            pre.prefills += 1
            pre.prefill_s += pdur
            if serving.enabled:
                serving.note_prefill(pdur, len(req.prompt))
                serving.note_token(req.rid, pre.clock)
            if _requests.enabled:
                _requests.note_stage(req.rid, "prefill",
                                     pre.clock - pdur, pre.clock,
                                     rank=pre.idx)
                _requests.note_token(req.rid, pre.clock, rank=pre.idx)
            self._last_t[req.rid] = pre.clock
            eos = (req.eos_id if req.eos_id is not None else self.eos_id)
            if (eos is not None and first == eos) or req.max_new <= 1:
                # done at the first token: nothing to migrate
                pcache.release(pslot)
                reason = ("eos" if eos is not None and first == eos
                          else "max_new")
                self.results[req.rid] = {
                    "rid": req.rid, "tokens": [first], "reason": reason,
                    "finished_at": pre.clock}
                if serving.enabled:
                    serving.note_evict(req.rid, reason, pre.clock)
                if _requests.enabled:
                    _requests.note_finish(req.rid, pre.clock, reason)
                continue
            t0 = time.perf_counter()
            dslot = self.fleet.migrate(pre, dec, pslot,
                                       len(req.prompt), req.max_new,
                                       rid=req.rid)
            mdur = time.perf_counter() - t0
            # bench --slo fault injection: a degraded migration lane is
            # extra virtual delay on every KV hand-off hop
            mdur += 1e-3 * float(_var.get("serve_req_chaos_migrate_ms",
                                          0.0))
            pre.clock += mdur
            pcache.release(pslot)
            if _requests.enabled:
                last = _reshard.report()["last"] or {}
                _requests.note_stage(
                    req.rid, "migrate", pre.clock - mdur, pre.clock,
                    rank=pre.idx, src=pre.idx, dst=dec.idx,
                    wire_bytes=int(last.get("wire_bytes", 0)),
                    link="decide:reshard")
            self.ready.append((pre.clock, req, dslot, first))

    def _join_ready(self) -> None:
        rest = []
        for t, req, dslot, first in self.ready:
            if t <= self.clock:
                self.active[dslot] = _Active(req=req, slot=dslot,
                                             tokens=[first], last=first)
                if _requests.enabled:
                    _requests.note_stage(req.rid, "join", t, self.clock,
                                         rank=self.replica.idx)
            else:
                rest.append((t, req, dslot, first))
        self.ready = rest

    def run(self, max_steps: int = 100000) -> Dict[str, Any]:
        while self.pending or self.ready or self.active:
            self._pump_prefill()
            self._join_ready()
            if not self.active:
                if self.ready:
                    # idle: jump the decode clock to the next handoff
                    self.clock = max(self.clock,
                                     min(t for t, *_ in self.ready))
                elif self.pending:
                    self.clock = max(self.clock,
                                     self.pending[0].arrival)
                else:
                    break
                continue
            self._step()
            if self.decode_steps >= max_steps:
                raise RuntimeError(f"fleet scheduler exceeded "
                                   f"{max_steps} decode steps without "
                                   "draining")
        return self.summary()


class ServingFleet:
    """R data-parallel serving replicas over disjoint tp-meshes.

    ``params`` arrive in the train layout ONCE (host or replicated);
    each replica shards them onto its own submesh and converts to the
    decode layout at engine init.  ``prefill_replicas=0`` is the
    colocated topology; ``prefill_replicas=k`` dedicates the first k
    replicas to prefill and the rest to decode."""

    def __init__(self, params: Dict, cfg, *, replicas: int = 1,
                 tp: int = 8, prefill_replicas: int = 0,
                 devices: Optional[List] = None, n_pages: int = 96,
                 page_size: int = 8, max_seqs: int = 8,
                 spc=None, router: Optional[FleetRouter] = None,
                 layout: str = "train") -> None:
        from ..models import transformer as tfm
        devs = list(devices) if devices is not None else \
            list(jax.devices())
        need = int(replicas) * int(tp)
        if len(devs) < need:
            raise ValueError(f"ServingFleet: {replicas} replicas × "
                             f"tp={tp} needs {need} devices, have "
                             f"{len(devs)}")
        if prefill_replicas < 0 or prefill_replicas >= replicas and \
                prefill_replicas > 0:
            raise ValueError(
                f"ServingFleet: prefill_replicas={prefill_replicas} "
                f"must leave at least one decode replica of {replicas}")
        self.cfg = cfg
        self.tp = int(tp)
        self.spc = spc
        self.mode = ("disaggregated" if prefill_replicas
                     else "colocated")
        self.replicas: List[_Replica] = []
        for r in range(int(replicas)):
            sub = devs[r * tp:(r + 1) * tp]
            mesh = make_mesh({"tp": tp}, devices=sub)
            dc = DeviceComm(mesh, "tp")
            dc.spc = spc
            sharded = (tfm.shard_params(params, mesh, cfg)
                       if layout == "train" else params)
            eng = ServingEngine(dc, sharded, cfg, n_pages=n_pages,
                                page_size=page_size, max_seqs=max_seqs,
                                layout=layout)
            role = ("prefill" if r < prefill_replicas
                    else ("decode" if prefill_replicas else "serve"))
            self.replicas.append(_Replica(r, role, sub, dc, eng))
        self.prefill_ids = list(range(prefill_replicas))
        self.serve_ids = list(range(prefill_replicas, int(replicas)))
        self.router = router if router is not None else \
            FleetRouter(len(self.serve_ids))
        self._bridges: Dict[Tuple[int, int], Any] = {}
        self._hot: Dict[int, bool] = {}
        serving.set_fleet_replicas(int(replicas))
        for rep in self.replicas:
            serving.update_replica(rep.idx, {"role": rep.role})

    # -- KV-page migration (the cross_reshard hop) -------------------------

    def _bridge(self, src: _Replica, dst: _Replica):
        key = (src.idx, dst.idx)
        m = self._bridges.get(key)
        if m is None:
            m = make_mesh({"fleet": 2, "tp": self.tp},
                          devices=src.devices + dst.devices)
            self._bridges[key] = m
        return m

    def migrate(self, src: _Replica, dst: _Replica, src_slot: int,
                prompt_len: int, max_new: int,
                rid: Any = None) -> int:
        """Hand ``src_slot``'s KV pages from ``src`` to ``dst``;
        returns the dest slot (admitted here, pages scattered through
        a donated write, ``seq_lens`` carried over).  Page values are
        moved bitwise — whole pages, dest pages fully overwritten."""
        t0 = time.perf_counter()
        try:
            return self._migrate(src, dst, src_slot, prompt_len,
                                 max_new, rid, t0)
        except BaseException:
            if trace.enabled:
                trace.record_span("serve:migrate", "serve", t0,
                                  time.perf_counter(),
                                  args={"rid": rid, "src": src.idx,
                                        "dst": dst.idx,
                                        "status": "error"})
            raise

    def _migrate(self, src: _Replica, dst: _Replica, src_slot: int,
                 prompt_len: int, max_new: int, rid: Any,
                 t0: float) -> int:
        scache, dcache = src.engine.cache, dst.engine.cache
        if (scache.page_size, scache.heads_local, scache.head_dim,
                scache.n_layers) != (dcache.page_size,
                                     dcache.heads_local,
                                     dcache.head_dim, dcache.n_layers):
            raise ValueError("ServingFleet.migrate: prefill/decode "
                             "cache geometries differ")
        pages = list(scache._slot_pages[src_slot])
        npg = len(pages)
        L, pg = scache.n_layers, scache.page_size
        hl, hd = scache.heads_local, scache.head_dim
        seq_len = int(scache.seq_lens[src_slot])
        dst_slot = dcache.admit(prompt_len, max_new)
        dpages = list(dcache._slot_pages[dst_slot])
        if len(dpages) != npg:
            dcache.release(dst_slot)
            raise RuntimeError(f"ServingFleet.migrate: page count "
                               f"mismatch ({npg} src vs {len(dpages)} "
                               "dst)")
        idx = jnp.asarray(pages, jnp.int32)
        bridge = self._bridge(src, dst)
        rows = 2 * L * npg                 # k then v, layer-major
        shape = (2, self.tp, rows, pg, hl, hd)
        src_sh = NamedSharding(bridge, P("fleet", "tp"))
        kmaps = [{s.device: s.data for s in pool.addressable_shards}
                 for pool in scache.k]
        vmaps = [{s.device: s.data for s in pool.addressable_shards}
                 for pool in scache.v]
        src_devs = set(src.devices)
        blocks = []
        for dev, _r in src_sh.devices_indices_map(shape).items():
            if dev in src_devs:
                parts = [jnp.take(kmaps[l][dev], idx, axis=1)
                         for l in range(L)]
                parts += [jnp.take(vmaps[l][dev], idx, axis=1)
                          for l in range(L)]
                blk = jnp.concatenate(parts, axis=1)
                blk = blk.reshape(1, 1, rows, pg, hl, hd)
            else:
                # the zero half: resident on the decode device, so its
                # piece is a zero-wire local copy in the cross plan
                blk = jax.device_put(
                    jnp.zeros((1, 1, rows, pg, hl, hd), scache.dtype),
                    dev)
            blocks.append(blk)
        x = jax.make_array_from_single_device_arrays(shape, src_sh,
                                                     blocks)
        dst_sh = NamedSharding(dst.dc.mesh, P(None, "tp"))
        out = _reshard.cross_reshard(x, dst_sh, spc=self.spc)
        last = _reshard.report()["last"] or {}
        wire = int(last.get("wire_bytes", 0))
        # cross_reshard audits wire/traffic on the bridge mesh; the
        # fleet additionally charges the simulated DCN hop when the
        # bridge's fleet axis classifies as DCN
        if wire and simdcn.us_per_mib() > 0 and \
                classify_axes(bridge).get("fleet") == "dcn":
            simdcn.charge(wire)
        payload = out[0]                   # (tp, rows, pg, hl, hd)
        didx = jnp.asarray(dpages, jnp.int32)
        for l in range(L):
            dcache.k[l] = _j_page_import(
                dcache.k[l], payload[:, l * npg:(l + 1) * npg], didx)
            dcache.v[l] = _j_page_import(
                dcache.v[l], payload[:, (L + l) * npg:
                                     (L + l + 1) * npg], didx)
        dcache.seq_lens[dst_slot] = seq_len
        t1 = time.perf_counter()
        if serving.enabled:
            serving.note_migration(rid, src.idx, dst.idx, npg, wire,
                                   int(last.get("peak_bytes", 0)),
                                   int(last.get("bound_bytes", 0)),
                                   t1 - t0)
        if trace.enabled:
            trace.record_span("serve:migrate", "serve", t0, t1,
                              args={"rid": rid, "src": src.idx,
                                    "dst": dst.idx, "pages": npg,
                                    "wire_bytes": wire,
                                    "seq_len": seq_len})
        return dst_slot

    # -- the fleet run -----------------------------------------------------

    def run(self, requests: List[Request], *,
            eos_id: Optional[int] = None,
            spec_k: int = 0) -> Dict[str, Any]:
        """Admit one request stream across the fleet: the router
        assigns every request (in arrival order) to a serving/decode
        replica under the current effective weights, each replica
        drains its share on its own virtual clock (replicas are
        concurrent machines — fleet makespan is the MAX replica clock,
        not the sum), then the per-replica rows feed the fleet ledger,
        the router's live goodput/ITL weights, and the hot_replica
        sentry."""
        serving.set_fleet_replicas(len(self.replicas))
        for rep in self.replicas:
            # each run() replays an independent stream whose arrivals
            # restart near t=0: the prefill lanes' virtual clocks (and
            # their busy accounting) restart with it, like the decode
            # schedulers' do
            rep.clock = 0.0
            rep.prefills = 0
            rep.prefill_s = 0.0
        reqs = sorted(requests, key=lambda r: r.arrival)
        buckets: Dict[int, List[Request]] = {i: [] for i in
                                             self.serve_ids}
        for req in reqs:
            pick = self.serve_ids[self.router.assign(req.rid)]
            buckets[pick].append(req)
        scheds: List[Tuple[int, _ReplicaScheduler]] = []
        for i, t in enumerate(self.serve_ids):
            dec = self.replicas[t]
            if self.mode == "disaggregated":
                pre = self.replicas[
                    self.prefill_ids[i % len(self.prefill_ids)]]
                s: _ReplicaScheduler = _DisaggScheduler(
                    self, pre, dec, buckets[t], eos_id=eos_id)
            else:
                s = _ReplicaScheduler(dec, buckets[t], eos_id=eos_id,
                                      spec_k=spec_k)
            scheds.append((t, s))
        results: Dict[Any, Dict[str, Any]] = {}
        itl_all: List[float] = []
        per_replica: List[Dict[str, Any]] = []
        total_tokens = 0
        total_steps = 0
        clock = 0.0
        for i, (t, s) in enumerate(scheds):
            out = s.run()
            results.update(out["results"])
            itl_all.extend(s.itl)
            total_tokens += out["tokens"]
            total_steps += out["decode_steps"]
            clock = max(clock, out["clock_s"])
            p99 = 1e3 * _percentile(s.itl, 0.99)
            row = {
                "replica": t, "role": self.replicas[t].role,
                "requests": len(buckets[t]),
                "tokens": out["tokens"],
                "decode_steps": out["decode_steps"],
                "clock_s": round(out["clock_s"], 6),
                "tokens_per_s": round(out["tokens_per_s"], 2),
                "occupancy": round(
                    s.occ_sum / max(s.decode_steps, 1), 4),
                "itl_p50_ms": round(1e3 * _percentile(s.itl, 0.50), 3),
                "itl_p99_ms": round(p99, 3),
            }
            per_replica.append(row)
            serving.update_replica(t, row)
            # live reweighting: goodput per unit tail latency
            self.router.update(i, out["tokens_per_s"], max(p99, 1e-3))
        for p in self.prefill_ids:
            pre = self.replicas[p]
            clock = max(clock, pre.clock)
            row = {"replica": p, "role": "prefill",
                   "prefills": pre.prefills,
                   "prefill_s": round(pre.prefill_s, 6),
                   "clock_s": round(pre.clock, 6)}
            per_replica.append(row)
            serving.update_replica(p, row)
        self.check_hot_replicas(step=total_steps)
        itl = sorted(itl_all)
        return {
            "mode": self.mode,
            "replicas": len(self.replicas),
            "tp": self.tp,
            "clock_s": clock,
            "completed": len(results),
            "tokens": total_tokens,
            "decode_steps": total_steps,
            "tokens_per_s": (total_tokens / clock) if clock else 0.0,
            "itl": {"count": len(itl),
                    "p50_ms": 1e3 * _percentile(itl, 0.50),
                    "p99_ms": 1e3 * _percentile(itl, 0.99)},
            "per_replica": per_replica,
            "results": results,
        }

    # -- the hot_replica sentry --------------------------------------------

    def check_hot_replicas(self, step: int = 0) -> List[Any]:
        """p99-ITL skew vs the fleet (lower) median across serving
        replicas.  Episode semantics: one ``policy_verdict`` per
        excursion, re-armed once the skew recovers below 90% of the
        threshold — the builtin ``fleet_hot_replica`` rule answers
        with the pre-verified ``route_weight`` action."""
        from .. import policy
        rep = serving.fleet_report()
        rows = [r for r in rep["replica_rows"]
                if r.get("role") != "prefill"
                and r.get("itl_p99_ms") is not None]
        if len(rows) < 2:
            return []
        p99s = sorted(float(r["itl_p99_ms"]) for r in rows)
        med = max(p99s[(len(p99s) - 1) // 2], 1e-9)
        thr = float(_var.get("serve_fleet_hot_skew", 1.75))
        out = []
        for r in rows:
            i = int(r["replica"])
            skew = float(r["itl_p99_ms"]) / med
            if skew >= thr and not self._hot.get(i):
                self._hot[i] = True
                out.append(policy.publish(
                    "serve", "hot_replica", "warn",
                    {"replica": i,
                     "itl_p99_ms": float(r["itl_p99_ms"]),
                     "median_p99_ms": med,
                     "skew": round(skew, 3),
                     "tokens_per_s": r.get("tokens_per_s")},
                    step=step))
            elif skew < 0.9 * thr:
                self._hot[i] = False
        return out
