"""Request plane — end-to-end per-request lifecycle observability.

Every existing plane observes *components* (ranks, collectives,
replicas); this one follows the REQUEST.  A request-scoped trace
context (rid) is threaded through every stage of the fleet path —
admit → route decision (with the router's effective weight snapshot as
structured evidence) → queue wait → prefill span → KV-migration span →
decode-join wait → per-token emit instants — and every emitted event
carries a ``rid=`` tag (comm-lint rule CL008), so ``trace.merge``'s
clock alignment stitches one globally ordered span tree per request
even when its stages ran on disjoint tp submeshes.

The ledger keeps three things, all bounded:

* **stage histograms** — per-stage duration samples (queue / prefill /
  migrate / join / decode), the p50/p99 table and the
  ``ompi_tpu_request_stage_seconds{stage,quantile}`` Prometheus family.
* **tail exemplars** — full span trees kept only for the slowest-k
  reservoir plus every SLO breach; everything else collapses into the
  histograms so the ring survives production QPS.  The reservoir is
  deterministic: identical request streams keep identical exemplars.
* **SLO judge** — declarative TTFT / per-request ITL p99 / e2e targets
  (0 = disabled).  A breach attributes the request's critical path to
  the stage with the largest excess over its own histogram median, and
  publishes ONE ``slo_breach`` verdict per excursion episode onto the
  policy bus with the attributed stage + decode replica as evidence —
  the pre-verified ``route_weight`` action then fires on the stage
  that is actually hot (re-armed when a request meets SLO again).

Stage durations run on the scheduler's VIRTUAL clock (the same clock
the serving ledger's queue-wait and ITL numbers use), so the
conservation law ``sum(stages) == e2e`` holds exactly in-process and
within clock confidence (±best_rtt/2) after a merge across ranks —
``trace.critical`` re-derives and checks it from the trace alone.

jax-free (spc's pvar read-through imports this module); every producer
call site is gated on ONE ``requests.enabled`` attribute read.
"""

from __future__ import annotations

import threading
import zlib
from typing import Any, Dict, List, Optional

from .. import trace as _trace
from ..core import var as _var

_var.register("serve", "req", "enabled", False, type=bool, level=3,
              help="Master switch for the request plane (per-request "
                   "stage spans, tail exemplars, SLO judge). Off by "
                   "default; the disabled path is one attribute read "
                   "per scheduler/fleet event.")
_var.register("serve", "req", "exemplar_k", 8, type=int, level=3,
              help="Slowest-k reservoir size for full request span "
                   "trees; SLO-breach exemplars are always kept on top "
                   "of the k slowest (both bounded by serve_table_cap).")
_var.register("serve", "req", "slo_ttft_ms", 0.0, type=float, level=3,
              help="Time-to-first-token SLO target in ms (0 disables). "
                   "A finished request exceeding it counts as a breach "
                   "and is judged for stage attribution.")
_var.register("serve", "req", "slo_itl_ms", 0.0, type=float, level=3,
              help="Per-request inter-token-latency p99 SLO target in "
                   "ms (0 disables).")
_var.register("serve", "req", "slo_e2e_ms", 0.0, type=float, level=3,
              help="End-to-end (arrival to finish) SLO target in ms "
                   "(0 disables).")
_var.register("serve", "req", "chaos_migrate_ms", 0.0, type=float, level=4,
              help="Fault injection for bench.py --slo: extra virtual "
                   "delay (ms) added to every KV-page migration hop, "
                   "modelling a degraded DCN lane. 0 = off.")
_var.register("serve", "req", "chaos_prefill_scale", 1.0, type=float,
              level=4,
              help="Fault injection for bench.py --slo: multiplier on "
                   "every fleet prefill's virtual duration, modelling "
                   "a slowed prefill replica. 1.0 = off.")

enabled: bool = bool(_var.get("serve_req_enabled", False))

PVARS = ("req_active", "req_completed", "req_slo_breaches",
         "req_exemplars_kept")

#: canonical stage vocabulary, in lifecycle order
STAGES = ("queue", "prefill", "migrate", "join", "decode")

_lock = threading.Lock()

_reqs: Dict[Any, Dict[str, Any]] = {}            # in-flight rid -> rec
_pending_routes: Dict[Any, Dict[str, Any]] = {}  # routed, not yet admitted
_stage_hist: Dict[str, List[float]] = {}         # stage -> dur samples (s)
_e2e: List[float] = []                           # completed e2e walls (s)
_exemplars: List[Dict[str, Any]] = []            # kept span trees
_completed = 0
_breaches = 0
_episodes = 0
_episode_open = False


def enable() -> None:
    global enabled
    enabled = True


def disable() -> None:
    global enabled
    enabled = False


def _on_enabled_var(v: Any) -> None:
    # mid-run OMPI_TPU_SERVE_REQ_ENABLED / set_cli writes take effect
    global enabled
    enabled = bool(v)


_var.watch("serve_req_enabled", _on_enabled_var)


def reset() -> None:
    global _completed, _breaches, _episodes, _episode_open
    with _lock:
        _reqs.clear()
        _pending_routes.clear()
        _stage_hist.clear()
        _e2e.clear()
        _exemplars.clear()
        _completed = 0
        _breaches = 0
        _episodes = 0
        _episode_open = False


def flow_id(rid: Any) -> int:
    """Stable Chrome-trace flow id for a request (the arrow chain that
    links its prefill → migration → decode hand-offs across lanes)."""
    try:
        return int(rid)
    except (TypeError, ValueError):
        return zlib.crc32(str(rid).encode())


# -- lifecycle (scheduler/fleet call these behind `requests.enabled`) -------

def note_route(rid: Any, replica: int, weights: List[float],
               t: Optional[float] = None) -> None:
    """One router admission decision, recorded as a DECISION event with
    the effective weight snapshot as structured evidence — "why this
    replica" is answerable from the trace alone, not just the doctor
    table."""
    snap = {"replica": int(replica),
            "weights": [round(float(w), 6) for w in weights]}
    with _lock:
        _pending_routes[rid] = snap
        if len(_pending_routes) > 4 * int(_var.get("serve_table_cap", 64)):
            _pending_routes.pop(next(iter(_pending_routes)))
    if _trace.enabled:
        _trace.decision("route", arm=f"replica={int(replica)}",
                        reason="learned:dwrr-goodput", nbytes=0,
                        rank=int(replica), t=t, verdict=None, rid=rid,
                        weights=snap["weights"])


def note_admit(rid: Any, arrival: float, now: float, prompt_len: int,
               max_new: int, replica: int = 0,
               rank: Optional[int] = None) -> None:
    """Request admitted at virtual time ``now``; the elapsed
    ``now - arrival`` is its queue-wait stage.  ``replica`` is the
    owning (decode) replica; ``rank`` the lane the queue span renders
    on (defaults to ``replica``)."""
    rank = int(replica if rank is None else rank)
    with _lock:
        route = _pending_routes.pop(rid, None)
        _reqs[rid] = {
            "rid": rid, "arrival": float(arrival),
            "admitted": float(now), "prompt_len": int(prompt_len),
            "max_new": int(max_new), "replica": int(replica),
            "route": route, "stages": {}, "spans": [], "tokens": 0,
            "first_token": None, "_last_token": None, "itl": [],
        }
    note_stage(rid, "queue", arrival, now, rank=rank)
    if _trace.enabled:
        _trace.instant("req:admit", "req", rank=rank,
                       args={"rid": rid, "prompt_len": int(prompt_len),
                             "max_new": int(max_new)}, t=now)


def note_stage(rid: Any, stage: str, t0: float, t1: float,
               rank: Optional[int] = None, **extra: Any) -> None:
    """One completed lifecycle stage on the virtual clock.  Emits the
    rid-tagged ``req:<stage>`` span and, for the migration hand-off,
    the Chrome-trace flow arrows (prefill → migration on the source
    lane, migration → decode closed by the join stage)."""
    dur = max(0.0, float(t1) - float(t0))
    with _lock:
        rec = _reqs.get(rid)
        if rec is None:
            return
        if rank is None:
            rank = rec["replica"]
        rec["stages"][stage] = rec["stages"].get(stage, 0.0) + dur
        rec["spans"].append({"stage": stage, "t0": float(t0),
                             "t1": float(t1), "rank": int(rank),
                             **{k: v for k, v in extra.items()}})
    if _trace.enabled:
        _trace.record_span(f"req:{stage}", "req", float(t0), float(t1),
                           rank=int(rank),
                           args={"rid": rid, **extra})
        fid = flow_id(rid)
        if stage == "migrate":
            src = int(extra.get("src", rank))
            _trace.flow("req:handoff", "req", fid, "s", rank=src,
                        t=float(t0), args={"rid": rid})
            _trace.flow("req:handoff", "req", fid, "t", rank=src,
                        t=float(t1), args={"rid": rid})
        elif stage == "join":
            _trace.flow("req:handoff", "req", fid, "f", rank=int(rank),
                        t=float(t1), args={"rid": rid})


def note_token(rid: Any, t: float, rank: Optional[int] = None) -> None:
    with _lock:
        rec = _reqs.get(rid)
        if rec is None:
            return
        rec["tokens"] += 1
        if rec["first_token"] is None:
            rec["first_token"] = float(t)
        last = rec["_last_token"]
        if last is not None:
            rec["itl"].append(float(t) - last)
        rec["_last_token"] = float(t)
        if rank is None:
            rank = rec["replica"]
        n = rec["tokens"]
    if _trace.enabled:
        _trace.instant("req:token", "req", rank=int(rank),
                       args={"rid": rid, "n": n}, t=float(t))


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(int(round(q * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[k]


def _attribute(stages: Dict[str, float]) -> Optional[str]:
    """Critical-path attribution: the stage with the largest excess
    over its own histogram median (argmax duration when no history) —
    a uniformly slow request blames its genuinely dominant stage, a
    degraded lane blames the degraded stage."""
    best, best_excess = None, float("-inf")
    for name, dur in stages.items():
        hist = _stage_hist.get(name)
        med = _percentile(sorted(hist), 0.50) if hist else 0.0
        excess = float(dur) - med
        if excess > best_excess:
            best, best_excess = name, excess
    return best


def _judge(ttft_ms: float, itl_p99_ms: float,
           e2e_ms: float) -> List[Dict[str, float]]:
    out = []
    for metric, value, vname in (
            ("ttft", ttft_ms, "serve_req_slo_ttft_ms"),
            ("itl_p99", itl_p99_ms, "serve_req_slo_itl_ms"),
            ("e2e", e2e_ms, "serve_req_slo_e2e_ms")):
        target = float(_var.get(vname, 0.0))
        if target > 0.0 and value > target:
            out.append({"metric": metric, "value_ms": round(value, 6),
                        "target_ms": target})
    return out


def _prune_exemplars_locked() -> None:
    k = max(0, int(_var.get("serve_req_exemplar_k", 8)))
    cap = max(k, int(_var.get("serve_table_cap", 64)))
    clean = [e for e in _exemplars if not e["breach"]]
    clean.sort(key=lambda e: (-e["e2e_ms"], str(e["rid"])))
    keep = [e for e in _exemplars if e["breach"]] + clean[:k]
    if len(keep) > cap:
        keep.sort(key=lambda e: (-e["e2e_ms"], str(e["rid"])))
        keep = keep[:cap]
    keep_ids = {id(e) for e in keep}
    _exemplars[:] = [e for e in _exemplars if id(e) in keep_ids]


def note_finish(rid: Any, t: float, reason: str = "eos") -> None:
    """Request finished at virtual time ``t``: close the decode stage
    (the remainder after the last explicit stage), run the SLO judge,
    fold the stages into the histograms, update the exemplar reservoir
    and — on the first breach of an excursion — publish the
    ``slo_breach`` verdict with the attributed stage as evidence."""
    global _completed, _breaches, _episodes, _episode_open
    with _lock:
        rec = _reqs.pop(rid, None)
    if rec is None:
        return
    arrival = rec["arrival"]
    decode_t0 = arrival + sum(rec["stages"].values())
    rank = int(rec["replica"])
    note_decode = max(0.0, float(t) - decode_t0)
    rec["stages"]["decode"] = note_decode
    rec["spans"].append({"stage": "decode", "t0": decode_t0,
                         "t1": float(t), "rank": rank})
    e2e = max(0.0, float(t) - arrival)
    ttft_ms = 1e3 * ((rec["first_token"] - arrival)
                     if rec["first_token"] is not None else e2e)
    itl_ms = 1e3 * _percentile(sorted(rec["itl"]), 0.99)
    breach = _judge(ttft_ms, itl_ms, 1e3 * e2e)
    with _lock:
        attributed = _attribute(rec["stages"])
        stage_sum = sum(rec["stages"].values())
        summary = {
            "rid": rid, "replica": rank, "reason": str(reason),
            "prompt_len": rec["prompt_len"], "max_new": rec["max_new"],
            "tokens": rec["tokens"], "arrival": arrival,
            "finished": float(t), "e2e_ms": round(1e3 * e2e, 6),
            "ttft_ms": round(ttft_ms, 6),
            "itl_p99_ms": round(itl_ms, 6),
            "breach": breach, "attributed_stage": attributed,
            "stages_ms": {k: round(1e3 * v, 6)
                          for k, v in rec["stages"].items()},
            "spans": list(rec["spans"]), "route": rec["route"],
            "conservation": {
                "stage_sum_ms": round(1e3 * stage_sum, 6),
                "e2e_ms": round(1e3 * e2e, 6),
                "resid_ms": round(1e3 * abs(stage_sum - e2e), 9),
            },
        }
        cap = int(_var.get("serve_latency_window", 4096))
        for name, dur in rec["stages"].items():
            hist = _stage_hist.setdefault(name, [])
            hist.append(float(dur))
            if len(hist) > cap:
                del hist[: len(hist) - cap]
        _e2e.append(e2e)
        if len(_e2e) > cap:
            del _e2e[: len(_e2e) - cap]
        _completed += 1
        step = _completed
        publish = False
        if breach:
            _breaches += 1
            if not _episode_open:
                _episode_open = True
                _episodes += 1
                publish = True
        else:
            _episode_open = False          # re-arm the episode
        _exemplars.append(summary)
        _prune_exemplars_locked()
    if _trace.enabled:
        # comm-lint: disable=CL002 virtual-time remainder span (decode_t0..t are scheduler clocks, not a wall-clock timed region)
        _trace.record_span("req:decode", "req", decode_t0, float(t),
                           rank=rank, args={"rid": rid})
        # comm-lint: disable=CL002 virtual-time envelope (arrival..t are scheduler clocks, not a wall-clock region timed around _judge)
        _trace.record_span("req:e2e", "req", arrival, float(t), rank=rank,
                           args={"rid": rid, "reason": str(reason),
                                 "tokens": rec["tokens"],
                                 "breach": bool(breach)})
    if publish:
        worst = breach[0]
        from .. import policy as _policy
        _policy.publish("serve", "slo_breach", "warn",
                        {"rid": rid, "replica": rank,
                         "stage": attributed,
                         "metric": worst["metric"],
                         "value_ms": worst["value_ms"],
                         "target_ms": worst["target_ms"],
                         "e2e_ms": round(1e3 * e2e, 6)},
                        step=step)


# -- pvar read-through + exporters ------------------------------------------

def pvar_value(name: str) -> float:
    with _lock:
        if name == "req_active":
            return float(len(_reqs))
        if name == "req_completed":
            return float(_completed)
        if name == "req_slo_breaches":
            return float(_breaches)
        if name == "req_exemplars_kept":
            return float(len(_exemplars))
    raise KeyError(name)


def prometheus_rows(rank: int = 0, comm: str = "world",
                    prefix: str = "ompi_tpu") -> List[str]:
    """Per-stage latency quantile family for the Prometheus exporter:
    ``<prefix>_request_stage_seconds{stage,quantile}`` (seconds, the
    exporter's base unit)."""
    with _lock:
        stages = {k: sorted(v) for k, v in _stage_hist.items() if v}
    if not stages:
        return []
    name = f"{prefix}_request_stage_seconds"
    rows = [f"# HELP {name} Per-stage request latency quantiles "
            "(request plane).",
            f"# TYPE {name} gauge"]
    for stage in sorted(stages):
        for q in (0.5, 0.99):
            val = _percentile(stages[stage], q)
            rows.append(f'{name}{{rank="{int(rank)}",comm="{comm}",'
                        f'stage="{stage}",quantile="{q:g}"}} {val:.9g}')
    return rows


def report() -> Dict[str, Any]:
    """Structured plane state for comm_doctor --requests / bench --slo."""
    with _lock:
        e2e = sorted(_e2e)
        stage_rows = {}
        for stage in STAGES:
            hist = _stage_hist.get(stage)
            if not hist:
                continue
            s = sorted(hist)
            stage_rows[stage] = {
                "count": len(s),
                "p50_ms": round(1e3 * _percentile(s, 0.50), 6),
                "p99_ms": round(1e3 * _percentile(s, 0.99), 6),
            }
        rollup: Dict[str, int] = {}
        for e in _exemplars:
            st = e.get("attributed_stage")
            if st is not None:
                rollup[st] = rollup.get(st, 0) + 1
        breach_rollup: Dict[str, int] = {}
        for e in _exemplars:
            if e["breach"] and e.get("attributed_stage") is not None:
                st = e["attributed_stage"]
                breach_rollup[st] = breach_rollup.get(st, 0) + 1
        return {
            "enabled": enabled,
            "active": len(_reqs),
            "completed": _completed,
            "slo_breaches": _breaches,
            "episodes": _episodes,
            "exemplars_kept": len(_exemplars),
            "slo": {
                "ttft_ms": float(_var.get("serve_req_slo_ttft_ms", 0.0)),
                "itl_p99_ms": float(_var.get("serve_req_slo_itl_ms", 0.0)),
                "e2e_ms": float(_var.get("serve_req_slo_e2e_ms", 0.0)),
            },
            "e2e": {
                "count": len(e2e),
                "p50_ms": round(1e3 * _percentile(e2e, 0.50), 6),
                "p99_ms": round(1e3 * _percentile(e2e, 0.99), 6),
            },
            "stages": stage_rows,
            "tail_attribution": rollup,
            "breach_attribution": breach_rollup,
            "exemplars": [dict(e) for e in _exemplars],
        }
