"""Fused decode program — the whole decode backbone + logits as ONE
jitted shard_map (``Config(decode_overlap="fused")``).

The eager decode path dispatches 11 audited collectives per token step
(1 embed AG + 4 AGs per layer + the logits RS→AG pair) between jitted
pieces — correct, fully audited, and dispatch-bound on the hottest loop
in the system.  This module is the decode-layout extension of the
``tp_overlap="fused"`` training path (ops/collective_matmul): the
residual stream is BATCH-sharded over tp (Megatron sequence parallelism
with sequence ↦ batch — each rank owns B/tp batch rows), so every tp
combine becomes an n−1-hop collective-matmul ring INSIDE one program:

* qkv / gate|up / logits — ``ring_allgather_matmul_local``: the (B/tp,
  d) residual shard rotates around the ring while each rank's
  column-local weight block multiplies the visiting rows (weights never
  move; d_ff/heads/vocab never cross the wire).
* wo / down — ``ring_matmul_reduce_scatter_local``: float32 partial
  sums ride the ring, each hop's matmul block produced just in time,
  and the output lands batch-scattered — the residual add is local.

Per decode step that leaves 4 rings per layer + 1 logits ring (the
gate/up pair shares one ring via a column-concat weight), every ring
carrying the same (B/tp, d) payload for n−1 hops, and exactly TWO eager
dispatches: the embed ``decode_ag`` (the d/tp feature combine that
builds the replicated residual) and the final logits ``decode_ag`` (the
vocab-shard combine).  11 → 2.

The audit moves with the traffic: each ring is decided (coll name
``decode_collmm``) and audited at the engine's dispatch site — one
decide event per ring, wire = (n−1)·payload charged to the ring edges —
and the static verifier (analysis/commgraph) extracts the program's
ppermute trips and proves static == runtime byte-for-byte
(``ServingEngine.verify_decode_program``).  The rings are built on
exactly n−1 ppermutes for this reason: a wasted last hop would break
the byte-for-byte proof, not just the perf.

Speculative decoding (scheduler ``spec_k``) stays on the eager window
path — the fused program is shape-specialized to the continuous batch.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..jaxcompat import shard_map
from ..models.transformer import _rms_norm, decode_attention, rope_rows
from ..ops.collective_matmul import (ring_allgather_matmul_local,
                                     ring_matmul_reduce_scatter_local)

# per-layer ring sites in program order; the logits ring closes the step
LAYER_SITES = ("qkv_ag", "wo_rs", "gateup_ag", "down_rs")
LOGITS_SITE = "logits_ag"


def ring_schedule(n_layers: int, B: int, d_model: int, n: int,
                  itemsize: int) -> List[Tuple[str, int, int]]:
    """The fused program's static ring schedule: one ``(site,
    payload_bytes, wire_bytes)`` row per ring, in dispatch order.
    Every ring rotates a (B/n, d_model) block for n−1 hops — the AG
    rings carry the residual shard in the compute dtype, the RS rings
    carry float32 partial sums — so wire = (n−1)·payload per rank.
    The engine decides + audits one ``decode_collmm`` event per row;
    the commgraph extractor reproduces the summed wire figure from the
    traced ppermute trips byte-for-byte."""
    rows: List[Tuple[str, int, int]] = []
    bl = B // n
    for i in range(n_layers):
        for site in LAYER_SITES:
            size = itemsize if site.endswith("_ag") else 4  # RS rides f32
            payload = bl * d_model * size
            rows.append((f"L{i}/{site}", payload, (n - 1) * payload))
    payload = bl * d_model * itemsize
    rows.append((LOGITS_SITE, payload, (n - 1) * payload))
    return rows


def build_fused_decode(mesh, axis: str, n_layers: int, head_dim: int,
                       rope_base: float):
    """Build the jitted fused decode program over ``mesh``/``axis``.

    Returned callable signature::

        fn(x_can, bt, pos, page_idx, offset, layers, final_norm,
           embed_lg, k_pools, v_pools) -> (logits_can, k_pools, v_pools)

    * ``x_can`` (tp, B, d) — canonical residual, replicated content
      (the eager embed AG's regrouped output).
    * ``bt`` (B, pmax) block tables; ``pos``/``page_idx``/``offset``
      (B,) — replicated host-side indices (pos int32, −1 = inactive).
    * ``layers`` — tuple of per-layer dicts: ``attn_norm``/``mlp_norm``
      (d,) replicated; ``wqkv`` (tp, d, 3h/tp) and ``wgu`` (tp, d,
      2f/tp) canonical column-parallel; ``wo`` (tp, h/tp, d) and ``wd``
      (tp, f/tp, d) canonical ROW-parallel (the train layout's shards —
      the RS ring contracts over the local rows).
    * ``embed_lg`` (tp, d, V/tp) — the tied embedding's transposed
      vocab-block columns (train layout, canonicalized + swapped).
    * ``k_pools``/``v_pools`` — tuples of (tp, n_pages, page, h/tp, hd)
      paged-cache pools, donated: the page writes happen inside the
      program and the pools update in place.

    Output ``logits_can`` is (tp, B, V/tp) with row r = vocab block r —
    one eager ``decode_ag`` + regroup away from full logits.
    """
    n = mesh.shape[axis]

    def body(xc, bt, pos, page_idx, offset, layers, final_norm,
             embed_lg, k_pools, v_pools):
        x = xc[0]                            # (B, d) replicated content
        B = x.shape[0]
        bl = B // n
        my = lax.axis_index(axis)
        xs = lax.dynamic_slice_in_dim(x, my * bl, bl, axis=0)
        new_k: List[Any] = []
        new_v: List[Any] = []
        for lw, kp4, vp4 in zip(layers, k_pools, v_pools):
            kp, vp = kp4[0], vp4[0]
            h = _rms_norm(xs, lw["attn_norm"])
            qkv = ring_allgather_matmul_local(h, lw["wqkv"][0], axis, n)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            hl = q.shape[-1] // head_dim
            q = rope_rows(q.reshape(B, hl, head_dim), pos, rope_base)
            k = rope_rows(k.reshape(B, hl, head_dim), pos, rope_base)
            v = v.reshape(B, hl, head_dim)
            kp = kp.at[page_idx, offset].set(k.astype(kp.dtype))
            vp = vp.at[page_idx, offset].set(v.astype(vp.dtype))
            new_k.append(kp[None])
            new_v.append(vp[None])
            kk = jnp.take(kp, bt, axis=0)    # (B, pmax, page, hl, hd)
            pmax, pg = kk.shape[1], kk.shape[2]
            kk = kk.reshape(B, pmax * pg, hl, head_dim)
            vv = jnp.take(vp, bt, axis=0).reshape(B, pmax * pg, hl,
                                                  head_dim)
            att = decode_attention(q, kk, vv, pos)
            att = att.reshape(B, hl * head_dim)
            o = ring_matmul_reduce_scatter_local(att, lw["wo"][0],
                                                 axis, n)
            xs = xs + o.astype(xs.dtype)
            h2 = _rms_norm(xs, lw["mlp_norm"])
            gu = ring_allgather_matmul_local(h2, lw["wgu"][0], axis, n)
            g, u = jnp.split(gu, 2, axis=-1)
            z = jax.nn.silu(g) * u
            dn = ring_matmul_reduce_scatter_local(z, lw["wd"][0],
                                                  axis, n)
            xs = xs + dn.astype(xs.dtype)
        hf = _rms_norm(xs, final_norm)
        lg = ring_allgather_matmul_local(hf, embed_lg[0], axis, n)
        return (lg[None].astype(jnp.float32), tuple(new_k),
                tuple(new_v))

    lw_spec = {"attn_norm": P(), "mlp_norm": P(), "wqkv": P(axis),
               "wgu": P(axis), "wo": P(axis), "wd": P(axis)}
    pools_spec = (P(axis),) * n_layers
    in_specs = (P(axis), P(), P(), P(), P(),
                tuple(dict(lw_spec) for _ in range(n_layers)),
                P(), P(axis), pools_spec, pools_spec)
    out_specs = (P(axis), pools_spec, pools_spec)
    # outputs are provenance-varying (they flowed through ppermute), so
    # the static VMA check can't type them — same waiver as the train
    # collective-matmul builders
    return jax.jit(shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False),
                   donate_argnums=(8, 9))
