"""Paged KV cache — fixed-size pages, block tables, head-sharded over tp.

The cache is the serving tier's only large mutable state: per layer one
K and one V page pool in the canonical dim-0 layout
``(tp, n_pages, page_size, heads/tp, head_dim)`` — every device holds
its own heads' slice of EVERY page, so a sequence's pages live on all
devices at once and the paged-attention gather is purely local.

Page bookkeeping (free list, per-slot block tables, sequence lengths)
is host-side integer state: admitting or evicting a sequence moves NO
cache data — the pages stay where they are and only the block-table
rows change.  The device arrays are touched exclusively through the
engine's donated jitted writes (``engine._j_page_write``), so cache
data never crosses to the host during serving.

Admission reserves ``ceil((prompt_len + max_new) / page_size)`` pages
up front: decode can then never fault mid-sequence, and the admission
check IS the backpressure signal the continuous-batching scheduler
polls.  Page 0 is a reserved scratch page — inactive batch slots write
their masked garbage there so the donated scatter never aliases a live
sequence's pages.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np


class PagedKVCache:
    """Block-table paged KV storage over one DeviceComm (tp axis)."""

    def __init__(self, dc, n_layers: int, n_heads: int, head_dim: int, *,
                 n_pages: int = 64, page_size: int = 16,
                 max_seqs: int = 8, max_pages_per_seq: Optional[int] = None,
                 dtype=None) -> None:
        import jax
        import jax.numpy as jnp

        if n_heads % dc.n:
            raise ValueError(
                f"PagedKVCache: n_heads={n_heads} not divisible by the "
                f"{dc.n}-way tp axis")
        if n_pages < 2:
            raise ValueError("PagedKVCache: need >= 2 pages (page 0 is "
                             "the reserved scratch page)")
        self.dc = dc
        self.n_layers = int(n_layers)
        self.heads_local = n_heads // dc.n
        self.head_dim = int(head_dim)
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.max_seqs = int(max_seqs)
        self.max_pages_per_seq = int(
            max_pages_per_seq if max_pages_per_seq is not None
            else n_pages - 1)
        self.dtype = dtype if dtype is not None else jnp.float32
        shape = (dc.n, self.n_pages, self.page_size, self.heads_local,
                 self.head_dim)
        zeros = jnp.zeros(shape, self.dtype)
        sh = dc.sharding()
        self.k: List = [jax.device_put(zeros, sh)
                        for _ in range(self.n_layers)]
        self.v: List = [jax.device_put(zeros, sh)
                        for _ in range(self.n_layers)]
        # host-side page bookkeeping (page 0 reserved as scratch)
        self._free: List[int] = list(range(self.n_pages - 1, 0, -1))
        self.block_tables = np.zeros((self.max_seqs,
                                      self.max_pages_per_seq), np.int32)
        self.seq_lens = np.zeros(self.max_seqs, np.int32)
        self.slot_live = np.zeros(self.max_seqs, bool)
        self._slot_pages: List[List[int]] = [[] for _ in
                                             range(self.max_seqs)]

    # -- admission / eviction (host integers only — zero cache traffic) ----

    def pages_needed(self, prompt_len: int, max_new: int) -> int:
        return max(1, math.ceil((prompt_len + max_new) / self.page_size))

    def can_admit(self, prompt_len: int, max_new: int) -> bool:
        need = self.pages_needed(prompt_len, max_new)
        return (need <= len(self._free)
                and need <= self.max_pages_per_seq
                and not self.slot_live.all())

    def admit(self, prompt_len: int, max_new: int) -> int:
        """Reserve a slot + its pages; returns the slot id."""
        need = self.pages_needed(prompt_len, max_new)
        if need > self.max_pages_per_seq:
            raise ValueError(
                f"sequence needs {need} pages > max_pages_per_seq="
                f"{self.max_pages_per_seq}")
        if need > len(self._free):
            raise RuntimeError(f"out of KV pages ({need} needed, "
                               f"{len(self._free)} free)")
        free_slots = np.flatnonzero(~self.slot_live)
        if free_slots.size == 0:
            raise RuntimeError("no free batch slot")
        slot = int(free_slots[0])
        pages = [self._free.pop() for _ in range(need)]
        self._slot_pages[slot] = pages
        self.block_tables[slot, :] = 0
        self.block_tables[slot, :need] = pages
        self.seq_lens[slot] = 0
        self.slot_live[slot] = True
        return slot

    def release(self, slot: int) -> None:
        self._free.extend(reversed(self._slot_pages[slot]))
        self._slot_pages[slot] = []
        self.block_tables[slot, :] = 0
        self.seq_lens[slot] = 0
        self.slot_live[slot] = False

    # -- per-step index helpers --------------------------------------------

    def position_index(self, slot: int, pos: int) -> Tuple[int, int]:
        """(page id, in-page offset) of sequence position ``pos``."""
        return (int(self.block_tables[slot, pos // self.page_size]),
                pos % self.page_size)

    def write_indices(self, slots: np.ndarray,
                      positions: np.ndarray) -> Tuple[np.ndarray,
                                                      np.ndarray]:
        """Vectorized (page_idx, offset) for one position per slot;
        positions < 0 (inactive slots) land on the scratch page 0."""
        slots = np.asarray(slots, np.int64)
        positions = np.asarray(positions, np.int64)
        live = positions >= 0
        p = np.where(live, positions, 0)
        page_slot = p // self.page_size
        page_idx = self.block_tables[slots, np.minimum(
            page_slot, self.max_pages_per_seq - 1)]
        page_idx = np.where(live, page_idx, 0).astype(np.int32)
        offset = np.where(live, p % self.page_size, 0).astype(np.int32)
        return page_idx, offset

    @property
    def pages_used(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    @property
    def live_slots(self) -> np.ndarray:
        return np.flatnonzero(self.slot_live)
