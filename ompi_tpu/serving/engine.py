"""Continuous-batching serving engine — prefill/decode over the decode
weight layout, decode collectives audited as ``decode_ag``/``decode_rs``.

Execution model (the host-orchestrated pattern of
``models/moe.moe_block_ep``): the per-layer compute is a handful of
jitted collective-free pieces over CANONICAL dim-0 arrays — every
weight shard lifted once at init through ``DeviceComm.canonicalize``
(a zero-wire local restack), every activation carried as ``(tp, B, …)``
— and the only cross-device traffic is the eagerly dispatched, audited
decode collectives between pieces.  That structure is what makes "one
decision event per decode collective" true by construction rather than
by instrumentation.

Dataflow per token step, consistent with
``models/transformer.decode_param_specs`` (all weights column-parallel,
output features sharded over ``tp``; the residual stream rides
replicated-content canonical form):

* embed lookup → ``decode_ag`` (combine the d/tp feature shards)
* per layer: qkv (local) → rope → paged-cache write (donated) →
  paged attention (local: heads are tp-sharded) → ``decode_ag`` (head
  combine) → wo (local) → ``decode_ag`` → +residual; mlp gate/up
  (local) → ``decode_ag`` (d_ff combine) → w_down (local) →
  ``decode_ag`` → +residual
* logits: per-device partial over its d/tp slice of the tied embedding
  → ``decode_rs`` + ``decode_ag`` (the bandwidth-bound psum: B×vocab
  float32 — exactly where the EQuARX int8 tier pays for itself)

Every dispatch runs the full decision chain (``coll/xla.decide_mode``:
force vars ``coll_xla_decode_ag_mode``/``coll_xla_decode_rs_mode`` >
blanket > learned > DEVICE_RULES rows > platform default) and fans out
the same audit record as ``coll/xla._audit``: arm/wire pvars, perf
``decode_*`` ledger cells, traffic ring-edge attribution (conservation:
edge-sum == ``coll_wire_bytes``), and the trace decision event.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import (_rms_norm, decode_attention,
                                  rope_rows)
from ..parallel.ring import attention_reference
from .cache import PagedKVCache

# -- jitted collective-free pieces (canonical dim-0 layout throughout) ------


def _regroup(y):
    """(tp, tp*B, c) allgather output → (tp, B, tp*c): per-token
    feature concat of the per-device column shards.  Each row is fully
    resident on one device, so this is a local reshape/transpose."""
    r, tb, c = y.shape
    b = tb // r
    return y.reshape(r, r, b, c).transpose(0, 2, 1, 3).reshape(r, b, r * c)


_j_regroup = jax.jit(_regroup)


@jax.jit
def _j_embed(embed_can, tokens):
    """(tp, V, d/tp), (B,) → (tp, B, d/tp) local embedding slices."""
    return jnp.take(embed_can, tokens, axis=1)


@partial(jax.jit, static_argnames=("head_dim", "base"))
def _j_qkv(x, norm_w, wqkv, pos, head_dim, base):
    """Residual (tp, B, d) → roped q, k, v (tp, B, heads/tp, head_dim).
    The qkv matmul is column-parallel: zero comm."""
    h = _rms_norm(x, norm_w)
    qkv = jnp.einsum("rbd,rdc->rbc", h, wqkv)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    r, b, c = q.shape
    q = rope_rows(q.reshape(r, b, c // head_dim, head_dim), pos, base)
    k = rope_rows(k.reshape(r, b, c // head_dim, head_dim), pos, base)
    return q, k, v.reshape(r, b, c // head_dim, head_dim)


@partial(jax.jit, donate_argnums=(0, 1))
def _j_page_write(kp, vp, k_new, v_new, page_idx, offset):
    """Scatter one k/v row per batch slot into its page — donated, so
    the pools update in place and cache data never visits the host."""
    kp = kp.at[:, page_idx, offset].set(k_new)
    vp = vp.at[:, page_idx, offset].set(v_new)
    return kp, vp


@jax.jit
def _j_paged_attn(q, kp, vp, bt, q_pos):
    """Decode attention against the paged pools: gather each slot's
    pages by block table, flatten to key positions, run the shared
    ``decode_attention`` core.  Heads are tp-sharded → fully local."""
    k = jnp.take(kp, bt, axis=1)       # (tp, B, pmax, page, hl, hd)
    v = jnp.take(vp, bt, axis=1)
    r, b, pmax, pg, hl, hd = k.shape
    k = k.reshape(r, b, pmax * pg, hl, hd)
    v = v.reshape(r, b, pmax * pg, hl, hd)
    att = decode_attention(q, k, v, q_pos)
    return att.reshape(r, b, hl * hd)


@jax.jit
def _j_prefill_attn(q, k, v):
    """Prompt-phase causal attention over the fresh q/k/v (the pages
    were just written; attending the in-register copies avoids the
    gather) — ``attention_reference`` with the tp rows as batch."""
    r, s, hl, hd = q.shape
    att = attention_reference(q, k, v, causal=True)
    return att.reshape(r, s, hl * hd)


@jax.jit
def _j_o_proj(ag_att, wo):
    return jnp.einsum("rbh,rhc->rbc", _regroup(ag_att), wo)


@jax.jit
def _j_mlp_in(ag_o, x, norm_w, wg, wu):
    x = x + _regroup(ag_o)
    h = _rms_norm(x, norm_w)
    g = jax.nn.silu(jnp.einsum("rbd,rdf->rbf", h, wg))
    u = jnp.einsum("rbd,rdf->rbf", h, wu)
    return x, g * u


@jax.jit
def _j_mlp_down(ag_z, wd):
    return jnp.einsum("rbf,rfc->rbc", _regroup(ag_z), wd)


@jax.jit
def _j_residual(ag_d, x):
    return x + _regroup(ag_d)


@jax.jit
def _j_logits_partial(x, norm_w, embed_can):
    """Per-device partial logits: each device multiplies ITS d/tp slice
    of the hidden state against its embedding columns — the partial
    sums then reduce through decode_rs + decode_ag (the audited psum)."""
    h = _rms_norm(x, norm_w)
    r, b, d = h.shape
    hs = h.reshape(r, b, r, d // r)
    idx = jnp.arange(r)
    hloc = hs[idx, :, idx, :]          # row r keeps its own slice
    part = jnp.einsum("rbd,rvd->rbv", hloc, embed_can)
    return part.reshape(r, b * part.shape[-1])


@partial(jax.jit, static_argnames=("b",))
def _j_logits_argmax(ag, b):
    r = ag.shape[0]
    logits = ag.reshape(r, b, -1).astype(jnp.float32)
    return logits, jnp.argmax(logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("s",))
def _j_last_pos(x, s):
    return x[:, s - 1:s, :]


@jax.jit
def _j_fused_logits_argmax(ag):
    """Fused-path logits: the program returns (tp, B, V/tp) with row r
    = vocab block r; after the eager decode_ag the regroup concats the
    blocks in rank order — full (tp, B, V) logits + greedy argmax."""
    lg = _regroup(ag).astype(jnp.float32)
    return lg, jnp.argmax(lg, axis=-1).astype(jnp.int32)


@jax.jit
def _j_moe_norm(x, w):
    return _rms_norm(x, w)


@jax.jit
def _j_moe_residual(x, add):
    return x + add[None]


# -- decision + audit shims (the moe.models pattern for custom colls) -------

def _decide_serve_coll(dc, coll: str, nbytes: int, dtype,
                       allowed: Tuple[str, ...] = ("native", "quant"),
                       ) -> Tuple[str, str, List[str]]:
    """Decision shim over coll/xla.decide_mode for the decode coll
    names: per-entry/blanket force vars, DEVICE_RULES rows (plane-keyed
    included), the learned source — the full precedence chain.  The
    decode collectives are single-stage (flat tp ring), so the hier
    arms are ineligible by construction.  The fused rings pass
    ``allowed=("native",)`` — the ring schedule has no quantized arm,
    and the decision never names an arm the site cannot execute."""
    from ..coll.xla import _load_device_rules, decide_mode
    from ..op import SUM, quantizable
    from ..parallel.hierarchy import classify_axes
    axes = dc.axis if isinstance(dc.axis, tuple) else (dc.axis,)
    kinds = classify_axes(dc.mesh)
    plane = ("dcn" if any(kinds.get(a) == "dcn" for a in axes)
             else "ici")
    platform = next(iter(dc.mesh.devices.flat)).platform
    return decide_mode(coll, int(nbytes), dc.n, platform,
                       _load_device_rules(), allowed,
                       quant_ok=quantizable(SUM, dtype), dtype=dtype,
                       op=None, plane=plane, hier_ok=False,
                       hier_why="decode collectives are single-stage")


def _audit_serve_coll(dc, coll: str, arm: str, reason: str,
                      chain: List[str], x, dur_s: float,
                      extra: Optional[Dict[str, Any]] = None) -> int:
    """ONE decision-audit record per decode collective — the same
    fan-out as coll/xla._audit: arm + wire pvars, an externally-timed
    perf sample (the ``decode_*`` ledger cells), traffic ring-edge
    attribution of the SAME wire figure (conservation's other half),
    and the trace decision event carrying the precedence chain."""
    from ..coll.quant import wire_bytes
    rows = max(x.shape[0], 1)
    nbytes = x.nbytes // rows
    qcoll = "allgather" if coll == "decode_ag" else "reduce_scatter"
    try:
        wb = wire_bytes(qcoll, max(x.size // rows, 1), dc.n, x.dtype)
    except (ValueError, TypeError):
        wb = None
    ratio = wb["ratio"] if wb is not None else None
    wire = nbytes
    if wb is not None:
        wire = wb["quant_bytes"] if arm == "quant" else wb["native_bytes"]
    spc = dc.spc
    if spc is not None:
        spc.inc(f"coll_arm_{arm}_count")
        spc.inc("coll_wire_bytes", int(wire))
    from ..parallel import simdcn
    if simdcn.us_per_mib() > 0:
        simdcn.charge(int(wire * simdcn.ring_dcn_fraction(dc.mesh,
                                                          dc.axis)))
    from .. import perf, trace, traffic
    if perf.enabled:
        # bank under the LOGICAL payload bytes (what decide_mode sees),
        # not the per-arm wire bytes — otherwise native and quant land
        # in different size buckets and learned lookups never find both
        # arms in one cell
        perf.note_sample(coll, arm, int(nbytes), dur_s, dc.n)
    if traffic.enabled:
        traffic.note_coll(dc, coll, arm, int(wire))
    if trace.enabled:
        bucket = 1 << max(int(nbytes) - 1, 0).bit_length()
        trace.decision(coll, arm=arm, reason=reason, verdict=None,
                       nbytes=int(nbytes),
                       shape_bucket=bucket, shape=tuple(x.shape),
                       dtype=str(x.dtype), ndev=dc.n,
                       wire_bytes=int(wire), quant_ratio=ratio,
                       chain=list(chain), **(extra or {}))
    return int(wire)


class ServingEngine:
    """Prefill + continuous decode over one tp DeviceComm.

    ``params`` arrive in the TRAIN layout by default and are converted
    on device through ``convert_params(to="decode")`` (the reshard
    engine — the serving tier is its first consumer in anger), then
    lifted shard-by-shard into canonical form with zero wire."""

    def __init__(self, dc, params: Dict, cfg, *, n_pages: int = 64,
                 page_size: int = 16, max_seqs: int = 8,
                 max_pages_per_seq: Optional[int] = None,
                 layout: str = "train") -> None:
        from ..models import transformer as tfm
        self.moe = cfg.mlp == "moe"
        dims = [("n_heads", cfg.n_heads), ("d_model", cfg.d_model),
                ("vocab", cfg.vocab)]
        if not self.moe:
            dims.append(("d_ff", cfg.d_ff))
        for name, dim in dims:
            if dim % dc.n:
                raise ValueError(
                    f"ServingEngine: cfg.{name}={dim} not divisible by "
                    f"the {dc.n}-way tp axis")
        if self.moe:
            # moe_block_ep's canonical (R, t, d) layout needs the batch
            # to split evenly across ranks, and rank j owns experts
            # [j·epr, (j+1)·epr)
            if int(max_seqs) % dc.n:
                raise ValueError(
                    f"ServingEngine: moe decode needs max_seqs="
                    f"{max_seqs} divisible by the {dc.n}-way comm axis")
            if cfg.n_experts % dc.n:
                raise ValueError(
                    f"ServingEngine: cfg.n_experts={cfg.n_experts} not "
                    f"divisible by the {dc.n}-way comm axis")
        self.fused = getattr(cfg, "decode_overlap", "eager") == "fused"
        if self.fused:
            if self.moe:
                raise ValueError(
                    "ServingEngine: decode_overlap='fused' is dense-MLP "
                    "only — moe decode stays on the eager path")
            if dc.n < 2:
                raise ValueError(
                    "ServingEngine: decode_overlap='fused' needs tp>=2 "
                    "(the rings are the whole point)")
            if int(max_seqs) % dc.n:
                raise ValueError(
                    f"ServingEngine: decode_overlap='fused' needs "
                    f"max_seqs={max_seqs} divisible by the {dc.n}-way "
                    f"tp axis (batch-sharded residual)")
        if layout == "train":
            params = tfm.convert_params(params, dc.mesh, cfg,
                                        to="decode")
        elif layout != "decode":
            raise ValueError(f"layout={layout!r} (want train|decode)")
        self.dc = dc
        self.cfg = cfg
        self.max_seqs = int(max_seqs)
        cdt = jnp.dtype(cfg.dtype)

        def can(w):
            # weight-stationary: store in the compute dtype (the same
            # cast forward() pays per step) before the zero-wire restack
            return dc.canonicalize(w.astype(cdt), 1)

        def can_qkv(w):
            # the fused (d, 3h) weight is a global [q|k|v] column
            # concat: canonicalizing it whole would hand rank r a
            # contiguous 3h/tp chunk of that concat (all-q on the low
            # ranks), so the per-rank q/k/v split in _j_qkv would slice
            # the wrong columns.  Canonicalize each projection on its
            # own and re-concat per rank: row r = [q_r | k_r | v_r],
            # i.e. global head block r of each.
            h3 = w.shape[1] // 3
            return jnp.concatenate(
                [can(w[:, i * h3:(i + 1) * h3]) for i in range(3)],
                axis=-1)

        self._embed = can(params["embed"])             # (tp, V, d/tp)
        self._final_norm = params["final_norm"]
        self._layers: List[Dict[str, Any]] = []
        for lw in params["layers"]:
            cl: Dict[str, Any] = {"attn_norm": lw["attn_norm"],
                                  "wqkv": can_qkv(lw["wqkv"]),
                                  "wo": can(lw["wo"]),
                                  "mlp_norm": lw["mlp_norm"]}
            if self.moe:
                # moe_block_ep consumes the (E, d, f) expert stacks
                # directly (it reshapes to (R, epr, …) itself) — no
                # canonical lift, same leaves the ragged train arm uses
                cl["moe"] = lw["moe"]
            else:
                cl["w_gate"] = can(lw["w_gate"])
                cl["w_up"] = can(lw["w_up"])
                cl["w_down"] = can(lw["w_down"])
            self._layers.append(cl)
        self.cache = PagedKVCache(
            dc, cfg.n_layers, cfg.n_heads, cfg.head_dim,
            n_pages=n_pages, page_size=page_size, max_seqs=max_seqs,
            max_pages_per_seq=max_pages_per_seq,
            dtype=jnp.dtype(cfg.dtype))
        self.dispatches: Dict[str, int] = {"decode_ag": 0,
                                           "decode_rs": 0,
                                           "decode_collmm": 0}
        self.wire_bytes = 0
        if self.fused:
            self._init_fused(params, cdt, can)

    def _init_fused(self, params: Dict, cdt, can) -> None:
        """Build the fused decode program + its weight views.  The AG
        rings reuse the canonical COLUMN shards already lifted above
        (gate|up concat into one ``wgu`` so the pair shares a ring); the
        RS rings contract over local ROWS, so wo/w_down/embed are
        re-laid out row-parallel — a one-time audited ``reshard`` at
        init, zero steady-state cost."""
        from jax.sharding import PartitionSpec as P
        from .fused import build_fused_decode, ring_schedule
        dc, cfg = self.dc, self.cfg

        def row_can(w):
            return dc.canonicalize(
                dc.reshard(w.astype(cdt), P(dc.axis, None)), 0)

        self._fused_layers: List[Dict[str, Any]] = []
        for lw, cl in zip(params["layers"], self._layers):
            self._fused_layers.append({
                "attn_norm": jnp.asarray(cl["attn_norm"]),
                "mlp_norm": jnp.asarray(cl["mlp_norm"]),
                "wqkv": cl["wqkv"],
                "wgu": jnp.concatenate([cl["w_gate"], cl["w_up"]],
                                       axis=-1),
                "wo": row_can(lw["wo"]),        # (tp, h/tp, d)
                "wd": row_can(lw["w_down"])})   # (tp, f/tp, d)
        # logits ring: vocab-block columns of the tied embedding —
        # row-parallel over V, transposed to (tp, d, V/tp)
        self._embed_lg = row_can(params["embed"]).swapaxes(1, 2)
        self._fused = build_fused_decode(
            dc.mesh, dc.axis, cfg.n_layers, cfg.head_dim,
            float(cfg.rope_base))
        # per-row-count ring schedules: the continuous batch and each
        # speculative window length get their own (the payloads scale
        # with the row count, the site list does not)
        self._ring_rows: Dict[int, List[Tuple[str, int, int]]] = {
            self.max_seqs: ring_schedule(cfg.n_layers, self.max_seqs,
                                         cfg.d_model, dc.n,
                                         cdt.itemsize)}

    # -- audited collective dispatch ---------------------------------------

    def _ag(self, x):
        t0 = time.perf_counter()
        arm, reason, chain = _decide_serve_coll(
            self.dc, "decode_ag", x.nbytes // x.shape[0], x.dtype)
        out = (self.dc.quant.allgather(x) if arm == "quant"
               else self.dc.allgather(x))
        dur = time.perf_counter() - t0
        self.wire_bytes += _audit_serve_coll(
            self.dc, "decode_ag", arm, reason, chain, x, dur)
        self.dispatches["decode_ag"] += 1
        from . import enabled as serve_enabled, note_dispatch
        if serve_enabled:
            note_dispatch("eager")
        return out

    def _rs(self, x):
        t0 = time.perf_counter()
        arm, reason, chain = _decide_serve_coll(
            self.dc, "decode_rs", x.nbytes // x.shape[0], x.dtype)
        out = (self.dc.quant.reduce_scatter(x) if arm == "quant"
               else self.dc.reduce_scatter(x))
        dur = time.perf_counter() - t0
        self.wire_bytes += _audit_serve_coll(
            self.dc, "decode_rs", arm, reason, chain, x, dur)
        self.dispatches["decode_rs"] += 1
        from . import enabled as serve_enabled, note_dispatch
        if serve_enabled:
            note_dispatch("eager")
        return out

    # -- forward pieces ----------------------------------------------------

    def _backbone(self, x, pos_dev, page_idx, offset,
                  attend: Callable) -> Any:
        cfg = self.cfg
        for i, lw in enumerate(self._layers):
            q, k, v = _j_qkv(x, lw["attn_norm"], lw["wqkv"], pos_dev,
                             head_dim=cfg.head_dim,
                             base=float(cfg.rope_base))
            self.cache.k[i], self.cache.v[i] = _j_page_write(
                self.cache.k[i], self.cache.v[i], k, v, page_idx,
                offset)
            att = attend(i, q, k, v)
            o = _j_o_proj(self._ag(att), lw["wo"])
            if self.moe:
                x = _j_residual(self._ag(o), x)
                x = self._moe_mlp(x, lw)
            else:
                x, z = _j_mlp_in(self._ag(o), x, lw["mlp_norm"],
                                 lw["w_gate"], lw["w_up"])
                d = _j_mlp_down(self._ag(z), lw["w_down"])
                x = _j_residual(self._ag(d), x)
        return x

    def _moe_mlp(self, x, lw):
        """Ragged-MoE MLP for one layer (PR 14's loose end closed):
        hand the normed residual to ``moe_block_ep`` in its canonical
        (R, t, d) row layout — ONLY the routed token payloads travel,
        under the audited ``moe_dispatch``/``moe_combine`` names — and
        add the expert mixture back.  The residual x is (tp, B, d) with
        replicated content, so row 0 is the full batch; B % R == 0 is
        checked at init."""
        from ..models.moe import moe_block_ep
        dc, cfg = self.dc, self.cfg
        h = _j_moe_norm(x, lw["mlp_norm"])
        b, d = h.shape[1], h.shape[2]
        hc = jax.device_put(jnp.reshape(h[0], (dc.n, b // dc.n, d)),
                            dc.sharding())
        out, _aux, _info = moe_block_ep(
            dc, hc, lw["moe"], cfg.n_experts, cfg.moe_top_k,
            cfg.moe_capacity_factor)
        add = jnp.asarray(np.asarray(out)).reshape(b, d)
        return _j_moe_residual(x, add.astype(x.dtype))

    # -- fused decode (decode_overlap="fused") -----------------------------

    def _audit_collmm(self, site: str, payload: int, wire: int,
                      arm: str, reason: str, chain: List[str],
                      dur_s: float, rows: int) -> None:
        """One decision-audit record per fused ring — the decode_collmm
        counterpart of ``_audit_serve_coll``.  The ring is an n−1-hop
        ppermute rotation, so the wire figure is exact (no per-arm
        model): it is charged to the ring edges via ``note_ring``
        (``decode_collmm`` is not in traffic's coll→pattern table, and
        ``note_coll`` would file it unattributed) and mirrored into
        ``coll_wire_bytes`` so conservation's two halves still meet."""
        from .. import perf, trace, traffic
        dc = self.dc
        spc = dc.spc
        if spc is not None:
            spc.inc(f"coll_arm_{arm}_count")
            spc.inc("coll_wire_bytes", int(wire))
        from ..parallel import simdcn
        if simdcn.us_per_mib() > 0:
            simdcn.charge(int(wire * simdcn.ring_dcn_fraction(dc.mesh,
                                                              dc.axis)))
        if perf.enabled:
            perf.note_sample("decode_collmm", arm, int(payload), dur_s,
                             dc.n)
        if traffic.enabled:
            traffic.note_ring(dc.mesh, dc.axis, int(wire),
                              "decode_collmm", "fwd")
        if trace.enabled:
            bucket = 1 << max(int(payload) - 1, 0).bit_length()
            trace.decision("decode_collmm", arm=arm, reason=reason,
                           verdict=None,
                           nbytes=int(payload), shape_bucket=bucket,
                           shape=(rows // dc.n, self.cfg.d_model),
                           dtype=str(self.cfg.dtype), ndev=dc.n,
                           wire_bytes=int(wire), quant_ratio=None,
                           chain=list(chain), site=site)
        self.dispatches["decode_collmm"] += 1
        self.wire_bytes += int(wire)
        from . import enabled as serve_enabled, note_dispatch
        if serve_enabled:
            note_dispatch("fused")

    def _decode_step_fused(self, tokens, positions, page_idx, offset,
                           bt):
        """The fused decode body: ONE jitted program carries the whole
        backbone + logits with every tp combine an n−1-hop collective-
        matmul ring (serving/fused), leaving exactly two eager
        dispatches — the embed ``decode_ag`` and the logits
        ``decode_ag``.  Every ring is still decided (full precedence
        chain, native-only arm set) and audited as ``decode_collmm``
        BEFORE the program runs: one decide event per dispatched decode
        collective, same as the eager path.  ``tokens``/``positions``/
        ``page_idx``/``offset``/``bt`` are flat over any row count
        divisible by tp — the continuous batch (decode_step) and the
        speculative verify window (decode_window) share this body, each
        shape with its own ring schedule and compiled program."""
        from .fused import ring_schedule
        rows = int(tokens.shape[0])
        cdt = jnp.dtype(self.cfg.dtype)
        ring_rows = self._ring_rows.get(rows)
        if ring_rows is None:
            ring_rows = ring_schedule(self.cfg.n_layers, rows,
                                      self.cfg.d_model, self.dc.n,
                                      cdt.itemsize)
            self._ring_rows[rows] = ring_rows
        decided = [(site, payload, wire)
                   + _decide_serve_coll(self.dc, "decode_collmm",
                                        payload, cdt,
                                        allowed=("native",))
                   for site, payload, wire in ring_rows]
        x = _j_regroup(self._ag(_j_embed(
            self._embed,
            jnp.asarray(np.where(positions >= 0, tokens,
                                 0).astype(np.int32)))))
        t0 = time.perf_counter()
        lg_can, new_k, new_v = self._fused(
            x, jnp.asarray(bt),
            jnp.asarray(positions.astype(np.int32)),
            jnp.asarray(page_idx), jnp.asarray(offset),
            tuple(self._fused_layers), jnp.asarray(self._final_norm),
            self._embed_lg, tuple(self.cache.k), tuple(self.cache.v))
        jax.block_until_ready(lg_can)
        dur = time.perf_counter() - t0
        self.cache.k[:] = list(new_k)
        self.cache.v[:] = list(new_v)
        share = dur / max(len(decided), 1)
        for site, payload, wire, arm, reason, chain in decided:
            self._audit_collmm(site, payload, wire, arm, reason, chain,
                               share, rows)
        logits, nxt = _j_fused_logits_argmax(self._ag(lg_can))
        return logits, nxt

    def _logits(self, x, b: int):
        part = _j_logits_partial(x, self._final_norm, self._embed)
        red = self._ag(self._rs(part))
        return _j_logits_argmax(red, b=b)

    @staticmethod
    def _bucket(n: int) -> int:
        p = 8
        while p < n:
            p *= 2
        return p

    # -- serving entry points ----------------------------------------------

    def prefill(self, slot: int, prompt: np.ndarray,
                rid: Any = None):
        """Run one request's prompt through the decode-layout model:
        writes its KV pages, returns (first greedy token, last-position
        logits (tp, 1, V)).  Prompts pad to a small power-of-2 bucket
        so compilations stay bounded; padded positions write to the
        scratch page and never enter the causal window.  ``rid`` tags
        the emitted span with the owning request (CL008)."""
        from .. import trace
        prompt = np.asarray(prompt, np.int32)
        s = int(prompt.shape[0])
        spad = self._bucket(s)
        tok = np.zeros(spad, np.int32)
        tok[:s] = prompt
        positions = np.arange(spad, dtype=np.int64)
        live_pos = np.where(positions < s, positions, -1)
        page_idx, offset = self.cache.write_indices(
            np.full(spad, slot), live_pos)
        t0 = time.perf_counter()
        try:
            x = _j_regroup(self._ag(_j_embed(self._embed,
                                             jnp.asarray(tok))))
            x = self._backbone(
                x, jnp.asarray(positions.astype(np.int32)),
                jnp.asarray(page_idx), jnp.asarray(offset),
                lambda i, q, k, v: _j_prefill_attn(q, k, v))
            logits, nxt = self._logits(_j_last_pos(x, s=s), b=1)
            jax.block_until_ready(nxt)
        finally:
            if trace.enabled:
                trace.record_span("serve:prefill", "serve", t0,
                                  time.perf_counter(),
                                  args={"slot": slot, "prompt_len": s,
                                        "rid": rid})
        self.cache.seq_lens[slot] = s
        return int(np.asarray(jax.device_get(nxt))[0, 0]), logits

    def decode_step(self, tokens: np.ndarray, positions: np.ndarray):
        """One continuous-batching decode step over the FULL device
        batch: ``tokens``/``positions`` are (max_seqs,) with
        position −1 marking an inactive slot (its lane computes masked
        garbage on the scratch page — the batch shape never changes, so
        one executable serves every occupancy).  Returns (next greedy
        token per slot (max_seqs,), logits (tp, max_seqs, V))."""
        from .. import trace
        b = self.max_seqs
        tokens = np.asarray(tokens, np.int32)
        positions = np.asarray(positions, np.int64)
        page_idx, offset = self.cache.write_indices(np.arange(b),
                                                    positions)
        t0 = time.perf_counter()
        try:
            if self.fused:
                logits, nxt = self._decode_step_fused(
                    tokens, positions, page_idx, offset,
                    self.cache.block_tables)
                jax.block_until_ready(nxt)
            else:
                bt = jnp.asarray(self.cache.block_tables)
                pos_dev = jnp.asarray(positions.astype(np.int32))
                x = _j_regroup(self._ag(_j_embed(
                    self._embed,
                    jnp.asarray(np.where(positions >= 0, tokens,
                                         0).astype(np.int32)))))
                x = self._backbone(
                    x, pos_dev, jnp.asarray(page_idx),
                    jnp.asarray(offset),
                    lambda i, q, k, v: _j_paged_attn(
                        q, self.cache.k[i], self.cache.v[i], bt,
                        pos_dev))
                logits, nxt = self._logits(x, b=b)
                jax.block_until_ready(nxt)
        finally:
            if trace.enabled:
                # comm-lint: disable=CL008 batch-scoped decode span covers every live rid at once
                trace.record_span(
                    "serve:decode_step", "serve", t0,
                    time.perf_counter(),
                    args={"active": int((positions >= 0).sum()),
                          "slots": b, "path": ("fused" if self.fused
                                               else "eager")})
        return np.asarray(jax.device_get(nxt))[0], logits

    def decode_window(self, tokens: np.ndarray,
                      positions: np.ndarray):
        """Teacher-forced k-token verify window for speculative
        decoding: ``tokens``/``positions`` are (max_seqs, k) — slot
        s's row is its last accepted token followed by k−1 draft
        tokens, at consecutive positions (−1 = inactive, whole row).
        All k KV rows are written to the slot's pages FIRST, then the
        flattened (max_seqs·k) batch attends with the causal position
        mask — within-window causality falls out of ``decode_attention``
        masking key positions > q_pos.  Returns (greedy next token per
        window position (max_seqs, k), logits (tp, max_seqs·k, V)).

        Rejection is the caller's job: truncate ``cache.seq_lens`` back
        to the accepted prefix — the stale KV rows beyond it are masked
        by every later query and get overwritten when the position is
        refilled.  The window rides whichever dispatch path the engine
        is configured for — eager (11 audited decode_ag/decode_rs) or
        fused (the same one-program collective-matmul rings at the
        window's row count) — and in both, window cost ≈ one step's
        dispatch cost, which is exactly why speculation wins on a
        dispatch-bound fabric."""
        from .. import trace
        b = self.max_seqs
        tokens = np.asarray(tokens, np.int32)
        positions = np.asarray(positions, np.int64)
        k = int(tokens.shape[1])
        slots = np.broadcast_to(np.arange(b)[:, None],
                                (b, k))
        page_idx, offset = self.cache.write_indices(slots, positions)
        bt = np.repeat(self.cache.block_tables, k, axis=0)
        flat_tok = np.where(positions >= 0, tokens, 0).reshape(-1)
        flat_pos = positions.reshape(-1)
        t0 = time.perf_counter()
        try:
            if self.fused:
                logits, nxt = self._decode_step_fused(
                    flat_tok, flat_pos, page_idx.reshape(-1),
                    offset.reshape(-1), bt)
                jax.block_until_ready(nxt)
            else:
                pos_dev = jnp.asarray(flat_pos.astype(np.int32))
                btj = jnp.asarray(bt)
                x = _j_regroup(self._ag(_j_embed(
                    self._embed,
                    jnp.asarray(flat_tok.astype(np.int32)))))
                x = self._backbone(
                    x, pos_dev, jnp.asarray(page_idx.reshape(-1)),
                    jnp.asarray(offset.reshape(-1)),
                    lambda i, q, kk, vv: _j_paged_attn(
                        q, self.cache.k[i], self.cache.v[i], btj,
                        pos_dev))
                logits, nxt = self._logits(x, b=b * k)
                jax.block_until_ready(nxt)
        finally:
            if trace.enabled:
                # comm-lint: disable=CL008 batch-scoped verify window covers every live rid at once
                trace.record_span(
                    "serve:decode_window", "serve", t0,
                    time.perf_counter(),
                    args={"active": int((positions[:, 0] >= 0).sum()),
                          "slots": b, "k": k})
        return (np.asarray(jax.device_get(nxt))[0].reshape(b, k),
                logits)

    # -- static verification (the commgraph proof) -------------------------

    def verify_decode_program(self):
        """Prove the fused decode program's static wire model against
        the runtime audit byte-for-byte: extract the jaxpr's ppermute
        trips (analysis/commgraph — scan trips multiplied through, the
        ring_attention precedent), run ONE real decode step, and
        compare static vs runtime per-coll wire deltas.  Returns the
        commgraph ``VerifyReport``; ``report.ok`` is the acceptance
        gate."""
        if not self.fused:
            raise ValueError("verify_decode_program needs "
                             "decode_overlap='fused'")
        from ..analysis import commgraph
        b = self.max_seqs
        zeros = np.zeros(b, np.int32)
        live = np.arange(b, dtype=np.int64) % 2  # mixed live/inactive
        positions = np.where(live > 0, 0, -1).astype(np.int64)
        page_idx, offset = self.cache.write_indices(np.arange(b),
                                                    positions)
        args = (jnp.zeros((self.dc.n, b, self.cfg.d_model),
                          jnp.dtype(self.cfg.dtype)),
                jnp.asarray(self.cache.block_tables),
                jnp.asarray(positions.astype(np.int32)),
                jnp.asarray(page_idx), jnp.asarray(offset),
                tuple(self._fused_layers),
                jnp.asarray(self._final_norm), self._embed_lg,
                tuple(self.cache.k), tuple(self.cache.v))

        def runner():
            self.decode_step(zeros, positions)

        return commgraph.verify(
            self._fused, args, self.dc.mesh,
            coll_map={"decode_collmm": "ppermute"}, runner=runner,
            source="serving.fused:decode")
