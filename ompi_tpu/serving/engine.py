"""Continuous-batching serving engine — prefill/decode over the decode
weight layout, decode collectives audited as ``decode_ag``/``decode_rs``.

Execution model (the host-orchestrated pattern of
``models/moe.moe_block_ep``): the per-layer compute is a handful of
jitted collective-free pieces over CANONICAL dim-0 arrays — every
weight shard lifted once at init through ``DeviceComm.canonicalize``
(a zero-wire local restack), every activation carried as ``(tp, B, …)``
— and the only cross-device traffic is the eagerly dispatched, audited
decode collectives between pieces.  That structure is what makes "one
decision event per decode collective" true by construction rather than
by instrumentation.

Dataflow per token step, consistent with
``models/transformer.decode_param_specs`` (all weights column-parallel,
output features sharded over ``tp``; the residual stream rides
replicated-content canonical form):

* embed lookup → ``decode_ag`` (combine the d/tp feature shards)
* per layer: qkv (local) → rope → paged-cache write (donated) →
  paged attention (local: heads are tp-sharded) → ``decode_ag`` (head
  combine) → wo (local) → ``decode_ag`` → +residual; mlp gate/up
  (local) → ``decode_ag`` (d_ff combine) → w_down (local) →
  ``decode_ag`` → +residual
* logits: per-device partial over its d/tp slice of the tied embedding
  → ``decode_rs`` + ``decode_ag`` (the bandwidth-bound psum: B×vocab
  float32 — exactly where the EQuARX int8 tier pays for itself)

Every dispatch runs the full decision chain (``coll/xla.decide_mode``:
force vars ``coll_xla_decode_ag_mode``/``coll_xla_decode_rs_mode`` >
blanket > learned > DEVICE_RULES rows > platform default) and fans out
the same audit record as ``coll/xla._audit``: arm/wire pvars, perf
``decode_*`` ledger cells, traffic ring-edge attribution (conservation:
edge-sum == ``coll_wire_bytes``), and the trace decision event.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import (_rms_norm, decode_attention,
                                  rope_rows)
from ..parallel.ring import attention_reference
from .cache import PagedKVCache

# -- jitted collective-free pieces (canonical dim-0 layout throughout) ------


def _regroup(y):
    """(tp, tp*B, c) allgather output → (tp, B, tp*c): per-token
    feature concat of the per-device column shards.  Each row is fully
    resident on one device, so this is a local reshape/transpose."""
    r, tb, c = y.shape
    b = tb // r
    return y.reshape(r, r, b, c).transpose(0, 2, 1, 3).reshape(r, b, r * c)


_j_regroup = jax.jit(_regroup)


@jax.jit
def _j_embed(embed_can, tokens):
    """(tp, V, d/tp), (B,) → (tp, B, d/tp) local embedding slices."""
    return jnp.take(embed_can, tokens, axis=1)


@partial(jax.jit, static_argnames=("head_dim", "base"))
def _j_qkv(x, norm_w, wqkv, pos, head_dim, base):
    """Residual (tp, B, d) → roped q, k, v (tp, B, heads/tp, head_dim).
    The qkv matmul is column-parallel: zero comm."""
    h = _rms_norm(x, norm_w)
    qkv = jnp.einsum("rbd,rdc->rbc", h, wqkv)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    r, b, c = q.shape
    q = rope_rows(q.reshape(r, b, c // head_dim, head_dim), pos, base)
    k = rope_rows(k.reshape(r, b, c // head_dim, head_dim), pos, base)
    return q, k, v.reshape(r, b, c // head_dim, head_dim)


@partial(jax.jit, donate_argnums=(0, 1))
def _j_page_write(kp, vp, k_new, v_new, page_idx, offset):
    """Scatter one k/v row per batch slot into its page — donated, so
    the pools update in place and cache data never visits the host."""
    kp = kp.at[:, page_idx, offset].set(k_new)
    vp = vp.at[:, page_idx, offset].set(v_new)
    return kp, vp


@jax.jit
def _j_paged_attn(q, kp, vp, bt, q_pos):
    """Decode attention against the paged pools: gather each slot's
    pages by block table, flatten to key positions, run the shared
    ``decode_attention`` core.  Heads are tp-sharded → fully local."""
    k = jnp.take(kp, bt, axis=1)       # (tp, B, pmax, page, hl, hd)
    v = jnp.take(vp, bt, axis=1)
    r, b, pmax, pg, hl, hd = k.shape
    k = k.reshape(r, b, pmax * pg, hl, hd)
    v = v.reshape(r, b, pmax * pg, hl, hd)
    att = decode_attention(q, k, v, q_pos)
    return att.reshape(r, b, hl * hd)


@jax.jit
def _j_prefill_attn(q, k, v):
    """Prompt-phase causal attention over the fresh q/k/v (the pages
    were just written; attending the in-register copies avoids the
    gather) — ``attention_reference`` with the tp rows as batch."""
    r, s, hl, hd = q.shape
    att = attention_reference(q, k, v, causal=True)
    return att.reshape(r, s, hl * hd)


@jax.jit
def _j_o_proj(ag_att, wo):
    return jnp.einsum("rbh,rhc->rbc", _regroup(ag_att), wo)


@jax.jit
def _j_mlp_in(ag_o, x, norm_w, wg, wu):
    x = x + _regroup(ag_o)
    h = _rms_norm(x, norm_w)
    g = jax.nn.silu(jnp.einsum("rbd,rdf->rbf", h, wg))
    u = jnp.einsum("rbd,rdf->rbf", h, wu)
    return x, g * u


@jax.jit
def _j_mlp_down(ag_z, wd):
    return jnp.einsum("rbf,rfc->rbc", _regroup(ag_z), wd)


@jax.jit
def _j_residual(ag_d, x):
    return x + _regroup(ag_d)


@jax.jit
def _j_logits_partial(x, norm_w, embed_can):
    """Per-device partial logits: each device multiplies ITS d/tp slice
    of the hidden state against its embedding columns — the partial
    sums then reduce through decode_rs + decode_ag (the audited psum)."""
    h = _rms_norm(x, norm_w)
    r, b, d = h.shape
    hs = h.reshape(r, b, r, d // r)
    idx = jnp.arange(r)
    hloc = hs[idx, :, idx, :]          # row r keeps its own slice
    part = jnp.einsum("rbd,rvd->rbv", hloc, embed_can)
    return part.reshape(r, b * part.shape[-1])


@partial(jax.jit, static_argnames=("b",))
def _j_logits_argmax(ag, b):
    r = ag.shape[0]
    logits = ag.reshape(r, b, -1).astype(jnp.float32)
    return logits, jnp.argmax(logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("s",))
def _j_last_pos(x, s):
    return x[:, s - 1:s, :]


# -- decision + audit shims (the moe.models pattern for custom colls) -------

def _decide_serve_coll(dc, coll: str, nbytes: int,
                       dtype) -> Tuple[str, str, List[str]]:
    """Decision shim over coll/xla.decide_mode for the decode coll
    names: per-entry/blanket force vars, DEVICE_RULES rows (plane-keyed
    included), the learned source — the full precedence chain.  The
    decode collectives are single-stage (flat tp ring), so the hier
    arms are ineligible by construction."""
    from ..coll.xla import _load_device_rules, decide_mode
    from ..op import SUM, quantizable
    from ..parallel.hierarchy import classify_axes
    axes = dc.axis if isinstance(dc.axis, tuple) else (dc.axis,)
    kinds = classify_axes(dc.mesh)
    plane = ("dcn" if any(kinds.get(a) == "dcn" for a in axes)
             else "ici")
    platform = next(iter(dc.mesh.devices.flat)).platform
    return decide_mode(coll, int(nbytes), dc.n, platform,
                       _load_device_rules(), ("native", "quant"),
                       quant_ok=quantizable(SUM, dtype), dtype=dtype,
                       op=None, plane=plane, hier_ok=False,
                       hier_why="decode collectives are single-stage")


def _audit_serve_coll(dc, coll: str, arm: str, reason: str,
                      chain: List[str], x, dur_s: float,
                      extra: Optional[Dict[str, Any]] = None) -> int:
    """ONE decision-audit record per decode collective — the same
    fan-out as coll/xla._audit: arm + wire pvars, an externally-timed
    perf sample (the ``decode_*`` ledger cells), traffic ring-edge
    attribution of the SAME wire figure (conservation's other half),
    and the trace decision event carrying the precedence chain."""
    from ..coll.quant import wire_bytes
    rows = max(x.shape[0], 1)
    nbytes = x.nbytes // rows
    qcoll = "allgather" if coll == "decode_ag" else "reduce_scatter"
    try:
        wb = wire_bytes(qcoll, max(x.size // rows, 1), dc.n, x.dtype)
    except (ValueError, TypeError):
        wb = None
    ratio = wb["ratio"] if wb is not None else None
    wire = nbytes
    if wb is not None:
        wire = wb["quant_bytes"] if arm == "quant" else wb["native_bytes"]
    spc = dc.spc
    if spc is not None:
        spc.inc(f"coll_arm_{arm}_count")
        spc.inc("coll_wire_bytes", int(wire))
    from ..parallel import simdcn
    if simdcn.us_per_mib() > 0:
        simdcn.charge(int(wire * simdcn.ring_dcn_fraction(dc.mesh,
                                                          dc.axis)))
    from .. import perf, trace, traffic
    if perf.enabled:
        perf.note_sample(coll, arm, int(wire), dur_s, dc.n)
    if traffic.enabled:
        traffic.note_coll(dc, coll, arm, int(wire))
    if trace.enabled:
        bucket = 1 << max(int(nbytes) - 1, 0).bit_length()
        trace.decision(coll, arm=arm, reason=reason, nbytes=int(nbytes),
                       shape_bucket=bucket, shape=tuple(x.shape),
                       dtype=str(x.dtype), ndev=dc.n,
                       wire_bytes=int(wire), quant_ratio=ratio,
                       chain=list(chain), **(extra or {}))
    return int(wire)


class ServingEngine:
    """Prefill + continuous decode over one tp DeviceComm.

    ``params`` arrive in the TRAIN layout by default and are converted
    on device through ``convert_params(to="decode")`` (the reshard
    engine — the serving tier is its first consumer in anger), then
    lifted shard-by-shard into canonical form with zero wire."""

    def __init__(self, dc, params: Dict, cfg, *, n_pages: int = 64,
                 page_size: int = 16, max_seqs: int = 8,
                 max_pages_per_seq: Optional[int] = None,
                 layout: str = "train") -> None:
        from ..models import transformer as tfm
        if cfg.mlp != "dense":
            raise ValueError("ServingEngine: decode path is dense-MLP "
                             f"only (cfg.mlp={cfg.mlp!r})")
        for name, dim in (("n_heads", cfg.n_heads),
                          ("d_model", cfg.d_model), ("d_ff", cfg.d_ff),
                          ("vocab", cfg.vocab)):
            if dim % dc.n:
                raise ValueError(
                    f"ServingEngine: cfg.{name}={dim} not divisible by "
                    f"the {dc.n}-way tp axis")
        if layout == "train":
            params = tfm.convert_params(params, dc.mesh, cfg,
                                        to="decode")
        elif layout != "decode":
            raise ValueError(f"layout={layout!r} (want train|decode)")
        self.dc = dc
        self.cfg = cfg
        self.max_seqs = int(max_seqs)
        cdt = jnp.dtype(cfg.dtype)

        def can(w):
            # weight-stationary: store in the compute dtype (the same
            # cast forward() pays per step) before the zero-wire restack
            return dc.canonicalize(w.astype(cdt), 1)

        def can_qkv(w):
            # the fused (d, 3h) weight is a global [q|k|v] column
            # concat: canonicalizing it whole would hand rank r a
            # contiguous 3h/tp chunk of that concat (all-q on the low
            # ranks), so the per-rank q/k/v split in _j_qkv would slice
            # the wrong columns.  Canonicalize each projection on its
            # own and re-concat per rank: row r = [q_r | k_r | v_r],
            # i.e. global head block r of each.
            h3 = w.shape[1] // 3
            return jnp.concatenate(
                [can(w[:, i * h3:(i + 1) * h3]) for i in range(3)],
                axis=-1)

        self._embed = can(params["embed"])             # (tp, V, d/tp)
        self._final_norm = params["final_norm"]
        self._layers: List[Dict[str, Any]] = [
            {"attn_norm": lw["attn_norm"],
             "wqkv": can_qkv(lw["wqkv"]),
             "wo": can(lw["wo"]),
             "mlp_norm": lw["mlp_norm"],
             "w_gate": can(lw["w_gate"]),
             "w_up": can(lw["w_up"]),
             "w_down": can(lw["w_down"])}
            for lw in params["layers"]]
        self.cache = PagedKVCache(
            dc, cfg.n_layers, cfg.n_heads, cfg.head_dim,
            n_pages=n_pages, page_size=page_size, max_seqs=max_seqs,
            max_pages_per_seq=max_pages_per_seq,
            dtype=jnp.dtype(cfg.dtype))
        self.dispatches: Dict[str, int] = {"decode_ag": 0,
                                           "decode_rs": 0}
        self.wire_bytes = 0

    # -- audited collective dispatch ---------------------------------------

    def _ag(self, x):
        t0 = time.perf_counter()
        arm, reason, chain = _decide_serve_coll(
            self.dc, "decode_ag", x.nbytes // x.shape[0], x.dtype)
        out = (self.dc.quant.allgather(x) if arm == "quant"
               else self.dc.allgather(x))
        dur = time.perf_counter() - t0
        self.wire_bytes += _audit_serve_coll(
            self.dc, "decode_ag", arm, reason, chain, x, dur)
        self.dispatches["decode_ag"] += 1
        return out

    def _rs(self, x):
        t0 = time.perf_counter()
        arm, reason, chain = _decide_serve_coll(
            self.dc, "decode_rs", x.nbytes // x.shape[0], x.dtype)
        out = (self.dc.quant.reduce_scatter(x) if arm == "quant"
               else self.dc.reduce_scatter(x))
        dur = time.perf_counter() - t0
        self.wire_bytes += _audit_serve_coll(
            self.dc, "decode_rs", arm, reason, chain, x, dur)
        self.dispatches["decode_rs"] += 1
        return out

    # -- forward pieces ----------------------------------------------------

    def _backbone(self, x, pos_dev, page_idx, offset,
                  attend: Callable) -> Any:
        cfg = self.cfg
        for i, lw in enumerate(self._layers):
            q, k, v = _j_qkv(x, lw["attn_norm"], lw["wqkv"], pos_dev,
                             head_dim=cfg.head_dim,
                             base=float(cfg.rope_base))
            self.cache.k[i], self.cache.v[i] = _j_page_write(
                self.cache.k[i], self.cache.v[i], k, v, page_idx,
                offset)
            att = attend(i, q, k, v)
            o = _j_o_proj(self._ag(att), lw["wo"])
            x, z = _j_mlp_in(self._ag(o), x, lw["mlp_norm"],
                             lw["w_gate"], lw["w_up"])
            d = _j_mlp_down(self._ag(z), lw["w_down"])
            x = _j_residual(self._ag(d), x)
        return x

    def _logits(self, x, b: int):
        part = _j_logits_partial(x, self._final_norm, self._embed)
        red = self._ag(self._rs(part))
        return _j_logits_argmax(red, b=b)

    @staticmethod
    def _bucket(n: int) -> int:
        p = 8
        while p < n:
            p *= 2
        return p

    # -- serving entry points ----------------------------------------------

    def prefill(self, slot: int, prompt: np.ndarray):
        """Run one request's prompt through the decode-layout model:
        writes its KV pages, returns (first greedy token, last-position
        logits (tp, 1, V)).  Prompts pad to a small power-of-2 bucket
        so compilations stay bounded; padded positions write to the
        scratch page and never enter the causal window."""
        from .. import trace
        prompt = np.asarray(prompt, np.int32)
        s = int(prompt.shape[0])
        spad = self._bucket(s)
        tok = np.zeros(spad, np.int32)
        tok[:s] = prompt
        positions = np.arange(spad, dtype=np.int64)
        live_pos = np.where(positions < s, positions, -1)
        page_idx, offset = self.cache.write_indices(
            np.full(spad, slot), live_pos)
        t0 = time.perf_counter()
        try:
            x = _j_regroup(self._ag(_j_embed(self._embed,
                                             jnp.asarray(tok))))
            x = self._backbone(
                x, jnp.asarray(positions.astype(np.int32)),
                jnp.asarray(page_idx), jnp.asarray(offset),
                lambda i, q, k, v: _j_prefill_attn(q, k, v))
            logits, nxt = self._logits(_j_last_pos(x, s=s), b=1)
            jax.block_until_ready(nxt)
        finally:
            if trace.enabled:
                trace.record_span("serve:prefill", "serve", t0,
                                  time.perf_counter(),
                                  args={"slot": slot, "prompt_len": s})
        self.cache.seq_lens[slot] = s
        return int(np.asarray(jax.device_get(nxt))[0, 0]), logits

    def decode_step(self, tokens: np.ndarray, positions: np.ndarray):
        """One continuous-batching decode step over the FULL device
        batch: ``tokens``/``positions`` are (max_seqs,) with
        position −1 marking an inactive slot (its lane computes masked
        garbage on the scratch page — the batch shape never changes, so
        one executable serves every occupancy).  Returns (next greedy
        token per slot (max_seqs,), logits (tp, max_seqs, V))."""
        from .. import trace
        b = self.max_seqs
        tokens = np.asarray(tokens, np.int32)
        positions = np.asarray(positions, np.int64)
        page_idx, offset = self.cache.write_indices(np.arange(b),
                                                    positions)
        t0 = time.perf_counter()
        try:
            bt = jnp.asarray(self.cache.block_tables)
            pos_dev = jnp.asarray(positions.astype(np.int32))
            x = _j_regroup(self._ag(_j_embed(
                self._embed,
                jnp.asarray(np.where(positions >= 0, tokens,
                                     0).astype(np.int32)))))
            x = self._backbone(
                x, pos_dev, jnp.asarray(page_idx), jnp.asarray(offset),
                lambda i, q, k, v: _j_paged_attn(
                    q, self.cache.k[i], self.cache.v[i], bt, pos_dev))
            logits, nxt = self._logits(x, b=b)
            jax.block_until_ready(nxt)
        finally:
            if trace.enabled:
                trace.record_span(
                    "serve:decode_step", "serve", t0,
                    time.perf_counter(),
                    args={"active": int((positions >= 0).sum()),
                          "slots": b})
        return np.asarray(jax.device_get(nxt))[0], logits
