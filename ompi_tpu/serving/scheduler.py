"""Request-level continuous batching over the ServingEngine.

The scheduler is pure host orchestration — admission, eviction and the
per-step device batch are integer bookkeeping against the paged cache;
all device work happens inside the engine's prefill/decode_step.  Two
policies share the loop so the serving bench can measure the tentpole
claim directly:

* ``continuous`` — admit whenever a batch slot AND the request's full
  page reservation are free, every step.  Finished sequences evict
  (EOS or max-new) and their slot refills on the next step, so the
  device batch stays full while requests of different lengths drain.
* ``static`` — the classic baseline: admit a wave only when the batch
  is EMPTY, then run the wave to completion.  Short requests finish
  early and their slots idle until the longest member drains.

Time is a virtual clock fed by MEASURED durations (prefill, decode
step, host bookkeeping): arrivals interleave against real step costs,
idle gaps jump to the next arrival, and the goodput split the serving
plane reports is the same wall time the clock integrated — so the
tokens/s the bench gates on is an end-to-end number, not a kernel
number.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .. import serving
from . import requests as _requests


@dataclass
class Request:
    """One inference request in the stream."""
    rid: int
    prompt: np.ndarray                 # (prompt_len,) int32 token ids
    max_new: int                       # generation budget (incl. the
                                       # prefill's first token)
    arrival: float = 0.0               # virtual-clock arrival time
    eos_id: Optional[int] = None       # per-request EOS override


def poisson_stream(n: int, qps: float, vocab: int, *, seed: int = 0,
                   prompt_len: tuple = (4, 16),
                   max_new: tuple = (4, 16),
                   eos_id: Optional[int] = None) -> List[Request]:
    """Synthetic open-loop request stream: exponential inter-arrival
    gaps at ``qps`` (a Poisson process), uniform prompt/generation
    lengths.  Deterministic under ``seed`` so the bench's continuous
    and static arms replay the IDENTICAL stream."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / qps, n)
    arrivals = np.cumsum(gaps)
    reqs = []
    for i in range(n):
        plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        reqs.append(Request(
            rid=i,
            prompt=rng.integers(0, vocab, plen).astype(np.int32),
            max_new=int(rng.integers(max_new[0], max_new[1] + 1)),
            arrival=float(arrivals[i]),
            eos_id=eos_id))
    return reqs


@dataclass
class _Active:
    req: Request
    slot: int
    tokens: List[int] = field(default_factory=list)
    last: int = 0                      # next decode step's input token


class ContinuousBatchingScheduler:
    """Drives one engine over a request stream; see module docstring."""

    def __init__(self, engine, requests: List[Request], *,
                 policy: str = "continuous",
                 eos_id: Optional[int] = None,
                 spec_k: int = 0) -> None:
        if policy not in ("continuous", "static"):
            raise ValueError(f"policy={policy!r} "
                             "(want continuous|static)")
        if spec_k < 0 or spec_k == 1:
            raise ValueError(f"spec_k={spec_k} (0 disables; >=2 sets "
                             "the draft/verify window length)")
        self.engine = engine
        self.policy = policy
        self.eos_id = eos_id
        self.spec_k = int(spec_k)
        self.pending: List[Request] = sorted(requests,
                                             key=lambda r: r.arrival)
        self.active: Dict[int, _Active] = {}       # slot -> state
        self.rank = 0                  # request-plane lane (replica id)
        self.clock = 0.0
        self.decode_steps = 0
        self.decode_s = 0.0
        self.occ_sum = 0.0
        self.results: Dict[int, Dict[str, Any]] = {}

    def _on_token(self, st: _Active) -> None:
        """Subclass hook: one emitted token for ``st`` at ``self.clock``
        (the fleet's per-replica ITL attribution overrides this)."""

    # -- lifecycle ---------------------------------------------------------

    def _admit_one(self, req: Request) -> None:
        cache = self.engine.cache
        slot = cache.admit(len(req.prompt), req.max_new)
        if serving.enabled:
            serving.note_admit(req.rid, len(req.prompt), req.max_new,
                               req.arrival, self.clock)
            serving.set_pages_used(cache.pages_used)
        if _requests.enabled:
            _requests.note_admit(req.rid, req.arrival, self.clock,
                                 len(req.prompt), req.max_new,
                                 replica=self.rank)
        t0 = time.perf_counter()
        first, _ = self.engine.prefill(slot, req.prompt, rid=req.rid)
        dur = time.perf_counter() - t0
        self.clock += dur
        st = _Active(req=req, slot=slot, tokens=[first], last=first)
        self.active[slot] = st
        if serving.enabled:
            serving.note_prefill(dur, len(req.prompt))
            serving.note_token(req.rid, self.clock)
        if _requests.enabled:
            _requests.note_stage(req.rid, "prefill", self.clock - dur,
                                 self.clock, rank=self.rank)
            _requests.note_token(req.rid, self.clock, rank=self.rank)
        self._on_token(st)
        self._maybe_finish(st, first)

    def _finish(self, st: _Active, reason: str) -> None:
        self.engine.cache.release(st.slot)
        del self.active[st.slot]
        self.results[st.req.rid] = {
            "rid": st.req.rid, "tokens": list(st.tokens),
            "reason": reason, "finished_at": self.clock}
        if serving.enabled:
            serving.note_evict(st.req.rid, reason, self.clock)
            serving.set_pages_used(self.engine.cache.pages_used)
        if _requests.enabled:
            _requests.note_finish(st.req.rid, self.clock, reason)

    def _maybe_finish(self, st: _Active, tok: int) -> bool:
        eos = (st.req.eos_id if st.req.eos_id is not None
               else self.eos_id)
        if eos is not None and tok == eos:
            self._finish(st, "eos")
            return True
        if len(st.tokens) >= st.req.max_new:
            self._finish(st, "max_new")
            return True
        return False

    def _admissible(self) -> bool:
        if not self.pending or self.pending[0].arrival > self.clock:
            return False
        if self.policy == "static" and self.active:
            return False
        req = self.pending[0]
        return self.engine.cache.can_admit(len(req.prompt), req.max_new)

    # -- the loop ----------------------------------------------------------

    def run(self, max_steps: int = 100000) -> Dict[str, Any]:
        cache = self.engine.cache
        while self.pending or self.active:
            th0 = time.perf_counter()
            while self._admissible():
                host = time.perf_counter() - th0
                self.clock += host
                if serving.enabled:
                    serving.note_host(host)
                self._admit_one(self.pending.pop(0))
                th0 = time.perf_counter()
            host = time.perf_counter() - th0
            self.clock += host
            if serving.enabled:
                serving.note_host(host)
            if not self.active:
                if not self.pending:
                    break
                # idle: jump the virtual clock to the next arrival
                self.clock = max(self.clock, self.pending[0].arrival)
                continue
            if self.spec_k >= 2:
                self._step_spec()
            else:
                self._step()
            if self.decode_steps >= max_steps:
                raise RuntimeError(f"scheduler exceeded {max_steps} "
                                   "decode steps without draining")
        return self.summary()

    def _step(self) -> None:
        cache = self.engine.cache
        b = self.engine.max_seqs
        tokens = np.zeros(b, np.int32)
        positions = np.full(b, -1, np.int64)
        for slot, st in self.active.items():
            tokens[slot] = st.last
            positions[slot] = int(cache.seq_lens[slot])
        t0 = time.perf_counter()
        nxt, _ = self.engine.decode_step(tokens, positions)
        dur = time.perf_counter() - t0
        self.clock += dur
        self.decode_steps += 1
        self.decode_s += dur
        self.occ_sum += len(self.active) / b
        if serving.enabled:
            serving.note_decode_step(dur, len(self.active), b)
        th0 = time.perf_counter()
        for slot in list(self.active):
            st = self.active[slot]
            cache.seq_lens[slot] += 1          # the input token's kv
            tok = int(nxt[slot])
            st.tokens.append(tok)
            st.last = tok
            if serving.enabled:
                serving.note_token(st.req.rid, self.clock)
            if _requests.enabled:
                _requests.note_token(st.req.rid, self.clock,
                                     rank=self.rank)
            self._on_token(st)
            self._maybe_finish(st, tok)
        host = time.perf_counter() - th0
        self.clock += host
        if serving.enabled:
            serving.note_host(host)

    # -- speculative decoding (spec_k >= 2) --------------------------------

    @staticmethod
    def _draft(history: List[int], n: int) -> List[int]:
        """n-gram SELF-draft: continue the sequence by the most recent
        bigram match in the request's own history (prompt + emitted
        tokens), falling back to repeating the last token.  Free — no
        second model — and measurably nonzero on any stream with local
        structure; the acceptance rate is MEASURED by the verify loop
        (serving.note_spec), never assumed."""
        work = list(history)
        out: List[int] = []
        for _ in range(n):
            d = None
            if len(work) >= 2:
                prev, last = work[-2], work[-1]
                for i in range(len(work) - 3, -1, -1):
                    if work[i] == prev and work[i + 1] == last:
                        d = work[i + 2]
                        break
            if d is None:
                d = work[-1]
            out.append(d)
            work.append(d)
        return out

    def _step_spec(self) -> None:
        """One draft/verify window: each active slot runs its next
        input token plus ``spec_k − 1`` draft tokens through ONE
        teacher-forced ``decode_window`` call, then accepts the longest
        prefix where draft i equals the model's greedy output at window
        position i−1 — so every emitted token is EXACTLY the token
        non-speculative greedy would have produced, and a rejection is
        a block-table truncate (``cache.seq_lens`` rolls back to the
        accepted prefix; the stale KV rows are masked and later
        overwritten)."""
        cache = self.engine.cache
        b, k = self.engine.max_seqs, self.spec_k
        tokens = np.zeros((b, k), np.int32)
        positions = np.full((b, k), -1, np.int64)
        drafts: Dict[int, List[int]] = {}
        for slot, st in self.active.items():
            d = self._draft(list(st.req.prompt) + st.tokens, k - 1)
            drafts[slot] = d
            tokens[slot] = [st.last] + d
            p = int(cache.seq_lens[slot])
            positions[slot] = np.arange(p, p + k)
        t0 = time.perf_counter()
        nxt, _ = self.engine.decode_window(tokens, positions)
        dur = time.perf_counter() - t0
        self.clock += dur
        self.decode_steps += 1
        self.decode_s += dur
        self.occ_sum += len(self.active) / b
        if serving.enabled:
            serving.note_decode_step(dur, len(self.active), b)
        th0 = time.perf_counter()
        for slot in list(self.active):
            st = self.active[slot]
            d = drafts[slot]
            y = [int(t) for t in nxt[slot]]
            j = 0
            while j < k - 1 and d[j] == y[j]:
                j += 1
            if serving.enabled:
                serving.note_spec(k - 1, j)
            emitted = 0
            finished = False
            for i in range(j + 1):       # y_0..y_j are all greedy-true
                tok = y[i]
                st.tokens.append(tok)
                st.last = tok
                emitted += 1
                if serving.enabled:
                    serving.note_token(st.req.rid, self.clock)
                if _requests.enabled:
                    _requests.note_token(st.req.rid, self.clock,
                                         rank=self.rank)
                self._on_token(st)
                if self._maybe_finish(st, tok):
                    finished = True
                    break
            if not finished:
                # consumed tokens = the input + the accepted drafts:
                # one KV row each; everything past it is rolled back
                cache.seq_lens[slot] = int(positions[slot, 0]) + emitted
        host = time.perf_counter() - th0
        self.clock += host
        if serving.enabled:
            serving.note_host(host)

    def summary(self) -> Dict[str, Any]:
        toks = sum(len(r["tokens"]) for r in self.results.values())
        return {
            "policy": self.policy,
            "clock_s": self.clock,
            "decode_steps": self.decode_steps,
            "completed": len(self.results),
            "tokens": toks,
            "tokens_per_s": toks / self.clock if self.clock else 0.0,
            "results": self.results,
        }


class FleetRouter:
    """Deterministic weighted admission across fleet replicas.

    Deficit weighted round-robin: every assignment credits each replica
    its share of the effective weight vector and picks the replica with
    the largest accumulated credit (ties break to the LOWEST replica
    id), then debits the winner one unit.  The decision is a pure
    function of the weight/credit history, so two routers fed identical
    streams under identical weights produce identical assignments — the
    property the fleet determinism test pins.

    Two inputs move the weights: ``update(replica, tokens_per_s,
    itl_p99_ms)`` feeds the serving plane's live goodput/ITL (a hot
    replica — high tail latency per unit goodput — loses share), and
    the policy plane's ``route_weight`` action multiplies a per-replica
    bias (``serving.fleet_route_bias``) read on EVERY assignment, so an
    audited ``decide:fleet_route`` shifts admission immediately."""

    def __init__(self, n: int,
                 weights: Optional[List[float]] = None) -> None:
        if n < 1:
            raise ValueError(f"n={n} (want >= 1 replicas)")
        if weights is not None and len(weights) != n:
            raise ValueError(f"{len(weights)} weights for {n} replicas")
        self.n = int(n)
        self.weights = ([1.0] * n if weights is None
                        else [float(w) for w in weights])
        self._credits = [0.0] * n

    def set_weight(self, replica: int, w: float) -> None:
        self.weights[int(replica)] = max(float(w), 0.0)

    def update(self, replica: int, tokens_per_s: float,
               itl_p99_ms: float) -> None:
        """Live reweighting from a replica's serving-plane stats:
        goodput per unit of tail latency, so slow-tail replicas shed
        admission share proportionally."""
        self.weights[int(replica)] = (max(float(tokens_per_s), 0.0)
                                      / max(float(itl_p99_ms), 1e-3))

    def effective_weights(self) -> List[float]:
        eff = [max(self.weights[i], 0.0)
               * serving.fleet_route_bias(i) for i in range(self.n)]
        if not any(w > 0.0 for w in eff):
            eff = [1.0] * self.n           # all-zero: fall back to even
        return eff

    def assign(self, rid: Any) -> int:
        eff = self.effective_weights()
        tot = sum(eff)
        for i in range(self.n):
            self._credits[i] += eff[i] / tot
        pick = 0
        for i in range(1, self.n):
            if self._credits[i] > self._credits[pick] + 1e-12:
                pick = i
        self._credits[pick] -= 1.0
        if serving.enabled:
            serving.note_route(rid, pick, eff)
        if _requests.enabled:
            # the weight snapshot rides the route DECISION event too, so
            # "why this replica" is answerable from the trace alone
            _requests.note_route(rid, pick, eff)
        return pick
