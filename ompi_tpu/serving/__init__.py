"""Serving plane — continuous-batching decode observability.

The serving tier (ROADMAP item 2) is the repo's first latency-bound hot
path: a continuous-batching inference engine over the decode weight
layout (models/transformer.decode_param_specs), with the decode matmul
combines dispatched as the audited coll names ``decode_ag`` /
``decode_rs`` so the decision layer's native|quant arms apply.  This
module is the plane's ledger — counters, the goodput split, inter-token
latency and the per-request table ``comm_doctor --serve`` renders:

* **counters** — ``serve_tokens`` / ``serve_active_seqs`` /
  ``serve_evictions`` / ``serve_kv_pages_used`` pvars (read-through in
  ``spc.py`` under the Prometheus grammar).
* **goodput split** — wall time attributed to prefill / decode / host
  (scheduler bookkeeping): the serving analog of the training tier's
  compute/comm/stall split, plus decode tokens/s.
* **inter-token latency** — per-request deltas between consecutive
  emitted tokens (a bounded sample window), p50/p99 in ``report()``;
  the engine additionally emits ``serve:prefill`` / ``serve:decode``
  trace spans so the fleet timeline carries the same story.
* **request table** — admit → prefill → decode → evict lifecycle rows
  (EOS vs max-len vs drain), bounded to the most recent requests.

The compute/dispatch pieces live in the submodules: ``cache`` (the
paged KV cache), ``engine`` (prefill/decode_step + the decode_ag/rs
dispatch shims), ``scheduler`` (continuous vs static batching and the
Poisson request stream).  They import jax; this module must stay
importable by spc.py's read-through without pulling the runtime in.

All entry points are behind ONE ``serving.enabled`` attribute read —
the same disabled-path bar as trace/health/perf/traffic/moe.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..core import var as _var

_var.register("serve", "", "enabled", False, type=bool, level=3,
              help="Master switch for the serving plane (request table, "
                   "goodput split, inter-token latency ledger). Off by "
                   "default; the disabled path is one attribute read "
                   "per engine/scheduler event.")
_var.register("serve", "", "latency_window", 4096, type=int, level=3,
              help="Inter-token latency samples kept for the p50/p99 "
                   "ledger (bounded ring; oldest samples drop first).")
_var.register("serve", "", "table_cap", 64, type=int, level=3,
              help="Request-lifecycle rows kept for comm_doctor "
                   "--serve's per-request table (oldest finished rows "
                   "drop first).")

enabled: bool = bool(_var.get("serve_enabled", False))

PVARS = ("serve_tokens", "serve_active_seqs", "serve_evictions",
         "serve_kv_pages_used")

_lock = threading.Lock()

# cumulative counters (pvars + report)
_tokens = 0                  # decode tokens emitted (prefill's first
                             # token counts: it is the request's first
                             # emission)
_evictions = 0
_active = 0                  # current in-flight sequences
_pages_used = 0              # current KV pages held (cache mirrors in)
_prefills = 0
_decode_steps = 0
_prefill_s = 0.0
_decode_s = 0.0
_host_s = 0.0
_occ_sum = 0.0               # sum over decode steps of active/slots
_itl: List[float] = []       # inter-token deltas, seconds
_requests: "dict[Any, Dict[str, Any]]" = {}
_finished_order: List[Any] = []
_spec_drafted = 0            # speculative: draft tokens proposed
_spec_accepted = 0           # speculative: draft tokens accepted
_spec_windows = 0            # speculative: verify windows run
_dispatches: Dict[str, int] = {"eager": 0, "fused": 0}


def enable() -> None:
    global enabled
    enabled = True


def disable() -> None:
    global enabled
    enabled = False


def _on_enabled_var(v: Any) -> None:
    # mid-run OMPI_TPU_SERVE_ENABLED / set_cli writes take effect
    global enabled
    enabled = bool(v)


_var.watch("serve_enabled", _on_enabled_var)


def reset() -> None:
    global _tokens, _evictions, _active, _pages_used, _prefills, \
        _decode_steps, _prefill_s, _decode_s, _host_s, _occ_sum, \
        _spec_drafted, _spec_accepted, _spec_windows
    with _lock:
        _tokens = 0
        _evictions = 0
        _active = 0
        _pages_used = 0
        _prefills = 0
        _decode_steps = 0
        _prefill_s = 0.0
        _decode_s = 0.0
        _host_s = 0.0
        _occ_sum = 0.0
        _spec_drafted = 0
        _spec_accepted = 0
        _spec_windows = 0
        _dispatches["eager"] = 0
        _dispatches["fused"] = 0
        _itl.clear()
        _requests.clear()
        _finished_order.clear()


# -- lifecycle events (the engine/scheduler call these when enabled) --------

def note_admit(rid: Any, prompt_len: int, max_new: int,
               arrival: float, now: float) -> None:
    global _active
    with _lock:
        _active += 1
        _requests[rid] = {"rid": rid, "state": "prefill",
                          "prompt_len": int(prompt_len),
                          "max_new": int(max_new), "generated": 0,
                          "arrival": float(arrival),
                          "admitted": float(now),
                          "queue_wait_s": float(now - arrival),
                          "finished": None, "evict_reason": None,
                          "_last_token_t": None}
        if len(_requests) > int(_var.get("serve_table_cap", 64)):
            # drop the OLDEST finished row; live rows are never dropped
            for old in list(_finished_order):
                if old in _requests:
                    del _requests[old]
                    _finished_order.remove(old)
                    break


def note_prefill(dur_s: float, n_tokens: int) -> None:
    global _prefills, _prefill_s
    with _lock:
        _prefills += 1
        _prefill_s += float(dur_s)


def note_decode_step(dur_s: float, active: int, slots: int) -> None:
    global _decode_steps, _decode_s, _occ_sum
    with _lock:
        _decode_steps += 1
        _decode_s += float(dur_s)
        _occ_sum += active / max(slots, 1)


def note_host(dur_s: float) -> None:
    global _host_s
    with _lock:
        _host_s += float(dur_s)


def note_token(rid: Any, now: float) -> None:
    global _tokens
    with _lock:
        _tokens += 1
        row = _requests.get(rid)
        if row is None:
            return
        row["generated"] += 1
        row["state"] = "decode"
        last = row["_last_token_t"]
        if last is not None:
            _itl.append(float(now - last))
            cap = int(_var.get("serve_latency_window", 4096))
            if len(_itl) > cap:
                del _itl[: len(_itl) - cap]
        row["_last_token_t"] = float(now)


def note_evict(rid: Any, reason: str, now: float) -> None:
    global _active, _evictions
    with _lock:
        _active = max(_active - 1, 0)
        _evictions += 1
        row = _requests.get(rid)
        if row is not None:
            row["state"] = "done"
            row["finished"] = float(now)
            row["evict_reason"] = str(reason)
            _finished_order.append(rid)


def set_pages_used(n: int) -> None:
    global _pages_used
    with _lock:
        _pages_used = int(n)


def note_spec(drafted: int, accepted: int) -> None:
    """One speculative verify window: ``drafted`` tokens proposed by the
    draft source, ``accepted`` of them matched the target model's greedy
    choice (0 ≤ accepted ≤ drafted).  The MEASURED acceptance rate —
    accepted/drafted over the run — is the number bench banks; it is
    never assumed."""
    global _spec_drafted, _spec_accepted, _spec_windows
    with _lock:
        _spec_drafted += int(drafted)
        _spec_accepted += int(accepted)
        _spec_windows += 1


def note_dispatch(mode: str, n: int = 1) -> None:
    """Count an eagerly dispatched decode collective (``mode="eager"``:
    decode_ag/decode_rs between jitted pieces) or a fused-program ring
    (``mode="fused"``: a decode_collmm site inside the one jitted
    program) — comm_doctor --serve renders the fused-vs-eager split."""
    with _lock:
        _dispatches[mode] = _dispatches.get(mode, 0) + int(n)


# -- pvar read-through + report ---------------------------------------------

def pvar_value(name: str) -> float:
    with _lock:
        if name == "serve_tokens":
            return float(_tokens)
        if name == "serve_active_seqs":
            return float(_active)
        if name == "serve_evictions":
            return float(_evictions)
        if name == "serve_kv_pages_used":
            return float(_pages_used)
    raise KeyError(name)


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(int(round(q * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[k]


def report() -> Dict[str, Any]:
    """Structured plane state for comm_doctor --serve / bench --serve."""
    with _lock:
        itl = sorted(_itl)
        total = _prefill_s + _decode_s + _host_s
        rows = []
        for row in _requests.values():
            r = {k: v for k, v in row.items()
                 if not k.startswith("_")}
            rows.append(r)
        return {
            "tokens": _tokens,
            "active_seqs": _active,
            "evictions": _evictions,
            "kv_pages_used": _pages_used,
            "prefills": _prefills,
            "decode_steps": _decode_steps,
            "batch_occupancy": _occ_sum / max(_decode_steps, 1),
            "goodput": {
                "prefill_s": round(_prefill_s, 6),
                "decode_s": round(_decode_s, 6),
                "host_s": round(_host_s, 6),
                "total_s": round(total, 6),
                "prefill_pct": 100.0 * _prefill_s / total if total else 0.0,
                "decode_pct": 100.0 * _decode_s / total if total else 0.0,
                "host_pct": 100.0 * _host_s / total if total else 0.0,
                "decode_tokens_per_s": (_tokens / _decode_s
                                        if _decode_s else 0.0),
            },
            "itl": {
                "count": len(itl),
                "p50_ms": 1e3 * _percentile(itl, 0.50),
                "p99_ms": 1e3 * _percentile(itl, 0.99),
                "mean_ms": (1e3 * sum(itl) / len(itl)) if itl else 0.0,
            },
            "speculative": {
                "windows": _spec_windows,
                "drafted": _spec_drafted,
                "accepted": _spec_accepted,
                "acceptance_rate": (_spec_accepted / _spec_drafted
                                    if _spec_drafted else 0.0),
            },
            "dispatches": dict(_dispatches),
            "requests": rows,
        }


# the engine/scheduler/cache classes import jax — load them lazily so
# spc.py's pvar read-through never drags the runtime in
def __getattr__(name: str):
    if name in ("ServingEngine",):
        from .engine import ServingEngine
        return ServingEngine
    if name in ("PagedKVCache",):
        from .cache import PagedKVCache
        return PagedKVCache
    if name in ("ContinuousBatchingScheduler", "Request",
                "poisson_stream"):
        from . import scheduler as _sched
        return getattr(_sched, name)
    raise AttributeError(name)
