"""Serving plane — continuous-batching decode observability.

The serving tier (ROADMAP item 2) is the repo's first latency-bound hot
path: a continuous-batching inference engine over the decode weight
layout (models/transformer.decode_param_specs), with the decode matmul
combines dispatched as the audited coll names ``decode_ag`` /
``decode_rs`` so the decision layer's native|quant arms apply.  This
module is the plane's ledger — counters, the goodput split, inter-token
latency and the per-request table ``comm_doctor --serve`` renders:

* **counters** — ``serve_tokens`` / ``serve_active_seqs`` /
  ``serve_evictions`` / ``serve_kv_pages_used`` pvars (read-through in
  ``spc.py`` under the Prometheus grammar).
* **goodput split** — wall time attributed to prefill / decode / host
  (scheduler bookkeeping): the serving analog of the training tier's
  compute/comm/stall split, plus decode tokens/s.
* **inter-token latency** — per-request deltas between consecutive
  emitted tokens (a bounded sample window), p50/p99 in ``report()``;
  the engine additionally emits ``serve:prefill`` / ``serve:decode``
  trace spans so the fleet timeline carries the same story.
* **request table** — admit → prefill → decode → evict lifecycle rows
  (EOS vs max-len vs drain), bounded to the most recent requests.

The compute/dispatch pieces live in the submodules: ``cache`` (the
paged KV cache), ``engine`` (prefill/decode_step + the decode_ag/rs
dispatch shims), ``scheduler`` (continuous vs static batching and the
Poisson request stream).  They import jax; this module must stay
importable by spc.py's read-through without pulling the runtime in.

All entry points are behind ONE ``serving.enabled`` attribute read —
the same disabled-path bar as trace/health/perf/traffic/moe.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..core import var as _var

_var.register("serve", "", "enabled", False, type=bool, level=3,
              help="Master switch for the serving plane (request table, "
                   "goodput split, inter-token latency ledger). Off by "
                   "default; the disabled path is one attribute read "
                   "per engine/scheduler event.")
_var.register("serve", "", "latency_window", 4096, type=int, level=3,
              help="Inter-token latency samples kept for the p50/p99 "
                   "ledger (bounded ring; oldest samples drop first).")
_var.register("serve", "", "table_cap", 64, type=int, level=3,
              help="Request-lifecycle rows kept for comm_doctor "
                   "--serve's per-request table (oldest finished rows "
                   "drop first).")
_var.register("serve", "fleet", "route_scale", 0.5, type=float, level=3,
              help="Admission-weight multiplier the policy plane's "
                   "route_weight action applies to a hot replica "
                   "(< 1 shifts load away; the router reads the "
                   "accumulated per-replica bias on every assignment).")
_var.register("serve", "fleet", "hot_skew", 1.75, type=float, level=3,
              help="p99-ITL skew vs the fleet median that trips the "
                   "hot_replica sentry (episode semantics: one verdict "
                   "per excursion, re-armed when the skew recovers "
                   "below 90% of the threshold).")
_var.register("serve", "fleet", "table_cap", 64, type=int, level=3,
              help="Router-decision and migration-ledger rows kept for "
                   "comm_doctor --fleet (oldest rows drop first).")

enabled: bool = bool(_var.get("serve_enabled", False))

PVARS = ("serve_tokens", "serve_active_seqs", "serve_evictions",
         "serve_kv_pages_used")
FLEET_PVARS = ("fleet_replicas", "fleet_migrations",
               "fleet_migrated_bytes", "fleet_rebalances")

_lock = threading.Lock()

# cumulative counters (pvars + report)
_tokens = 0                  # decode tokens emitted (prefill's first
                             # token counts: it is the request's first
                             # emission)
_evictions = 0
_active = 0                  # current in-flight sequences
_pages_used = 0              # current KV pages held (cache mirrors in)
_prefills = 0
_decode_steps = 0
_prefill_s = 0.0
_decode_s = 0.0
_host_s = 0.0
_occ_sum = 0.0               # sum over decode steps of active/slots
_itl: List[float] = []       # inter-token deltas, seconds
_requests: "dict[Any, Dict[str, Any]]" = {}
_finished_order: List[Any] = []
_spec_drafted = 0            # speculative: draft tokens proposed
_spec_accepted = 0           # speculative: draft tokens accepted
_spec_windows = 0            # speculative: verify windows run
_dispatches: Dict[str, int] = {"eager": 0, "fused": 0}

# fleet ledger (multi-replica tier; jax-free so spc read-through stays
# import-light)
_fleet_replicas = 0          # replicas in the most recent fleet
_fleet_migrations = 0        # KV-page migrations (cross_reshard hops)
_fleet_migrated_bytes = 0    # wire bytes those migrations moved
_fleet_rebalances = 0        # route_weight applications (policy action)
_fleet_rows: Dict[int, Dict[str, Any]] = {}      # replica -> stats row
_fleet_migration_log: List[Dict[str, Any]] = []  # bounded ledger
_fleet_routes: List[Dict[str, Any]] = []         # bounded decision table
_fleet_route_bias: Dict[int, float] = {}         # replica -> multiplier


def enable() -> None:
    global enabled
    enabled = True


def disable() -> None:
    global enabled
    enabled = False


def _on_enabled_var(v: Any) -> None:
    # mid-run OMPI_TPU_SERVE_ENABLED / set_cli writes take effect
    global enabled
    enabled = bool(v)


_var.watch("serve_enabled", _on_enabled_var)


def reset() -> None:
    global _tokens, _evictions, _active, _pages_used, _prefills, \
        _decode_steps, _prefill_s, _decode_s, _host_s, _occ_sum, \
        _spec_drafted, _spec_accepted, _spec_windows, \
        _fleet_replicas, _fleet_migrations, _fleet_migrated_bytes, \
        _fleet_rebalances
    with _lock:
        _fleet_replicas = 0
        _fleet_migrations = 0
        _fleet_migrated_bytes = 0
        _fleet_rebalances = 0
        _fleet_rows.clear()
        _fleet_migration_log.clear()
        _fleet_routes.clear()
        _fleet_route_bias.clear()
        _tokens = 0
        _evictions = 0
        _active = 0
        _pages_used = 0
        _prefills = 0
        _decode_steps = 0
        _prefill_s = 0.0
        _decode_s = 0.0
        _host_s = 0.0
        _occ_sum = 0.0
        _spec_drafted = 0
        _spec_accepted = 0
        _spec_windows = 0
        _dispatches["eager"] = 0
        _dispatches["fused"] = 0
        _itl.clear()
        _requests.clear()
        _finished_order.clear()


# -- lifecycle events (the engine/scheduler call these when enabled) --------

def note_admit(rid: Any, prompt_len: int, max_new: int,
               arrival: float, now: float) -> None:
    global _active
    with _lock:
        _active += 1
        _requests[rid] = {"rid": rid, "state": "prefill",
                          "prompt_len": int(prompt_len),
                          "max_new": int(max_new), "generated": 0,
                          "arrival": float(arrival),
                          "admitted": float(now),
                          "queue_wait_s": float(now - arrival),
                          "finished": None, "evict_reason": None,
                          "_last_token_t": None}
        if len(_requests) > int(_var.get("serve_table_cap", 64)):
            # drop the OLDEST finished row; live rows are never dropped
            for old in list(_finished_order):
                if old in _requests:
                    del _requests[old]
                    _finished_order.remove(old)
                    break


def note_prefill(dur_s: float, n_tokens: int) -> None:
    global _prefills, _prefill_s
    with _lock:
        _prefills += 1
        _prefill_s += float(dur_s)


def note_decode_step(dur_s: float, active: int, slots: int) -> None:
    global _decode_steps, _decode_s, _occ_sum
    with _lock:
        _decode_steps += 1
        _decode_s += float(dur_s)
        _occ_sum += active / max(slots, 1)


def note_host(dur_s: float) -> None:
    global _host_s
    with _lock:
        _host_s += float(dur_s)


def note_token(rid: Any, now: float) -> None:
    global _tokens
    with _lock:
        _tokens += 1
        row = _requests.get(rid)
        if row is None:
            return
        row["generated"] += 1
        row["state"] = "decode"
        last = row["_last_token_t"]
        if last is not None:
            _itl.append(float(now - last))
            cap = int(_var.get("serve_latency_window", 4096))
            if len(_itl) > cap:
                del _itl[: len(_itl) - cap]
        row["_last_token_t"] = float(now)


def note_evict(rid: Any, reason: str, now: float) -> None:
    global _active, _evictions
    with _lock:
        _active = max(_active - 1, 0)
        _evictions += 1
        row = _requests.get(rid)
        if row is not None:
            row["state"] = "done"
            row["finished"] = float(now)
            row["evict_reason"] = str(reason)
            _finished_order.append(rid)


def set_pages_used(n: int) -> None:
    global _pages_used
    with _lock:
        _pages_used = int(n)


def note_spec(drafted: int, accepted: int) -> None:
    """One speculative verify window: ``drafted`` tokens proposed by the
    draft source, ``accepted`` of them matched the target model's greedy
    choice (0 ≤ accepted ≤ drafted).  The MEASURED acceptance rate —
    accepted/drafted over the run — is the number bench banks; it is
    never assumed."""
    global _spec_drafted, _spec_accepted, _spec_windows
    with _lock:
        _spec_drafted += int(drafted)
        _spec_accepted += int(accepted)
        _spec_windows += 1


def note_dispatch(mode: str, n: int = 1) -> None:
    """Count an eagerly dispatched decode collective (``mode="eager"``:
    decode_ag/decode_rs between jitted pieces) or a fused-program ring
    (``mode="fused"``: a decode_collmm site inside the one jitted
    program) — comm_doctor --serve renders the fused-vs-eager split."""
    with _lock:
        _dispatches[mode] = _dispatches.get(mode, 0) + int(n)


# -- fleet ledger (multi-replica tier) --------------------------------------

def set_fleet_replicas(n: int) -> None:
    global _fleet_replicas
    with _lock:
        _fleet_replicas = int(n)


def note_migration(rid: Any, src: int, dst: int, pages: int,
                   nbytes: int, peak_bytes: int, bound_bytes: int,
                   dur_s: float) -> None:
    """One KV-page migration: prefill replica ``src`` handed ``pages``
    finished pages (``nbytes`` on the wire via cross_reshard) to decode
    replica ``dst``.  peak/bound come from the reshard plan so the
    ledger shows every migration's standing under the
    ``reshard_peak_factor`` contract."""
    global _fleet_migrations, _fleet_migrated_bytes
    with _lock:
        _fleet_migrations += 1
        _fleet_migrated_bytes += int(nbytes)
        _fleet_migration_log.append({
            "rid": rid, "src": int(src), "dst": int(dst),
            "pages": int(pages), "bytes": int(nbytes),
            "peak_bytes": int(peak_bytes),
            "bound_bytes": int(bound_bytes),
            "within_bound": int(peak_bytes) <= int(bound_bytes),
            "dur_ms": 1e3 * float(dur_s),
        })
        cap = int(_var.get("serve_fleet_table_cap", 64))
        if len(_fleet_migration_log) > cap:
            del _fleet_migration_log[: len(_fleet_migration_log) - cap]


def note_route(rid: Any, replica: int, weights: List[float]) -> None:
    """One router admission decision: request ``rid`` assigned to
    ``replica`` under the effective (bias-adjusted) weight vector."""
    with _lock:
        _fleet_routes.append({"rid": rid, "replica": int(replica),
                              "weights": [round(float(w), 6)
                                          for w in weights]})
        cap = int(_var.get("serve_fleet_table_cap", 64))
        if len(_fleet_routes) > cap:
            del _fleet_routes[: len(_fleet_routes) - cap]


def update_replica(replica: int, row: Dict[str, Any]) -> None:
    """Merge a per-replica stats row (role, requests, tokens, goodput,
    ITL percentiles, occupancy) into the fleet table."""
    with _lock:
        cur = _fleet_rows.setdefault(int(replica),
                                     {"replica": int(replica)})
        cur.update(row)


def fleet_route_bias(replica: int) -> float:
    """Admission-weight multiplier for ``replica`` (1.0 until a
    route_weight action downweights it)."""
    with _lock:
        return float(_fleet_route_bias.get(int(replica), 1.0))


def apply_route_weight(replica: int, scale: float) -> Optional[float]:
    """The policy plane's pre-verified ``route_weight`` action: scale
    ``replica``'s admission bias by ``scale`` (the live router reads the
    bias on every assignment).  Returns the new bias, or None when the
    replica is unknown to the fleet table (no-op — the policy engine
    then reports the action as not applied)."""
    global _fleet_rebalances
    with _lock:
        if _fleet_rows and int(replica) not in _fleet_rows:
            return None
        new = _fleet_route_bias.get(int(replica), 1.0) * float(scale)
        _fleet_route_bias[int(replica)] = new
        _fleet_rebalances += 1
        return new


def fleet_pvar_value(name: str) -> float:
    with _lock:
        if name == "fleet_replicas":
            return float(_fleet_replicas)
        if name == "fleet_migrations":
            return float(_fleet_migrations)
        if name == "fleet_migrated_bytes":
            return float(_fleet_migrated_bytes)
        if name == "fleet_rebalances":
            return float(_fleet_rebalances)
    raise KeyError(name)


def fleet_report() -> Dict[str, Any]:
    """Structured fleet state for comm_doctor --fleet / bench --fleet."""
    with _lock:
        rows = [dict(_fleet_rows[r]) for r in sorted(_fleet_rows)]
        for row in rows:
            row["route_bias"] = float(
                _fleet_route_bias.get(int(row["replica"]), 1.0))
        return {
            "replicas": _fleet_replicas,
            "migrations": _fleet_migrations,
            "migrated_bytes": _fleet_migrated_bytes,
            "rebalances": _fleet_rebalances,
            "replica_rows": rows,
            "migration_log": [dict(m) for m in _fleet_migration_log],
            "routes": [dict(r) for r in _fleet_routes],
        }


# -- pvar read-through + report ---------------------------------------------

def pvar_value(name: str) -> float:
    with _lock:
        if name == "serve_tokens":
            return float(_tokens)
        if name == "serve_active_seqs":
            return float(_active)
        if name == "serve_evictions":
            return float(_evictions)
        if name == "serve_kv_pages_used":
            return float(_pages_used)
    raise KeyError(name)


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(int(round(q * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[k]


def report() -> Dict[str, Any]:
    """Structured plane state for comm_doctor --serve / bench --serve."""
    with _lock:
        itl = sorted(_itl)
        total = _prefill_s + _decode_s + _host_s
        rows = []
        for row in _requests.values():
            r = {k: v for k, v in row.items()
                 if not k.startswith("_")}
            rows.append(r)
        return {
            "tokens": _tokens,
            "active_seqs": _active,
            "evictions": _evictions,
            "kv_pages_used": _pages_used,
            "prefills": _prefills,
            "decode_steps": _decode_steps,
            "batch_occupancy": _occ_sum / max(_decode_steps, 1),
            "goodput": {
                "prefill_s": round(_prefill_s, 6),
                "decode_s": round(_decode_s, 6),
                "host_s": round(_host_s, 6),
                "total_s": round(total, 6),
                "prefill_pct": 100.0 * _prefill_s / total if total else 0.0,
                "decode_pct": 100.0 * _decode_s / total if total else 0.0,
                "host_pct": 100.0 * _host_s / total if total else 0.0,
                "decode_tokens_per_s": (_tokens / _decode_s
                                        if _decode_s else 0.0),
            },
            "itl": {
                "count": len(itl),
                "p50_ms": 1e3 * _percentile(itl, 0.50),
                "p99_ms": 1e3 * _percentile(itl, 0.99),
                "mean_ms": (1e3 * sum(itl) / len(itl)) if itl else 0.0,
            },
            "speculative": {
                "windows": _spec_windows,
                "drafted": _spec_drafted,
                "accepted": _spec_accepted,
                "acceptance_rate": (_spec_accepted / _spec_drafted
                                    if _spec_drafted else 0.0),
            },
            "dispatches": dict(_dispatches),
            "requests": rows,
        }


# the engine/scheduler/cache classes import jax — load them lazily so
# spc.py's pvar read-through never drags the runtime in
def __getattr__(name: str):
    if name in ("ServingEngine",):
        from .engine import ServingEngine
        return ServingEngine
    if name in ("PagedKVCache",):
        from .cache import PagedKVCache
        return PagedKVCache
    if name in ("ContinuousBatchingScheduler", "Request",
                "poisson_stream", "FleetRouter"):
        from . import scheduler as _sched
        return getattr(_sched, name)
    if name in ("ServingFleet",):
        from .fleet import ServingFleet
        return ServingFleet
    raise AttributeError(name)
