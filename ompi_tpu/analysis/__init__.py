"""Static communication verifier (MPI-Checker/MUST discipline at the
jaxpr level) plus the repo-invariant comm-lint.

Three modules:

* ``commgraph`` — extract the collective program of any jitted
  function (or a compiled reshard plan) into a ``CommGraph``, run the
  SPMD well-formedness checks (sequence matching, ppermute bijections,
  hier axis cover, device->host transfers), and predict per-collective
  wire bytes with the same busbw-factor models ``perf/model.py`` and
  the traffic plane charge — ``verify()`` cross-checks the static
  figure against the runtime attribution byte-for-byte.
* ``lint`` — AST comm-lint over the tree: rules CL001–CL006 encode the
  plane contracts (decision-audited dispatch, exception-safe spans,
  pvar read-through, one-attribute-read disabled paths, the decision
  reason grammar, osc epoch discipline).
* ``rules`` — the DEVICE_RULES grammar authority shared by the
  dispatch-time loader and CI.

``rules`` and ``lint`` are import-light (no jax); ``commgraph`` pulls
jax and is loaded lazily so ``coll/xla -> analysis.rules`` stays a
cheap import edge.
"""

from __future__ import annotations

_COMMGRAPH_NAMES = (
    "CollRecord", "CommGraph", "Issue", "VerifyReport",
    "extract", "from_reshard_plan", "verify",
)
_LINT_NAMES = ("Finding", "lint_paths", "lint_sources", "RULES")

__all__ = list(_COMMGRAPH_NAMES) + list(_LINT_NAMES) + ["rules"]


def __getattr__(name: str):
    import importlib
    if name in ("rules", "lint", "commgraph"):
        return importlib.import_module(f".{name}", __name__)
    if name in _COMMGRAPH_NAMES:
        return getattr(importlib.import_module(".commgraph", __name__), name)
    if name in _LINT_NAMES:
        return getattr(importlib.import_module(".lint", __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
