"""comm-lint: AST rules encoding THIS repo's plane contracts.

Off-the-shelf linters know Python; they do not know that a raw
``lax.psum`` bypasses four observability planes, that a manually
recorded trace span silently vanishes when the timed call raises, or
that the decision layer's reason strings are a parseable grammar the
trace analyzer replays.  Each rule below states one such invariant,
carries a fix-hint, and can be waived per line with a *justified*
comment::

    # comm-lint: disable=CL001 <why this site is exempt>

A waiver without a justification does not waive (the why IS the
contract: six months later nobody remembers which exemptions were
load-bearing).  Multiple codes: ``disable=CL001,CL002 <why>``.  The
comment waives findings on its own line, or — as a standalone comment
— on the next code line.

Rule catalog (docs/static-analysis.md has the long rationale):

* **CL001** raw ``lax.p*`` collective / ``shard_map`` call outside the
  coll/xla dispatch-engine layer — bypasses decision audit, traffic
  attribution, perf sampling and numerics probes.
* **CL002** manual ``trace.record_span`` whose timed region can raise
  before the span is recorded (no ``status=error`` close on the
  exception path) — a raising sync loses its span and the perf model
  inherits an open-ended latency.
* **CL003** pvar registered in a plane's ``PVARS``/``_PVARS`` but not
  listed in ``spc.COUNTERS`` — ``spc.get``/``snapshot`` read through
  the plane registries by COUNTERS membership, so an unlisted pvar is
  invisible to pvar_read_all/Prometheus.
* **CL004** disabled-path guard doing more than one attribute read —
  the plane contract is ONE module-attribute read on the disabled
  path (``<plane>.enabled`` first in any ``and``-chain; never
  ``_var.get("<plane>_enabled")`` at a call site).
* **CL005** decision-reason literal outside the audited grammar
  (``force:|blanket:|rule:|floor:|off:|ineligible:|default:|learned:``)
  — the trace analyzer's drift check parses these prefixes.
* **CL006** one-sided window put/accumulate outside an RMA epoch — no
  completion or ordering guarantee without fence/lock/PSCW.
* **CL007** the policy-plane attribution contract: every
  ``trace.decision(...)`` audit-event constructor must thread a
  ``verdict=`` cause (``verdict=None`` is the explicit operator-forced
  spelling), and every sentry verdict dict must carry ``plane`` and
  ``severity`` keys — an unattributed decision or an envelope-less
  verdict is invisible to ``comm_doctor --policy``.
* **CL008** the request-plane stitching contract: every span recorded
  inside the serving request path (``ompi_tpu/serving/``) must carry a
  ``rid=`` tag in its args — an untagged span is invisible to the
  per-request span-tree stitching and the critical-path analyzer.
  Batch-scoped spans (one decode step covers every live request) waive
  with the why.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "CL001": "raw collective/shard_map call outside the dispatch engine",
    "CL002": "trace span not closed on the exception path",
    "CL003": "pvar registered but not read-through in spc.get/snapshot",
    "CL004": "disabled-path guard does more than one attribute read",
    "CL005": "decision reason outside the audited grammar",
    "CL006": "one-sided window op reachable outside an RMA epoch",
    "CL007": "decision without a verdict= cause / verdict without "
             "plane+severity",
    "CL008": "serving request-path span without a rid= tag",
}

_HINTS: Dict[str, str] = {
    "CL001": "dispatch through the engine layer (DeviceComm / coll.xla / "
             "the audited wrappers), or attribute the comm at the eager "
             "boundary (traffic.note_*) and waive with the why",
    "CL002": "wrap the timed region in try/except BaseException recording "
             "the span with args={'status': 'error'} before re-raising "
             "(or use the `with trace.span(...)` context manager, which "
             "closes tagged spans itself)",
    "CL003": "add the pvar to spc.COUNTERS — get()/snapshot() read "
             "through each plane's PVARS by COUNTERS membership, so an "
             "unlisted name never reaches pvar_read_all/Prometheus",
    "CL004": "make the plane gate the FIRST operand (`<plane>.enabled "
             "and ...`) and never re-read the var registry at call "
             "sites — the disabled path must cost one attribute read",
    "CL005": "start the reason with one of force:/blanket:/rule:/floor:/"
             "off:/ineligible:/default:/learned: — the trace analyzer's "
             "decision-drift check parses the prefix",
    "CL006": "open an epoch first (fence / lock / lock_all / start+post) "
             "— a one-sided op outside an epoch has no completion or "
             "ordering guarantee",
    "CL007": "thread the causing verdict through the audit event "
             "(verdict=<cause>, or the explicit verdict=None for an "
             "operator-forced decision), and give every sentry verdict "
             "dict the bus envelope keys 'plane' and 'severity' — "
             "comm_doctor --policy renders only attributed decisions",
    "CL008": "tag the span's args with the owning request (rid=...) so "
             "the request plane's span-tree stitching can group it; a "
             "genuinely batch-scoped span (one decode step serves every "
             "live request) waives with the why",
}

# -- CL001 vocabulary --------------------------------------------------------

_RAW_COLLS = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "all_to_all",
    "all_gather", "psum_scatter", "pshuffle",
})

# the dispatch/engine layer: modules whose JOB is to issue raw
# collectives, each with decision/traffic/perf/numerics integration
# (or, for coll_tune, whose job is to MEASURE the raw arms that feed
# DEVICE_RULES).  Matched as path suffixes.
_CL001_ENGINE_SUFFIXES = (
    "ompi_tpu/coll/xla.py",
    "ompi_tpu/coll/quant.py",
    "ompi_tpu/parallel/collectives.py",
    "ompi_tpu/parallel/hierarchy.py",
    "ompi_tpu/parallel/reshard.py",
    "ompi_tpu/parallel/overlap.py",
    "ompi_tpu/ops/collective_matmul.py",
    "ompi_tpu/serving/fused.py",
    "ompi_tpu/jaxcompat.py",
    "ompi_tpu/tools/coll_tune.py",
)

# -- CL002 vocabulary --------------------------------------------------------

# calls assumed non-raising between t0 and record_span (timers, the
# tracer itself, cheap builtins); anything else can raise and lose the
# span
_CL002_SAFE_CALLS = frozenset({
    "perf_counter", "record_span", "instant", "monotonic", "time",
    "len", "sum", "min", "max", "int", "float", "round", "repr",
    "str", "dict", "list", "tuple", "bool", "format", "get", "items",
    "keys", "values", "describe", "append", "inc",
})
# the trace engine itself defines the span machinery
_CL002_ENGINE_SUFFIXES = ("ompi_tpu/trace/__init__.py",)

# -- CL004 vocabulary --------------------------------------------------------

_PLANES = ("trace", "traffic", "perf", "numerics", "health", "policy",
           "history")
_PLANE_ENABLED_VARS = frozenset(f"{p}_enabled" for p in _PLANES)

# -- CL005 vocabulary --------------------------------------------------------

_REASON_PREFIXES = ("force:", "blanket:", "rule:", "floor:", "off:",
                    "ineligible:", "default:", "learned:")

# -- CL007 vocabulary --------------------------------------------------------

# the decision constructor's home (defines the signature, is not a call
# site) and the engine that BUILDS the verdict= payload it threads
_CL007_ENGINE_SUFFIXES = ("ompi_tpu/trace/__init__.py",)
# names whose dict construction is held to the bus-envelope contract
_CL007_VERDICT_NAMES = re.compile(r"(^|_)verdicts?$")

# -- CL008 vocabulary --------------------------------------------------------

# the serving request path: every span these modules record narrates a
# request's lifecycle, so the request plane's stitching needs the rid tag
_CL008_PATH_FRAGMENT = "ompi_tpu/serving/"

# -- CL006 vocabulary --------------------------------------------------------

_RMA_OPS = frozenset({"put", "accumulate", "get_accumulate",
                      "fetch_and_op", "compare_and_swap"})
_EPOCH_OPENERS = frozenset({"fence", "lock", "lock_all", "start", "post"})
# SHMEM's contract is an always-exposed symmetric heap with
# fence/quiet ordering — not MPI window epochs — so its put/get layer
# is exempt wholesale rather than line-waived
_CL006_EXEMPT_SUFFIXES = ("ompi_tpu/shmem/",)

_WAIVER_RE = re.compile(
    r"#\s*comm-lint:\s*disable=((?:CL\d{3})(?:\s*,\s*CL\d{3})*)\s*(.*)")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    msg: str
    hint: str = ""
    waived: bool = False
    waiver: str = ""

    def format(self) -> str:
        tag = f" [waived: {self.waiver}]" if self.waived else ""
        return (f"{self.path}:{self.line}: {self.rule} {self.msg}{tag}"
                + (f"\n    hint: {self.hint}" if self.hint and
                   not self.waived else ""))


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _attr_chain(node) -> str:
    """'a.b.c' for nested attributes, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _finding(rule: str, path: str, node, msg: str) -> Finding:
    return Finding(rule=rule, path=path, line=getattr(node, "lineno", 1),
                   msg=msg, hint=_HINTS[rule])


# ---------------------------------------------------------------------------
# per-rule passes
# ---------------------------------------------------------------------------

def _cl001(tree: ast.AST, path: str) -> List[Finding]:
    if any(_norm(path).endswith(s) for s in _CL001_ENGINE_SUFFIXES):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "shard_map":
            out.append(_finding(
                "CL001", path, node,
                "shard_map program built outside the dispatch engine — "
                "its collectives bypass the decision/traffic/perf/"
                "numerics planes"))
        elif name in _RAW_COLLS:
            chain = _attr_chain(node.func)
            # only lax.<coll> / jax.lax.<coll> spellings: a different
            # receiver (self.psum, comm.all_gather) IS the engine path
            if chain in (f"lax.{name}", f"jax.lax.{name}", name):
                out.append(_finding(
                    "CL001", path, node,
                    f"raw lax.{name} outside the dispatch engine — "
                    "bypasses decision audit and traffic attribution"))
    return out


def _cl002(tree: ast.AST, path: str) -> List[Finding]:
    if any(_norm(path).endswith(s) for s in _CL002_ENGINE_SUFFIXES):
        return []
    out = []
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in funcs:
        spans = [n for n in ast.walk(fn)
                 if isinstance(n, ast.Call)
                 and _call_name(n) == "record_span"]
        if not spans:
            continue
        # protection map: line ranges of try-bodies whose handlers
        # either record an error span or do not re-raise (flow still
        # reaches the span call)
        protected: List[Tuple[int, int]] = []
        finally_lines: List[Tuple[int, int]] = []
        handler_lines: List[Tuple[int, int]] = []
        for t in ast.walk(fn):
            if not isinstance(t, ast.Try):
                continue
            for h in t.handlers:
                handler_lines.append((h.lineno, h.end_lineno or h.lineno))
                records = any(isinstance(c, ast.Call)
                              and _call_name(c) == "record_span"
                              for b in h.body for c in ast.walk(b))
                reraises = any(isinstance(c, ast.Raise)
                               for b in h.body for c in ast.walk(b))
                if records or not reraises:
                    body_end = max((b.end_lineno or b.lineno)
                                   for b in t.body)
                    protected.append((t.body[0].lineno, body_end))
            if t.finalbody:
                finally_lines.append(
                    (t.finalbody[0].lineno,
                     t.finalbody[-1].end_lineno
                     or t.finalbody[-1].lineno))

        def _in(ranges, line):
            return any(a <= line <= b for a, b in ranges)

        for call in spans:
            if _in(finally_lines, call.lineno) or _in(handler_lines,
                                                      call.lineno):
                continue          # already on an exception-safe path
            if len(call.args) < 3 or not isinstance(call.args[2],
                                                    ast.Name):
                continue          # t_begin not a plain name: synthetic
            t0 = call.args[2].id
            t0_line = None
            for n in ast.walk(fn):
                if (isinstance(n, ast.Assign) and n.lineno < call.lineno
                        and any(isinstance(x, ast.Name) and x.id == t0
                                for x in n.targets)):
                    t0_line = max(t0_line or 0, n.lineno)
            if t0_line is None:
                continue
            risky = []
            for n in ast.walk(fn):
                if (isinstance(n, ast.Call)
                        and t0_line < n.lineno < call.lineno
                        and _call_name(n) not in _CL002_SAFE_CALLS
                        and not _in(protected, n.lineno)
                        and not _in(handler_lines, n.lineno)):
                    risky.append(n)
            if risky:
                out.append(_finding(
                    "CL002", path, call,
                    f"span recorded at line {call.lineno} is lost if "
                    f"the call at line {risky[0].lineno} "
                    f"({_call_name(risky[0])}) raises — no "
                    "status=error close on the exception path"))
    return out


def _collect_pvars(tree: ast.AST) -> List[Tuple[int, str]]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id in ("PVARS", "_PVARS")
                   for t in node.targets):
            continue
        v = node.value
        if isinstance(v, (ast.Tuple, ast.List)):
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append((e.lineno, e.value))
        elif isinstance(v, ast.Dict):
            for k in v.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.append((k.lineno, k.value))
    return out


def _collect_counters(tree: ast.AST) -> Optional[Set[str]]:
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "COUNTERS"
                        for t in node.targets)
                and isinstance(node.value, (ast.List, ast.Tuple))):
            names = set()
            for elt in node.value.elts:
                if (isinstance(elt, (ast.Tuple, ast.List)) and elt.elts
                        and isinstance(elt.elts[0], ast.Constant)):
                    names.add(elt.elts[0].value)
                elif isinstance(elt, ast.Constant):
                    names.add(elt.value)
            return names
    return None


def _cl003(trees: Dict[str, ast.AST]) -> List[Finding]:
    counters: Optional[Set[str]] = None
    for path, tree in trees.items():
        if _norm(path).endswith("spc.py") or "COUNTERS" in \
                {t.id for n in ast.walk(tree) if isinstance(n, ast.Assign)
                 for t in n.targets if isinstance(t, ast.Name)}:
            c = _collect_counters(tree)
            if c:
                counters = c if counters is None else counters | c
    if counters is None:
        return []                 # no registry in this file set
    out = []
    for path, tree in trees.items():
        if _collect_counters(tree):
            continue              # the registry module itself
        for line, name in _collect_pvars(tree):
            if name not in counters:
                out.append(Finding(
                    rule="CL003", path=path, line=line,
                    msg=f"pvar {name!r} registered here is not in "
                        "spc.COUNTERS — invisible to get()/snapshot()/"
                        "pvar_read_all/Prometheus",
                    hint=_HINTS["CL003"]))
    return out


def _cl004(tree: ast.AST, path: str) -> List[Finding]:
    npath = _norm(path)
    own_plane = next((p for p in _PLANES
                      if f"ompi_tpu/{p}/" in npath
                      or npath.endswith(f"ompi_tpu/{p}.py")), None)
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.And):
            for i, operand in enumerate(node.values):
                if i == 0:
                    continue
                for sub in ast.walk(operand):
                    if (isinstance(sub, ast.Attribute)
                            and sub.attr == "enabled"
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id in _PLANES):
                        out.append(_finding(
                            "CL004", path, node,
                            f"{sub.value.id}.enabled is operand "
                            f"#{i + 1} of an and-chain — the disabled "
                            "path pays every earlier operand before "
                            "the gate short-circuits"))
        if isinstance(node, ast.Call) and _call_name(node) == "get":
            chain = _attr_chain(node.func)
            if chain.split(".")[0] not in ("_var", "var", "registry"):
                continue
            if (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value in _PLANE_ENABLED_VARS):
                plane = node.args[0].value[:-len("_enabled")]
                if plane != own_plane:
                    out.append(_finding(
                        "CL004", path, node,
                        f"_var.get({node.args[0].value!r}) at a call "
                        "site — the registry lookup costs far more "
                        f"than the one-attribute read {plane}.enabled "
                        "the plane exports"))
    return out


def _literal_prefix(node) -> Optional[str]:
    """Leading literal text of a Constant-str or JoinedStr, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


def _cl005(tree: ast.AST, path: str) -> List[Finding]:
    out = []

    def _check(node, text: Optional[str]) -> None:
        if text is None:
            return
        if not text.startswith(_REASON_PREFIXES):
            out.append(_finding(
                "CL005", path, node,
                f"decision reason {text[:40]!r}... does not start with "
                f"a grammar prefix ({'|'.join(_REASON_PREFIXES)})"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "reason":
                    _check(kw.value, _literal_prefix(kw.value))
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == "reason"
                   for t in node.targets):
                _check(node, _literal_prefix(node.value))
    return out


def _cl007(tree: ast.AST, path: str) -> List[Finding]:
    if any(_norm(path).endswith(s) for s in _CL007_ENGINE_SUFFIXES):
        return []
    out = []

    def _dict_keys(node) -> Optional[Set[str]]:
        """Constant keys of a dict literal or dict(...) call, else None."""
        if isinstance(node, ast.Dict):
            return {k.value for k in node.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
        if isinstance(node, ast.Call) and _call_name(node) == "dict":
            return {kw.arg for kw in node.keywords if kw.arg}
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _call_name(node) == "decision":
            chain = _attr_chain(node.func)
            # only the audit constructor's spellings (trace.decision /
            # _trace.decision); a different receiver is not the event
            if chain.split(".")[0] not in ("trace", "_trace") \
                    and chain != "decision":
                continue
            if not any(kw.arg == "verdict" for kw in node.keywords):
                out.append(_finding(
                    "CL007", path, node,
                    "decision audit event without a verdict= cause — "
                    "pass the causing verdict, or the explicit "
                    "verdict=None for an operator-forced decision"))
        elif isinstance(node, ast.Assign):
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if not any(_CL007_VERDICT_NAMES.search(n) for n in names):
                continue
            keys = _dict_keys(node.value)
            if keys is None or "kind" not in keys:
                continue              # not a sentry verdict construction
            missing = [k for k in ("plane", "severity") if k not in keys]
            if missing:
                out.append(_finding(
                    "CL007", path, node,
                    f"sentry verdict dict missing the bus envelope "
                    f"key(s) {missing} — every verdict must carry "
                    "plane + severity for the policy bus"))
    return out


def _cl008(tree: ast.AST, path: str) -> List[Finding]:
    if _CL008_PATH_FRAGMENT not in _norm(path):
        return []
    out = []

    def _dict_keys(node) -> Optional[Set[str]]:
        """Constant keys of a dict literal or dict(...) call, else None."""
        if isinstance(node, ast.Dict):
            return {k.value for k in node.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
        if isinstance(node, ast.Call) and _call_name(node) == "dict":
            return {kw.arg for kw in node.keywords if kw.arg}
        return None

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _call_name(node) == "record_span"):
            continue
        chain = _attr_chain(node.func)
        # only the trace recorder's spellings (trace.record_span /
        # _trace.record_span); a different receiver is not the event
        if chain.split(".")[0] not in ("trace", "_trace") \
                and chain != "record_span":
            continue
        args_kw = next((kw.value for kw in node.keywords
                        if kw.arg == "args"), None)
        if args_kw is None and len(node.args) >= 6:
            args_kw = node.args[5]
        if args_kw is None:
            out.append(_finding(
                "CL008", path, node,
                "request-path span recorded with no args= at all — "
                "it cannot carry the rid= tag the request plane "
                "stitches span trees on"))
            continue
        keys = _dict_keys(args_kw)
        if keys is not None and "rid" not in keys:
            out.append(_finding(
                "CL008", path, node,
                "request-path span args without a rid= tag — the "
                "per-request span tree and critical-path analyzer "
                "cannot attribute it"))
    return out


def _cl006(tree: ast.AST, path: str) -> List[Finding]:
    npath = _norm(path)
    if any(s in npath for s in _CL006_EXEMPT_SUFFIXES):
        return []
    out = []
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        # window-like receivers: named *win* or assigned from a
        # window-constructing call
        windowish: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign):
                src = n.value
                ctor = _call_name(src) if isinstance(src, ast.Call) else ""
                if "window" in ctor.lower() or ctor == "win_create":
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            windowish.add(t.id)

        def _is_window(recv) -> bool:
            name = (recv.id if isinstance(recv, ast.Name)
                    else recv.attr if isinstance(recv, ast.Attribute)
                    else "")
            return "win" in name.lower() or name in windowish

        opened_before: Dict[str, int] = {}
        calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)]
        calls.sort(key=lambda c: c.lineno)
        for c in calls:
            recv = c.func.value
            if not _is_window(recv):
                continue
            rname = (recv.id if isinstance(recv, ast.Name) else recv.attr)
            if c.func.attr in _EPOCH_OPENERS:
                opened_before.setdefault(rname, c.lineno)
            elif c.func.attr in _RMA_OPS:
                if rname not in opened_before \
                        or opened_before[rname] > c.lineno:
                    out.append(_finding(
                        "CL006", path, c,
                        f"{rname}.{c.func.attr}() with no epoch opened "
                        "on this window earlier in the function "
                        "(fence/lock/lock_all/start/post)"))
    return out


# ---------------------------------------------------------------------------
# waivers + driver
# ---------------------------------------------------------------------------

def _waivers(src: str) -> Dict[int, Tuple[Set[str], str]]:
    """line -> (codes, justification); a standalone waiver comment also
    covers the next line."""
    out: Dict[int, Tuple[Set[str], str]] = {}
    lines = src.splitlines()
    for i, line in enumerate(lines, 1):
        m = _WAIVER_RE.search(line)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",")}
        why = m.group(2).strip()
        out[i] = (codes, why)
        if line.lstrip().startswith("#"):
            out[i + 1] = (codes, why)
    return out


def _apply_waivers(findings: List[Finding], src_by_path: Dict[str, str]
                   ) -> List[Finding]:
    waivers = {p: _waivers(s) for p, s in src_by_path.items()}
    out = []
    for f in findings:
        w = waivers.get(f.path, {}).get(f.line)
        if w and f.rule in w[0]:
            codes, why = w
            if why:
                f.waived, f.waiver = True, why
            else:
                f.msg += " (waiver present but has NO justification — "\
                         "the why is required)"
        out.append(f)
    return out


def lint_sources(src_by_path: Dict[str, str]) -> List[Finding]:
    """Lint a {path: source} mapping (the testable core)."""
    trees: Dict[str, ast.AST] = {}
    findings: List[Finding] = []
    for path, src in src_by_path.items():
        try:
            trees[path] = ast.parse(src)
        except SyntaxError as exc:
            findings.append(Finding(
                rule="CL000", path=path, line=exc.lineno or 1,
                msg=f"syntax error: {exc.msg}"))
    for path, tree in trees.items():
        findings += _cl001(tree, path)
        findings += _cl002(tree, path)
        findings += _cl004(tree, path)
        findings += _cl005(tree, path)
        findings += _cl006(tree, path)
        findings += _cl007(tree, path)
        findings += _cl008(tree, path)
    findings += _cl003(trees)
    findings = _apply_waivers(findings, src_by_path)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    """Lint files/directories (recursing into ``*.py``)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files += [os.path.join(root, n) for n in sorted(names)
                          if n.endswith(".py")]
        else:
            files.append(p)
    srcs = {}
    for f in sorted(set(files)):
        with open(f) as fh:
            srcs[f] = fh.read()
    return lint_sources(srcs)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="repo-invariant comm-lint (rules CL001-CL008; "
                    "waive per line with '# comm-lint: disable=CLnnn "
                    "<why>')")
    ap.add_argument("paths", nargs="*", default=["ompi_tpu"])
    ap.add_argument("--show-waived", action="store_true",
                    help="also print waived findings")
    ns = ap.parse_args(argv)
    findings = lint_paths(ns.paths or ["ompi_tpu"])
    live = [f for f in findings if not f.waived]
    shown = findings if ns.show_waived else live
    for f in shown:
        print(f.format())
    n_waived = sum(1 for f in findings if f.waived)
    print(f"comm-lint: {len(live)} finding(s), {n_waived} waived")
    return 1 if live else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
