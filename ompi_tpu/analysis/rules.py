"""Standalone DEVICE_RULES validator — the single parser behind the
dispatch-time loader AND CI.

The rules-file grammar ('<coll>[@<plane>] <min_ndev> <min_bytes> <mode>')
grew organically across the device tier (PR 3), the plane-keyed rows
(PR 8) and the learned-ledger provenance headers (PR 6's coll_tune
--from-ledger).  Until this module the only parser lived inside
``coll/xla._load_device_rules`` where a malformed file is caught at
dispatch time — and an exactly-duplicated row was *not* caught at all
(list order made the later row win decide_mode's walk silently).  This
module is the one grammar authority:

* ``parse_text`` / ``parse_file`` — strict parse shared by the loader:
  every historic ValueError (bad row shape, unknown mode, unknown
  plane) keeps its message, and an exact duplicate key
  ``(coll[@plane], min_ndev, min_bytes)`` is now a loud ValueError
  naming BOTH lines.
* ``validate_file`` — the CI arm (make comm-lint): parse errors plus
  non-fatal lint warnings (hier rows that are not plane-keyed,
  malformed provenance headers).

No jax import here: the validator must stay loadable by the lint CLI
and by coll/xla's import path without cycles.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# every mode any decision point can name — MUST stay in lockstep with
# coll.xla._MODES (xla imports this module and asserts equality at
# import so the two vocabularies cannot drift apart silently)
MODES = ("native", "staged", "quant", "bidir", "hier", "hier+quant")
# plane vocabulary for '<coll>@<plane>' rows (parallel/hierarchy's
# classify_axes split, incl. the topo_sim_dcn_axes override)
PLANES = ("ici", "dcn")

# provenance headers emitted by machine rule-writers (coll_tune
# --device / --from-ledger, bench.py --selfdrive's policy plane): a
# '# learned from ...' comment is a machine-written claim about where
# the rows came from, so its shape is part of the file contract
_PROVENANCE_PREFIX = "# learned from "
_PROVENANCE_SOURCES = ("PERF_LEDGER", "policy")

Row = Tuple[str, int, int, str]


@dataclass
class RulesReport:
    """validate_file's result: rows when the file parses, else the
    parse error; warnings never fail the loader, only inform CI."""
    path: str
    rows: List[Row] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors


def parse_text(text: str, path: str = "<rules>") -> List[Row]:
    """Parse rules text into (coll, min_ndev, min_bytes, mode) rows.

    Raises ValueError on the first malformed row — including an exact
    duplicate ``(coll[@plane], min_ndev, min_bytes)`` key, which names
    both offending lines (before this validator the later row silently
    won the decide_mode walk)."""
    rules: List[Row] = []
    seen = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            coll, min_ndev, min_bytes, mode = line.split()
            min_ndev, min_bytes = int(min_ndev), int(min_bytes)
        except ValueError as exc:
            raise ValueError(
                f"{path}:{lineno}: bad device rule {line!r} "
                "(want '<coll>[@<plane>] <min_ndev> <min_bytes> "
                f"<native|staged>'): {exc}") from None
        if "@" in coll:
            base, plane = coll.split("@", 1)
            if not base or plane not in PLANES:
                raise ValueError(
                    f"{path}:{lineno}: unknown plane in "
                    f"{coll!r} (want '<coll>@<plane>' with "
                    f"plane one of {', '.join(PLANES)})")
        if mode not in MODES:
            raise ValueError(
                f"{path}:{lineno}: unknown device mode {mode!r} "
                f"(want one of {', '.join(MODES)})")
        key = (coll, min_ndev, min_bytes)
        if key in seen:
            first_line, first_mode = seen[key]
            raise ValueError(
                f"{path}:{lineno}: duplicate device rule for "
                f"{coll!r} (min_ndev={min_ndev}, min_bytes={min_bytes}): "
                f"line {first_line} already set mode {first_mode!r}, "
                f"line {lineno} sets {mode!r} — delete one (the loader "
                "no longer lets the later row win silently)")
        seen[key] = (lineno, mode)
        rules.append((coll, min_ndev, min_bytes, mode))
    return rules


def parse_file(path: str) -> List[Row]:
    """Strict parse of a rules file (the loader's entry point).

    A *named but missing* file is a loud error — misconfiguration must
    be distinguishable from no configuration (the reference's
    dynamic-file loader reports a missing file,
    coll_tuned_dynamic_file.c:58)."""
    if not os.path.exists(path):
        raise ValueError(
            f"coll_xla_dynamic_rules names a missing file: {path!r}")
    with open(path) as fh:
        return parse_text(fh.read(), path)


def validate_file(path: str) -> RulesReport:
    """CI validation: strict parse + non-fatal grammar lint.

    Warnings (do not fail the dispatch-time loader):
      * a ``hier``/``hier+quant`` mode on a row that is NOT plane-keyed
        — the arm needs a two-tier axis split (``hier_axes``), so a
        base row also matches single-plane comms where the arm is
        always vetoed ``ineligible:hier:...``; plane-keying the row
        (``<coll>@dcn``) states the eligibility precondition in the
        grammar itself.
      * a ``# learned from ...`` provenance header naming an unknown
        source (coll_tune writes ``# learned from PERF_LEDGER <path>``;
        anything else is a hand-edit masquerading as machine output).
    """
    rep = RulesReport(path=path)
    try:
        rep.rows = parse_file(path)
    except ValueError as exc:
        rep.errors.append(str(exc))
        return rep
    for coll, min_ndev, min_bytes, mode in rep.rows:
        if mode in ("hier", "hier+quant") and "@" not in coll:
            rep.warnings.append(
                f"{path}: rule '{coll} {min_ndev} {min_bytes} {mode}' "
                f"picks the {mode!r} arm without a plane key — the arm "
                "is only eligible on two-tier comms (hier_axes), so a "
                f"base row also matches comms where it is always "
                f"vetoed; prefer '{coll}@dcn'")
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            s = line.strip()
            if not s.startswith(_PROVENANCE_PREFIX):
                continue
            rest = s[len(_PROVENANCE_PREFIX):]
            if not any(rest.startswith(src) for src in _PROVENANCE_SOURCES):
                rep.warnings.append(
                    f"{path}:{lineno}: provenance header names unknown "
                    f"source {rest.split()[0] if rest.split() else ''!r} "
                    f"(known: {', '.join(_PROVENANCE_SOURCES)})")
    return rep


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m ompi_tpu.analysis.rules [path ...]`` — validate
    rules files for CI; nonzero exit on any parse error."""
    import argparse

    ap = argparse.ArgumentParser(
        description="DEVICE_RULES validator (grammar, mode/plane "
                    "vocabulary, duplicate rows, provenance headers)")
    ap.add_argument("paths", nargs="*", default=["DEVICE_RULES.txt"],
                    help="rules files to validate")
    ns = ap.parse_args(argv)
    rc = 0
    for path in (ns.paths or ["DEVICE_RULES.txt"]):
        rep = validate_file(path)
        for w in rep.warnings:
            print(f"warning: {w}")
        for e in rep.errors:
            print(f"error: {e}")
            rc = 1
        if rep.ok:
            print(f"{path}: {len(rep.rows)} rule row(s) ok"
                  + (f", {len(rep.warnings)} warning(s)"
                     if rep.warnings else ""))
    return rc


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
