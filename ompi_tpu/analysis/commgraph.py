"""Static collective-program extraction + SPMD verification.

MPI's static collective-matching verifiers (MPI-Checker, MUST) prove a
communication program well-formed before it runs: every rank issues
the same (op, communicator, dtype, count) sequence, point-to-point
patterns pair up, nothing escapes the accounted path.  The jaxpr is
this repo's communication program: one traced SPMD program whose
collective eqns (psum / ppermute / all_to_all / all_gather /
reduce_scatter) carry axis names, dtypes and per-shard shapes — so the
same discipline applies *before dispatch*, which is exactly the proof
obligation the observe->decide->act loop (ROADMAP item 5) needs under
it: a policy layer may only rewrite arms live over a program that is
statically known to be well-formed.

Three consumers:

* ``extract(fn, *args)`` — walk the closed jaxpr of any jittable
  callable into a ``CommGraph`` of ``CollRecord``s (recursing through
  pjit / shard_map / scan / while / cond / remat / custom-vjp bodies,
  multiplying scan trip counts through).
* ``from_reshard_plan(plan)`` — the reshard plan compiler's step list
  is already a static collective program; lift it into the same
  representation so bijection/axis checks and wire prediction apply.
* ``verify(fn, args, mesh)`` — checks + static wire prediction + a
  live run under the traffic plane, comparing the static figure with
  the runtime per-coll attribution **byte-for-byte** (same integer
  expressions as the runtime note models, same 2(r-1)/r-style factors
  as ``perf/model.py`` — ``tests/test_analysis.py`` pins the factor
  agreement against ``perf.model._FACTOR``).

What the extractor can and cannot see: explicit collectives (shard_map
programs, pmean/psum under vmap-style axes) appear as eqns; the psums
GSPMD *inserts* during SPMD partitioning of an auto-sharded jit do
not exist at trace time and are invisible here — consistently with
the runtime side, which never attributes them either (the traffic
plane charges through wrapper-level note models and the audited
dispatch layer, both of which run outside XLA's partitioner).  Both
ledgers therefore cover the same program: the explicitly-dispatched
collectives.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# collective primitives -> canonical op name (jax names reduce_scatter's
# primitive "reduce_scatter"; lax.psum_scatter builds it)
_COLL_PRIMS = {
    "psum": "psum",
    "pmin": "pmin",
    "pmax": "pmax",
    "ppermute": "ppermute",
    "all_to_all": "all_to_all",
    "all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "psum_scatter": "reduce_scatter",
}

# primitives that move device data through the host inside a traced
# program — a device->host round-trip hiding in a device path
_HOST_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_local_array_to_global_array",
})

# eqn params that hold subjaxprs we recurse into (plus 'branches' for
# cond/switch, handled specially for divergence detection)
_SUBJAXPR_PARAMS = ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr",
                    "fun_jaxpr", "fwd_jaxpr_thunk")


@dataclass(frozen=True)
class CollRecord:
    """One collective eqn operand in program order."""
    op: str                          # canonical op name
    axes: Tuple[str, ...]            # mesh axis names the eqn reduces over
    dtype: str
    shape: Tuple[int, ...]           # per-shard payload shape (inside
    #                                  shard_map avals are per-device)
    nbytes: int                      # payload bytes per executed call
    trips: int = 1                   # enclosing-scan length product
    perm: Tuple[Tuple[int, int], ...] = ()   # ppermute (src, dst) pairs
    path: str = ""                   # eqn nesting, e.g. pjit/shard_map/scan
    bounded: bool = True             # False under a data-dependent while

    @property
    def total_bytes(self) -> int:
        return self.nbytes * self.trips

    @property
    def control(self) -> bool:
        """Scalar payloads are control-plane figures (loss means, flags):
        the runtime note models exclude them from wire attribution, so
        the static wire models do too (they ride the same wire in O(1)
        bytes)."""
        return self.shape == ()

    def signature(self) -> Tuple[str, Tuple[str, ...], str, int]:
        """The MPI-Checker matching tuple: (op, axes, dtype, count)."""
        count = int(np.prod(self.shape)) if self.shape else 1
        return (self.op, self.axes, self.dtype, count * self.trips)


@dataclass(frozen=True)
class Issue:
    kind: str        # bijection|mismatch|hier-cover|host-transfer|
    #                  unknown-axis|unbounded
    msg: str
    severity: str = "error"          # error | warn


@dataclass
class CommGraph:
    """The extracted collective program."""
    records: List[CollRecord] = field(default_factory=list)
    host_transfers: List[str] = field(default_factory=list)
    divergent_conds: List[str] = field(default_factory=list)
    source: str = ""

    # -- extraction helpers -------------------------------------------

    def signatures(self) -> List[Tuple]:
        return [r.signature() for r in self.records]

    def by_op(self) -> Dict[str, List[CollRecord]]:
        out: Dict[str, List[CollRecord]] = {}
        for r in self.records:
            out.setdefault(r.op, []).append(r)
        return out

    # -- SPMD well-formedness checks ----------------------------------

    def check(self, mesh=None) -> List[Issue]:
        """All static checks; ``mesh`` (a jax Mesh or {axis: size}
        mapping) enables the axis-existence / permutation-range /
        hier-cover checks."""
        sizes = _axis_sizes(mesh)
        issues: List[Issue] = []
        issues += self._check_bijections(sizes)
        issues += self._check_axes(sizes)
        issues += self._check_hier_cover(sizes)
        for p in self.divergent_conds:
            issues.append(Issue(
                "mismatch",
                f"collective sequence differs across cond branches at "
                f"{p}: ranks taking different branches would issue "
                "different (op, axes, dtype, count) sequences "
                "(MPI-Checker's matching violation)"))
        for p in self.host_transfers:
            issues.append(Issue(
                "host-transfer",
                f"device->host transfer inside a device path at {p}: "
                "a callback serializes the program against the host "
                "and escapes every plane's accounting"))
        for r in self.records:
            if not r.bounded:
                issues.append(Issue(
                    "unbounded",
                    f"{r.op} over {r.axes} at {r.path} executes under "
                    "a data-dependent while: trip count (and wire "
                    "bytes) are not statically bounded", "warn"))
        return issues

    def _check_bijections(self, sizes) -> List[Issue]:
        issues = []
        for r in self.records:
            if r.op != "ppermute" or not r.perm:
                continue
            srcs = [s for s, _ in r.perm]
            dsts = [d for _, d in r.perm]
            if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
                issues.append(Issue(
                    "bijection",
                    f"ppermute over {r.axes} at {r.path} is not a "
                    f"bijection: perm {r.perm} repeats a "
                    f"{'source' if len(set(srcs)) != len(srcs) else 'destination'}"
                    " (two ranks would send to / receive from the same "
                    "peer in one step)"))
                continue
            if sizes and all(a in sizes for a in r.axes):
                dom = int(np.prod([sizes[a] for a in r.axes]))
                bad = [p for p in r.perm
                       if not (0 <= p[0] < dom and 0 <= p[1] < dom)]
                if bad:
                    issues.append(Issue(
                        "bijection",
                        f"ppermute over {r.axes} at {r.path}: pairs "
                        f"{bad} fall outside the axis domain [0, {dom})"))
        return issues

    def _check_axes(self, sizes) -> List[Issue]:
        if not sizes:
            return []
        issues = []
        for r in self.records:
            missing = [a for a in r.axes if a not in sizes]
            if missing:
                issues.append(Issue(
                    "unknown-axis",
                    f"{r.op} at {r.path} names axis "
                    f"{missing[0]!r} not on the mesh "
                    f"({tuple(sizes)})"))
        return issues

    def _check_hier_cover(self, sizes) -> List[Issue]:
        """The hier arm's shape is reduce_scatter(inner) ->
        reduce(outer) -> all_gather(inner); the two stages must cover
        the comm's axis product — an outer stage reusing an inner axis
        reduces twice over one plane and never over the other."""
        issues = []
        recs = [r for r in self.records if not r.control]
        for i, r in enumerate(recs):
            if r.op != "reduce_scatter":
                continue
            outer = next((x for x in recs[i + 1:]
                          if x.op in ("psum", "pmin", "pmax")), None)
            gather = next((x for x in recs[i + 1:]
                           if x.op == "all_gather"), None)
            if outer is None or gather is None:
                continue
            if gather.axes != r.axes:
                continue          # not the hier shape
            if set(outer.axes) & set(r.axes):
                issues.append(Issue(
                    "hier-cover",
                    f"hier split at {r.path}: outer stage reduces over "
                    f"{outer.axes} which reuses inner axis(es) "
                    f"{tuple(set(outer.axes) & set(r.axes))} — the "
                    "split does not cover the axis product (one plane "
                    "reduced twice, the other never)"))
            elif sizes:
                uncovered = [a for a in sizes
                             if a not in r.axes and a not in outer.axes
                             and sizes[a] > 1]
                # axes genuinely outside the comm (e.g. tp during a dp
                # sync) are legitimate; only warn so two-tier meshes
                # with a typo'd outer axis surface
                if uncovered:
                    issues.append(Issue(
                        "hier-cover",
                        f"hier split at {r.path} covers "
                        f"{r.axes + outer.axes}; mesh axes "
                        f"{tuple(uncovered)} are outside the split "
                        "(fine for a partial-mesh comm, wrong for a "
                        "full allreduce)", "warn"))
        return issues

    def match(self, other: "CommGraph") -> List[Issue]:
        """Cross-program matching (MPMD-style: one extracted program
        per rank group).  SPMD single-program repos hit this through
        tests and through cond-divergence above."""
        a, b = self.signatures(), other.signatures()
        issues = []
        for i, (sa, sb) in enumerate(zip(a, b)):
            if sa != sb:
                issues.append(Issue(
                    "mismatch",
                    f"collective #{i} differs: {sa} vs {sb}"))
                break
        if not issues and len(a) != len(b):
            issues.append(Issue(
                "mismatch",
                f"collective count differs: {len(a)} vs {len(b)} "
                f"(first extra: "
                f"{(a + b)[min(len(a), len(b))]})"))
        return issues

    # -- static wire prediction ---------------------------------------

    def psum_ring_bytes(self, mesh, axes: Optional[Tuple[str, ...]] = None
                        ) -> int:
        """Ring-allreduce wire model over the non-control psum records:
        2(n-1)/n x payload bytes per rank — the same expression
        ``perf/model._FACTOR['allreduce']`` prices and
        ``overlap._note_traffic`` charges (one floor-division over the
        summed payload, so the figures agree byte-for-byte)."""
        sizes = _axis_sizes(mesh)
        groups: Dict[Tuple[str, ...], int] = {}
        for r in self.records:
            if r.op == "psum" and not r.control:
                if axes is None or r.axes == tuple(axes):
                    groups[r.axes] = groups.get(r.axes, 0) + r.total_bytes
        total = 0
        for ax, payload in groups.items():
            n = int(np.prod([sizes.get(a, 1) for a in ax])) if sizes else 1
            if n > 1:
                total += 2 * (n - 1) * payload // n
        return total

    def ppermute_bytes(self) -> int:
        """ppermute moves the full payload once per trip (factor 1 —
        the traffic plane's note_ring/note_ppermute convention)."""
        return sum(r.total_bytes for r in self.records
                   if r.op == "ppermute" and not r.control)

    def all_to_all_bytes(self) -> int:
        """all_to_all wire = the per-rank shard payload (factor 1 —
        the audited dispatch convention: the (n-1)/n on-wire discount
        lives in the busbw factor table, not the byte ledger)."""
        return sum(r.total_bytes for r in self.records
                   if r.op == "all_to_all" and not r.control)

    def gather_scatter_bytes(self, mesh) -> int:
        """all_gather / reduce_scatter: (n-1)/n x the gathered (full)
        buffer == (n-1) x the per-shard payload for all_gather, and
        (n-1)/n x the per-rank buffer for reduce_scatter — the
        ``perf/model._FACTOR`` (r-1)/r family."""
        sizes = _axis_sizes(mesh)
        total = 0
        for r in self.records:
            if r.control:
                continue
            n = int(np.prod([sizes.get(a, 1) for a in r.axes])) \
                if sizes else 1
            if n <= 1:
                continue
            if r.op == "all_gather":
                total += (n - 1) * r.total_bytes
            elif r.op == "reduce_scatter":
                total += (n - 1) * r.total_bytes // n
        return total

    def reshard_bytes(self) -> int:
        """Plan-lifted graphs: the step wire figures the plan compiler
        modeled (and the reshard executor charges verbatim)."""
        return sum(r.total_bytes for r in self.records
                   if r.path.startswith("reshard-plan"))


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

def _axis_sizes(mesh) -> Dict[str, int]:
    if mesh is None:
        return {}
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    return {str(a): int(mesh.shape[a]) for a in mesh.axis_names}


def _axes_of(params: Dict[str, Any]) -> Tuple[str, ...]:
    ax = params.get("axes", params.get("axis_name", ()))
    if isinstance(ax, (tuple, list)):
        return tuple(str(a) for a in ax)
    return (str(ax),)


def _subjaxprs(v) -> List[Any]:
    """Jaxpr-like values inside one eqn param value."""
    if hasattr(v, "eqns"):
        return [v]
    if hasattr(v, "jaxpr"):
        return [v.jaxpr]
    if isinstance(v, (tuple, list)):
        out = []
        for x in v:
            if hasattr(x, "eqns"):
                out.append(x)
            elif hasattr(x, "jaxpr"):
                out.append(x.jaxpr)
        return out
    return []


def _walk(jaxpr, g: CommGraph, trips: int, path: str, bounded: bool
          ) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _COLL_PRIMS:
            op = _COLL_PRIMS[name]
            axes = _axes_of(eqn.params)
            perm = tuple(tuple(int(x) for x in p)
                         for p in eqn.params.get("perm", ()))
            for iv in eqn.invars:
                aval = getattr(iv, "aval", None)
                if aval is None or not hasattr(aval, "shape"):
                    continue
                shape = tuple(int(s) for s in aval.shape)
                dt = np.dtype(aval.dtype)
                g.records.append(CollRecord(
                    op=op, axes=axes, dtype=dt.name, shape=shape,
                    nbytes=int(np.prod(shape)) * dt.itemsize if shape
                    else dt.itemsize,
                    trips=trips, perm=perm, path=path or "<top>",
                    bounded=bounded))
            continue
        if name in _HOST_PRIMS:
            g.host_transfers.append(f"{path or '<top>'}/{name}")
            # fall through: callbacks can still carry subjaxprs
        sub_path = f"{path}/{name}" if path else name
        if name in ("cond", "switch"):
            branches = eqn.params.get("branches", ())
            sub_sigs = []
            for br in branches:
                bg = CommGraph()
                for bj in _subjaxprs(br):
                    _walk(bj, bg, trips, sub_path, bounded)
                sub_sigs.append((bg, bg.signatures()))
            if sub_sigs:
                first_g, first_sig = sub_sigs[0]
                if any(sig != first_sig for _, sig in sub_sigs[1:]):
                    g.divergent_conds.append(sub_path)
                # merge the first branch so prediction sees one arm;
                # divergence itself is already a matching error
                g.records.extend(first_g.records)
                g.host_transfers.extend(
                    h for bg, _ in sub_sigs for h in bg.host_transfers)
            continue
        sub_trips = trips
        sub_bounded = bounded
        if name == "scan":
            sub_trips = trips * int(eqn.params.get("length", 1))
        elif name == "while":
            sub_bounded = False
        for key, v in eqn.params.items():
            if key == "branches":
                continue
            for sj in _subjaxprs(v):
                _walk(sj, g, sub_trips, sub_path, sub_bounded)


def extract(fn: Callable, *args, source: str = "", **kwargs) -> CommGraph:
    """Trace ``fn(*args, **kwargs)`` (jitted or plain) and extract its
    collective program."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    g = CommGraph(source=source or getattr(fn, "__name__", "<fn>"))
    _walk(closed.jaxpr, g, 1, "", True)
    return g


def from_reshard_plan(plan) -> CommGraph:
    """Lift a compiled ``ReshardPlan`` into a CommGraph: the plan's
    step list is a static collective program whose wire figures the
    executor charges verbatim, so the plan-side record carries
    ``step.wire_bytes`` and the usual checks (bijection, axis
    existence) apply to its ppermute steps."""
    g = CommGraph(source=f"reshard-plan:{plan.label}")
    step_ops = {"all_to_all": "all_to_all", "all_gather": "all_gather",
                "ppermute": "ppermute", "device_put": "device_put",
                "slice": "slice"}
    for i, step in enumerate(plan.steps):
        op = step_ops.get(step.op, step.op)
        if op == "slice":
            continue              # local, no wire
        g.records.append(CollRecord(
            op=op, axes=tuple(step.axes), dtype=plan.dtype,
            shape=(), nbytes=int(step.wire_bytes), trips=1,
            perm=tuple(tuple(int(x) for x in p) for p in step.perm),
            path=f"reshard-plan/step{i}:{step.describe()}"))
    return g


# ---------------------------------------------------------------------------
# verify: static prediction vs runtime attribution
# ---------------------------------------------------------------------------

# runtime per-coll ledger key -> static wire model.  The traffic plane
# files its charges under wrapper-chosen coll names; each maps to the
# static model that reproduces the wrapper's byte expression exactly.
_DEFAULT_COLL_MAP = {
    "grad_sync": "psum_ring",
    "ring_attention": "ppermute",
    "ulysses": "all_to_all",
    "reshard": "reshard",
    # the fused decode program's collective-matmul rings: n−1 ppermute
    # hops per ring, charged per-ring by the serving engine — the
    # ppermute trip model reproduces the schedule's wire column exactly
    "decode_collmm": "ppermute",
}


@dataclass
class VerifyReport:
    """``verify()``'s typed result."""
    source: str
    n_records: int
    issues: List[Issue]
    rows: List[Dict[str, Any]]       # coll / static / runtime / ok
    host_transfers: List[str]

    @property
    def ok(self) -> bool:
        return (all(r["ok"] for r in self.rows)
                and not any(i.severity == "error" for i in self.issues))

    def to_json(self) -> Dict[str, Any]:
        return {
            "source": self.source, "ok": self.ok,
            "n_records": self.n_records,
            "issues": [{"kind": i.kind, "msg": i.msg,
                        "severity": i.severity} for i in self.issues],
            "rows": self.rows,
            "host_transfers": list(self.host_transfers),
        }

    def summary(self) -> str:
        lines = [f"commgraph: {self.source}: {self.n_records} collective "
                 f"record(s), {len(self.issues)} issue(s), "
                 f"{'OK' if self.ok else 'FAIL'}"]
        for r in self.rows:
            lines.append(
                f"  {r['coll']}: static {r['static']} B vs runtime "
                f"{r['runtime']} B {'==' if r['ok'] else '!='}")
        for i in self.issues:
            lines.append(f"  [{i.severity}] {i.kind}: {i.msg}")
        return "\n".join(lines)


def _static_bytes(g: CommGraph, mesh, model: str) -> int:
    if model == "psum_ring":
        return g.psum_ring_bytes(mesh)
    if model == "ppermute":
        return g.ppermute_bytes()
    if model == "all_to_all":
        return g.all_to_all_bytes()
    if model == "gather_scatter":
        return g.gather_scatter_bytes(mesh)
    if model == "reshard":
        return g.reshard_bytes()
    raise ValueError(f"unknown static wire model {model!r}")


def verify(fn: Callable, args: Sequence[Any], mesh,
           coll_map: Optional[Dict[str, str]] = None,
           graph: Optional[CommGraph] = None,
           runner: Optional[Callable[[], Any]] = None,
           source: str = "") -> VerifyReport:
    """Static checks + byte-for-byte static-vs-runtime wire agreement.

    Extracts ``fn``'s collective program (or takes a pre-built
    ``graph``, e.g. a plan-lifted one), runs the well-formedness
    checks, then executes ``runner()`` (default: ``fn(*args)`` blocked
    to completion) under the traffic plane and compares the runtime
    per-coll byte deltas against the static models named by
    ``coll_map`` (default ``_DEFAULT_COLL_MAP``).  The traffic plane's
    prior enabled state is restored."""
    import jax

    from .. import traffic

    g = graph if graph is not None else extract(
        fn, *args, source=source or getattr(fn, "__name__", "<fn>"))
    issues = g.check(mesh)
    cmap = dict(_DEFAULT_COLL_MAP if coll_map is None else coll_map)

    was_enabled = traffic.enabled
    if not was_enabled:
        traffic.enable()
    try:
        before = traffic.matrix.per_coll()
        out = runner() if runner is not None else fn(*args)
        jax.block_until_ready(out)
        after = traffic.matrix.per_coll()
    finally:
        if not was_enabled:
            traffic.disable()

    rows: List[Dict[str, Any]] = []
    for coll, model in cmap.items():
        static = _static_bytes(g, mesh, model)
        runtime = int(after.get(coll, 0)) - int(before.get(coll, 0))
        if static == 0 and runtime == 0:
            continue
        rows.append({"coll": coll, "model": model, "static": int(static),
                     "runtime": runtime, "ok": static == runtime})
    return VerifyReport(source=g.source, n_records=len(g.records),
                        issues=issues, rows=rows,
                        host_transfers=list(g.host_transfers))


# -- policy action verification ----------------------------------------------

# the decided-dispatch vocabulary a policy action may retarget, with the
# flat native arm's per-device hop factor (fraction of the payload, the
# same 2(n-1)/n-family expressions as perf/model._FACTOR and the
# runtime note models)
_ACTION_COLL_FACTORS: Dict[str, Callable[[int], float]] = {
    "allreduce": lambda n: 2.0 * (n - 1) / n,
    "grad_sync": lambda n: 2.0 * (n - 1) / n,        # bucketed allreduce
    "reduce_scatter": lambda n: (n - 1) / n,
    "allgather": lambda n: (n - 1) / n,
    "alltoall": lambda n: (n - 1) / n,
    "broadcast": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
    "collmm": lambda n: (n - 1) / n,
    "moe_dispatch": lambda n: (n - 1) / n,
    "moe_combine": lambda n: (n - 1) / n,
    "decode_ag": lambda n: (n - 1) / n,
    "decode_rs": lambda n: (n - 1) / n,
}

# ops with a quantized wire format (coll/quant.wire_bytes vocabulary,
# plus the bucketed-allreduce alias)
_QUANTIZABLE = {"allreduce": "allreduce", "grad_sync": "allreduce",
                "reduce_scatter": "reduce_scatter",
                "allgather": "allgather"}


def verify_action(coll: str, arm: str, nbytes: int = 1 << 20,
                  ndev: int = 8, dtype: str = "float32"
                  ) -> Dict[str, Any]:
    """Statically verify one policy-reachable ``(coll, arm)`` retarget.

    The policy engine calls this at CONSTRUCTION for every arm its
    rules can reach — an action that cannot be verified here is
    rejected at registration, never at 3 a.m.  Checks the arm against
    the DEVICE_RULES mode vocabulary, the op against the decided
    dispatch vocabulary, and that the arm has a wire format for the op
    (``quant`` on an op with no quantized codec is structurally
    impossible, not a runtime surprise).  Returns the wire-byte
    prediction for a ``nbytes`` payload over ``ndev`` devices — the
    figure the decision ledger records next to the measured effect.

    Raises ``ValueError`` with the full (coll, arm) context on any
    unverifiable action.
    """
    from . import rules as _rules

    if arm not in _rules.MODES:
        raise ValueError(
            f"policy action retargets {coll!r} to unknown arm {arm!r} "
            f"— not in the DEVICE_RULES mode vocabulary {_rules.MODES}")
    if coll not in _ACTION_COLL_FACTORS:
        raise ValueError(
            f"policy action retargets unknown op {coll!r} (arm {arm!r}) "
            f"— not in the decided dispatch vocabulary "
            f"{tuple(sorted(_ACTION_COLL_FACTORS))}")
    n = max(int(ndev), 2)
    esize = int(np.dtype(dtype).itemsize)
    native = int(round(_ACTION_COLL_FACTORS[coll](n) * int(nbytes)))
    wire = native
    quant_ratio = None
    if arm in ("quant", "hier+quant"):
        qcoll = _QUANTIZABLE.get(coll)
        if qcoll is None:
            raise ValueError(
                f"policy action retargets {coll!r} to arm {arm!r} but "
                f"{coll!r} has no quantized wire format "
                f"(quantizable: {tuple(sorted(_QUANTIZABLE))})")
        from ..coll.quant import wire_bytes
        wb = wire_bytes(qcoll, max(int(nbytes) // esize, 1), n, dtype)
        wire, native = int(wb["quant_bytes"]), int(wb["native_bytes"])
        quant_ratio = round(float(wb["ratio"]), 4)
    return {"coll": coll, "arm": arm, "ndev": n, "nbytes": int(nbytes),
            "predicted_wire_bytes": wire, "native_wire_bytes": native,
            "quant_ratio": quant_ratio, "ok": True}
