"""Latency-hiding collective matmuls (comm/compute overlap on ICI).

The reference hides communication latency by *segmenting* large payloads and
pipelining segments through ring schedules (segmented ring allreduce,
coll_base_allreduce.c:621; the RDMA pipeline, pml_ob1_rdma.c). The TPU-native
form of that idea fuses the pipeline with the consumer: instead of
``allgather then matmul`` (ICI idle during the matmul, MXU idle during the
gather), rotate shards around the ring with ``lax.ppermute`` and issue the
matmul block for each visiting shard — XLA overlaps step i's ppermute with
step i's dot, keeping both ICI and MXU busy.

Two schedules (the two halves of a sharded matmul, "How to Scale Your
Model" recipe):

  * ``allgather_matmul``   —  Y = all_gather(X, axis) @ W, X sharded on its
    row (m) dimension. Used by column-parallel layers with sequence/data
    sharded activations (Megatron sequence parallelism's g operator).
  * ``matmul_reduce_scatter`` — Y = reduce_scatter(X @ W, axis), X/W sharded
    on the contraction (k) dimension, output scattered on m. The
    row-parallel half (Megatron's ḡ operator); the ring carries partial
    sums, the matmul for hop i is computed just-in-time before it is added.

Both are expressed in ``shard_map`` so they compose with any outer pjit
program; correctness reference in tests/test_ops.py. Both accept an
optional leading batch dimension (activations shaped (b, m, k), optionally
sharded over ``batch_axis``) and a ``bidirectional`` schedule that splits
the payload across the two ICI ring directions — two half-rings of
concurrent ppermutes — so each link carries half the bytes. The decision
layer arbitrates unidirectional vs bidirectional per call site under the
coll name ``collmm`` (see parallel/overlap.decide_collmm).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..jaxcompat import shard_map


def ring_allgather_matmul_local(x, w, axis: str, n: int, *,
                                reverse: bool = False):
    """Shard-level body of the allgather-matmul ring, callable INSIDE
    any shard_map over ``axis``: x (..., m_local, k) is this rank's row
    shard, w (k, c) its (column-local) weight; returns (..., m_local*n,
    c) with every rank's block filled.  Exactly n−1 ppermutes: the own
    block's matmul is peeled before the loop, so the rotating shard
    makes the minimum number of hops and the static extractor's
    trips × payload figure equals the runtime (n−1)·shard charge
    byte-for-byte (the serving tier's fused decode program verifies
    this per step)."""
    m_local = x.shape[-2]
    my = lax.axis_index(axis)
    lead = (0,) * (x.ndim - 2)

    def place(out, block, row0):
        return lax.dynamic_update_slice(
            out, block.astype(out.dtype), lead + (row0, 0))

    out = jnp.zeros(x.shape[:-2] + (m_local * n, w.shape[1]),
                    jnp.promote_types(x.dtype, w.dtype))
    out = place(out, jnp.dot(x, w, preferred_element_type=out.dtype),
                my * m_local)
    if n == 1:
        return out
    shift = 1 if not reverse else -1
    perm = [(j, (j + shift) % n) for j in range(n)]

    def step(i, carry):
        out, xs = carry
        xs = lax.ppermute(xs, axis, perm)
        # after i hops the visiting shard originated at rank (my - i*shift)
        src = (my - i * shift) % n
        block = jnp.dot(xs, w, preferred_element_type=out.dtype)
        return place(out, block, src * m_local), xs

    out, _ = lax.fori_loop(1, n, step, (out, x))
    return out


def ring_allgather_matmul_bidir_local(x, w, axis: str, n: int):
    """Bidirectional variant of :func:`ring_allgather_matmul_local`:
    the local rows split in half and rotate in OPPOSITE directions —
    two concurrent ppermutes per step drive both ICI link directions at
    once, so each link carries half the bytes. The +1 half visiting at
    step i originated at (my - i); the -1 half at (my + i). n−1 hops
    per half (own halves peeled)."""
    m_local = x.shape[-2]
    my = lax.axis_index(axis)
    lead = (0,) * (x.ndim - 2)

    def place(out, block, row0):
        return lax.dynamic_update_slice(
            out, block.astype(out.dtype), lead + (row0, 0))

    mh = m_local // 2
    xa = lax.slice_in_dim(x, 0, mh, axis=-2)
    xb = lax.slice_in_dim(x, mh, m_local, axis=-2)
    out = jnp.zeros(x.shape[:-2] + (m_local * n, w.shape[1]),
                    jnp.promote_types(x.dtype, w.dtype))
    out = place(out, jnp.dot(xa, w, preferred_element_type=out.dtype),
                my * m_local)
    out = place(out, jnp.dot(xb, w, preferred_element_type=out.dtype),
                my * m_local + mh)
    if n == 1:
        return out
    perm_f = [(j, (j + 1) % n) for j in range(n)]
    perm_b = [(j, (j - 1) % n) for j in range(n)]

    def step(i, carry):
        out, xf, xr = carry
        xf = lax.ppermute(xf, axis, perm_f)
        xr = lax.ppermute(xr, axis, perm_b)
        src_f = (my - i) % n
        src_b = (my + i) % n
        bf = jnp.dot(xf, w, preferred_element_type=out.dtype)
        br = jnp.dot(xr, w, preferred_element_type=out.dtype)
        out = place(out, bf, src_f * m_local)
        out = place(out, br, src_b * m_local + mh)
        return out, xf, xr

    out, _, _ = lax.fori_loop(1, n, step, (out, xa, xb))
    return out


@functools.lru_cache(maxsize=64)
def _build_allgather_matmul(mesh: Mesh, axis: str, w_spec: P, reverse: bool,
                            bidir: bool, batch_axis: Optional[str],
                            ndim: int):
    n = mesh.shape[axis]

    def local(x, w):
        if bidir:
            return ring_allgather_matmul_bidir_local(x, w, axis, n)
        return ring_allgather_matmul_local(x, w, axis, n, reverse=reverse)

    if batch_axis is not None or ndim == 3:
        x_spec = P(batch_axis, axis, None)
        out_spec = P(batch_axis, None, w_spec[1])
    else:
        x_spec = P(axis, None)
        out_spec = P(None, w_spec[1])
    # The output is value-replicated over `axis` (every rank fills all n
    # blocks) but provenance-varying (it flowed through ppermute), so the
    # static VMA check can't prove replication — disable it here.
    return jax.jit(shard_map(local, mesh=mesh,
                             in_specs=(x_spec, w_spec),
                             out_specs=out_spec,
                             check_vma=False))


def allgather_matmul(x: jax.Array, w: jax.Array, mesh: Mesh, axis: str,
                     w_sharded_axis: Optional[str] = None,
                     reverse: bool = False, bidirectional: bool = False,
                     batch_axis: Optional[str] = None) -> jax.Array:
    """Y = all_gather(X over `axis`) @ W without a standalone all-gather.

    x: (m, k) or batched (b, m, k) sharded on m over `axis` (and optionally
    on b over `batch_axis`); w: (k, n), optionally sharded on n over
    `w_sharded_axis` (the column-parallel case). Returns (..., m, n) with m
    fully gathered, n keeping w's sharding.

    ``bidirectional=True`` splits each rank's rows across both ICI ring
    directions (two half-rings of concurrent ppermutes) so each link
    carries half the bytes; it needs an even per-rank row count and
    ignores ``reverse`` (both directions are in flight).
    """
    if x.ndim not in (2, 3):
        raise ValueError(f"allgather_matmul wants 2-D or 3-D x, got "
                         f"shape {x.shape}")
    n = mesh.shape[axis]
    m = x.shape[-2]
    if bidirectional and (m // n) % 2:
        raise ValueError(
            f"bidirectional ring needs an even per-rank row count, got "
            f"m={m} over {n} ranks (m_local={m // n})")
    w_spec = P(None, w_sharded_axis)
    from .. import traffic
    if traffic.enabled and not isinstance(x, jax.core.Tracer):
        # each rank's x shard makes n-1 ring hops; direction follows the
        # schedule actually lowered (collmm decision's reverse/bidir)
        traffic.note_ring(
            mesh, axis, (n - 1) * x.nbytes // max(n, 1),
            "allgather_matmul",
            "bidir" if bidirectional else ("rev" if reverse else "fwd"))
    return _build_allgather_matmul(mesh, axis, w_spec, bool(reverse),
                                   bool(bidirectional), batch_axis,
                                   x.ndim)(x, w)


def ring_matmul_reduce_scatter_local(x, w, axis: str, n: int):
    """Shard-level body of the matmul-reduce-scatter ring, callable
    INSIDE any shard_map over ``axis``: x (..., m, k_local) carries the
    full m rows with this rank's contraction slice, w (k_local, c) its
    weight rows; returns (..., m/n, c) — the fully reduced m-block this
    rank owns.  n−1 ppermutes: partial sums ride the ring in float32
    and each hop's matmul block is produced just in time.

    The chunk destined for rank d starts at rank (d+1)%n and rides the
    ring n−1 hops, each visited rank adding its local partial block.
    After t hops, rank r therefore holds the chunk destined for
    d = (r-1-t) % n; after n−1 hops that is d = r — its own."""
    m = x.shape[-2]
    if m % n:
        raise ValueError(f"m={m} not divisible by ring size {n}")
    mb = m // n
    my = lax.axis_index(axis)

    def block(idx, off, nrows):
        rows = lax.dynamic_slice_in_dim(x, idx * mb + off, nrows,
                                        axis=-2)
        return jnp.dot(rows, w, preferred_element_type=jnp.float32)

    out_dtype = jnp.promote_types(x.dtype, w.dtype)
    acc = block((my - 1) % n, 0, mb)
    if n == 1:
        return acc.astype(out_dtype)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(t, acc):
        return (lax.ppermute(acc, axis, perm)
                + block((my - 1 - t) % n, 0, mb))

    acc = lax.fori_loop(1, n, step, acc)
    return acc.astype(out_dtype)


def ring_matmul_reduce_scatter_bidir_local(x, w, axis: str, n: int):
    """Bidirectional variant of :func:`ring_matmul_reduce_scatter_local`:
    each destination's mb rows split in half.  The top half rides the
    +1 ring; the bottom half rides the -1 ring — its chunk for dest d
    starts at rank (d-1)%n, and after t backward hops rank r holds the
    chunk destined for d = (r+1+t) % n, landing at d = r after n−1
    hops. One fori_loop carries both accumulators so XLA can keep both
    ppermutes (both ICI directions) in flight at once."""
    m = x.shape[-2]
    if m % n:
        raise ValueError(f"m={m} not divisible by ring size {n}")
    mb = m // n
    my = lax.axis_index(axis)

    def block(idx, off, nrows):
        rows = lax.dynamic_slice_in_dim(x, idx * mb + off, nrows,
                                        axis=-2)
        return jnp.dot(rows, w, preferred_element_type=jnp.float32)

    out_dtype = jnp.promote_types(x.dtype, w.dtype)
    mbh = mb // 2
    perm_f = [(j, (j + 1) % n) for j in range(n)]
    perm_b = [(j, (j - 1) % n) for j in range(n)]

    def step(t, carry):
        af, ab = carry
        af = (lax.ppermute(af, axis, perm_f)
              + block((my - 1 - t) % n, 0, mbh))
        ab = (lax.ppermute(ab, axis, perm_b)
              + block((my + 1 + t) % n, mbh, mb - mbh))
        return af, ab

    af = block((my - 1) % n, 0, mbh)
    ab = block((my + 1) % n, mbh, mb - mbh)
    if n > 1:
        af, ab = lax.fori_loop(1, n, step, (af, ab))
    return jnp.concatenate([af, ab], axis=-2).astype(out_dtype)


@functools.lru_cache(maxsize=64)
def _build_matmul_rs(mesh: Mesh, axis: str, bidir: bool,
                     batch_axis: Optional[str], ndim: int):
    n = mesh.shape[axis]

    def local(x, w):
        # x: (..., m, k_local), w: (k_local, n_cols): full partial product
        # would be x @ w (..., m, n_cols); ring-reduce-scatter it over the m
        # dimension while computing each m-block just in time.
        if bidir:
            return ring_matmul_reduce_scatter_bidir_local(x, w, axis, n)
        return ring_matmul_reduce_scatter_local(x, w, axis, n)

    if batch_axis is not None or ndim == 3:
        in_specs = (P(batch_axis, None, axis), P(axis, None))
        out_spec = P(batch_axis, axis, None)
    else:
        in_specs = (P(None, axis), P(axis, None))
        out_spec = P(axis, None)
    return jax.jit(shard_map(local, mesh=mesh,
                             in_specs=in_specs,
                             out_specs=out_spec))


def matmul_reduce_scatter(x: jax.Array, w: jax.Array, mesh: Mesh,
                          axis: str, bidirectional: bool = False,
                          batch_axis: Optional[str] = None) -> jax.Array:
    """Y = reduce_scatter(X @ W over `axis`), contraction sharded.

    x: (m, k) or batched (b, m, k) sharded on k over `axis` (and
    optionally on b over `batch_axis`); w: (k, n) sharded on k likewise.
    Returns (..., m, n) sharded on m over `axis` — each rank holds the
    fully reduced m-block it owns. Partial sums ride the ring and each
    hop's matmul block is produced just-in-time, overlapping ICI with the
    MXU.

    ``bidirectional=True`` halves each destination chunk across the two
    ICI ring directions (concurrent forward/backward ppermutes); it needs
    an even per-rank row count (``m // ring_size`` even).
    """
    if x.ndim not in (2, 3):
        raise ValueError(f"matmul_reduce_scatter wants 2-D or 3-D x, got "
                         f"shape {x.shape}")
    n = mesh.shape[axis]
    m = x.shape[-2]
    if bidirectional and (m // n) % 2:
        raise ValueError(
            f"bidirectional ring needs an even per-rank row count, got "
            f"m={m} over {n} ranks (m_local={m // n})")
    from .. import traffic
    if traffic.enabled and not isinstance(x, jax.core.Tracer):
        import numpy as np
        # the ring carries (m/n, n_cols) partial-sum blocks in the
        # promoted output dtype for n-1 hops per rank
        odt = np.promote_types(x.dtype, w.dtype)
        batch = x.shape[0] if x.ndim == 3 else 1
        if batch_axis is not None:
            batch //= max(mesh.shape[batch_axis], 1)
        traffic.note_ring(
            mesh, axis,
            (n - 1) * (m // max(n, 1)) * batch * w.shape[-1]
            * odt.itemsize,
            "matmul_reduce_scatter", "bidir" if bidirectional else "fwd")
    return _build_matmul_rs(mesh, axis, bool(bidirectional), batch_axis,
                            x.ndim)(x, w)
