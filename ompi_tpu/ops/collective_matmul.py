"""Latency-hiding collective matmuls (comm/compute overlap on ICI).

The reference hides communication latency by *segmenting* large payloads and
pipelining segments through ring schedules (segmented ring allreduce,
coll_base_allreduce.c:621; the RDMA pipeline, pml_ob1_rdma.c). The TPU-native
form of that idea fuses the pipeline with the consumer: instead of
``allgather then matmul`` (ICI idle during the matmul, MXU idle during the
gather), rotate shards around the ring with ``lax.ppermute`` and issue the
matmul block for each visiting shard — XLA overlaps step i's ppermute with
step i's dot, keeping both ICI and MXU busy.

Two schedules (the two halves of a sharded matmul, "How to Scale Your
Model" recipe):

  * ``allgather_matmul``   —  Y = all_gather(X, axis) @ W, X sharded on its
    row (m) dimension. Used by column-parallel layers with sequence/data
    sharded activations (Megatron sequence parallelism's g operator).
  * ``matmul_reduce_scatter`` — Y = reduce_scatter(X @ W, axis), X/W sharded
    on the contraction (k) dimension, output scattered on m. The
    row-parallel half (Megatron's ḡ operator); the ring carries partial
    sums, the matmul for hop i is computed just-in-time before it is added.

Both are expressed in ``shard_map`` so they compose with any outer pjit
program; correctness reference in tests/test_ops.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..jaxcompat import shard_map


@functools.lru_cache(maxsize=64)
def _build_allgather_matmul(mesh: Mesh, axis: str, w_spec: P, reverse: bool):
    n = mesh.shape[axis]

    def local(x, w):
        # x: (m_local, k) — this rank's shard; w: (k, n_local or n)
        m_local = x.shape[0]
        my = lax.axis_index(axis)
        shift = 1 if not reverse else -1
        perm = [(j, (j + shift) % n) for j in range(n)]

        def step(i, carry):
            out, xs = carry
            # the shard visiting at step i originated at rank (my - i*shift)
            src = (my - i * shift) % n
            block = jnp.dot(xs, w, preferred_element_type=out.dtype)
            out = lax.dynamic_update_slice(
                out, block.astype(out.dtype), (src * m_local, 0))
            xs = lax.ppermute(xs, axis, perm)
            return out, xs

        out0 = jnp.zeros((m_local * n, w.shape[1]),
                         jnp.promote_types(x.dtype, w.dtype))
        out, _ = lax.fori_loop(0, n, step, (out0, x))
        return out

    x_spec = P(axis, None)
    # The output is value-replicated over `axis` (every rank fills all n
    # blocks) but provenance-varying (it flowed through ppermute), so the
    # static VMA check can't prove replication — disable it here.
    return jax.jit(shard_map(local, mesh=mesh,
                             in_specs=(x_spec, w_spec),
                             out_specs=P(None, w_spec[1]),
                             check_vma=False))


def allgather_matmul(x: jax.Array, w: jax.Array, mesh: Mesh, axis: str,
                     w_sharded_axis: Optional[str] = None,
                     reverse: bool = False) -> jax.Array:
    """Y = all_gather(X over `axis`) @ W without a standalone all-gather.

    x: (m, k) sharded on m over `axis`; w: (k, n), optionally sharded on n
    over `w_sharded_axis` (the column-parallel case). Returns (m, n) with m
    fully gathered, n keeping w's sharding.
    """
    w_spec = P(None, w_sharded_axis)
    return _build_allgather_matmul(mesh, axis, w_spec, bool(reverse))(x, w)


@functools.lru_cache(maxsize=64)
def _build_matmul_rs(mesh: Mesh, axis: str):
    n = mesh.shape[axis]

    def local(x, w):
        # x: (m, k_local), w: (k_local, n_cols): full partial product would be
        # x @ w (m, n_cols); ring-reduce-scatter it over the m dimension while
        # computing each m-block just in time.
        m = x.shape[0]
        if m % n:
            raise ValueError(f"m={m} not divisible by ring size {n}")
        mb = m // n
        my = lax.axis_index(axis)
        perm = [(j, (j + 1) % n) for j in range(n)]

        def block(idx):
            rows = lax.dynamic_slice(x, (idx * mb, 0), (mb, x.shape[1]))
            return jnp.dot(rows, w, preferred_element_type=jnp.float32)

        # The chunk destined for rank d starts at rank (d+1)%n and rides the
        # ring n-1 hops, each visited rank adding its local partial block.
        # After t hops, rank r therefore holds the chunk destined for
        # d = (r-1-t) % n; after n-1 hops that is d = r — its own.
        def step(t, acc):
            acc = lax.ppermute(acc, axis, perm) + block((my - 1 - t) % n)
            return acc

        acc = block((my - 1) % n)
        acc = lax.fori_loop(1, n, step, acc)
        return acc.astype(jnp.promote_types(x.dtype, w.dtype))

    return jax.jit(shard_map(local, mesh=mesh,
                             in_specs=(P(None, axis), P(axis, None)),
                             out_specs=P(axis, None)))


def matmul_reduce_scatter(x: jax.Array, w: jax.Array, mesh: Mesh,
                          axis: str) -> jax.Array:
    """Y = reduce_scatter(X @ W over `axis`), contraction sharded.

    x: (m, k) sharded on k over `axis`; w: (k, n) sharded on k likewise.
    Returns (m, n) sharded on m over `axis` — each rank holds the fully
    reduced m-block it owns. Partial sums ride the ring and each hop's
    matmul block is produced just-in-time, overlapping ICI with the MXU.
    """
    return _build_matmul_rs(mesh, axis)(x, w)
