"""Block flash attention as a Pallas TPU kernel.

Design (pallas_guide.md patterns): grid = (batch*heads, q_blocks, kv_blocks)
with the kv dimension innermost — on TPU the innermost grid dimension is
sequential per core, so the online-softmax state (row max ``m``, denominator
``l``, un-normalized accumulator ``acc``) lives in VMEM scratch and is
carried across kv steps; the final kv step normalizes and writes the output
block. The QK and PV dots run in the storage dtype with float32
accumulation (``preferred_element_type``): bfloat16 inputs stay bfloat16 in
HBM/VMEM and on the MXU operand ports, probabilities are downcast to the
storage dtype for the PV dot, and only the online-softmax state (m, l, acc)
is float32.

Three entry points:
  * ``flash_attention`` — self-contained attention (optionally causal);
  * ``flash_attention_partials`` — returns the *un-normalized* (o, m, l)
    triple for a Q-shard against one visiting K/V shard, with global
    position offsets for the causal mask.  This is the per-step block
    compute of ring attention (parallel/ring.py), which merges partials
    across ring hops — the kernel analog of the reference's segmented ring
    schedule (coll_base_allreduce.c:621).
  * ``flash_mha`` — differentiable (custom-VJP) flash attention for
    training: the forward saves only (o, logsumexp) and the backward
    recomputes probabilities blockwise in two Pallas kernels (dq; dk/dv),
    the FlashAttention-2 scheme — O(seq) residual memory instead of the
    O(seq²) score tensor, which is what lets the flagship train step keep
    long sequences on the MXU at high utilization.

Interpret mode (``interpret=True``) runs the same kernels on CPU for tests;
on TPU backends the default is the compiled path.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


def check_tpu_block(block, array_shape, what: str = "block",
                    dtype=jnp.float32) -> None:
    """Enforce the Mosaic TPU tiling rule at trace time, on EVERY backend.

    Real-TPU Pallas requires the last two block dims be divisible by the
    dtype's (sublane, lane) tile — (8, 128) for 4-byte types, sublanes
    doubling as the itemsize halves (16 for bf16, 32 for int8/fp8) — or
    equal to the corresponding array dim. Interpret mode (the CPU test
    path) never checks this, which is how an unlowerable (1, bq) block on
    a (bh, s_q) output survived 500+ green CPU tests and then failed the
    first real-chip flagship compile (commit d5b947d). Calling this in
    the kernel wrappers makes that failure class a CPU-testable
    invariant."""
    if len(block) < 2:
        return                       # 1-D blocks: lane tiling only, exempt
    if len(block) != len(array_shape):
        raise ValueError(
            f"{what}: block {tuple(block)} and array {tuple(array_shape)} "
            f"have different ranks — mis-paired shapes, nothing checked")
    sublane = 8 * max(1, 4 // jnp.dtype(dtype).itemsize)
    for off, req in ((-2, sublane), (-1, 128)):
        b, a = block[off], array_shape[off]
        if b != a and b % req:
            raise ValueError(
                f"{what}: block {tuple(block)} on array "
                f"{tuple(array_shape)} ({jnp.dtype(dtype).name}) is not "
                f"TPU-lowerable — dim {off} block size {b} is neither a "
                f"multiple of {req} nor equal to the array dim {a}")


def _auto_block(s: int) -> int:
    """Largest power-of-two block ≤1024 dividing the sequence: the v5e
    block sweep (BASELINE.md) shows 1024² blocks run 2.4× faster than 256²
    (fewer grid steps amortize the VMEM scratch round-trips; ~2 MB VMEM at
    d=64 stays well under budget)."""
    for b in (1024, 512, 256, 128, 64, 32):
        if s % b == 0:
            return b
    if s <= 1024:
        return s       # odd short sequence: one full-seq block fits VMEM
    # long and no usable divisor: never auto-pick a full-seq block (a
    # seq² fp32 score tile would blow VMEM) — the caller must choose
    raise ValueError(
        f"no power-of-two block ≤1024 divides sequence length {s}; pass "
        f"block_q/block_k explicitly")


def _auto_block_bwd(s: int) -> int:
    """Block auto-pick for the BACKWARD kernels (dq; dk/dv). Tracked
    separately from the forward pick so an on-chip bwd block sweep (the
    A/B harness's 'flash bwd block' rows) can retune it without touching
    the fwd choice; until chip evidence says otherwise it mirrors the
    forward heuristic (the bwd kernels carry two extra VMEM accumulators,
    so if anything the sweep is expected to prefer the SAME or one notch
    smaller block)."""
    return _auto_block(s)


def _block_sizes(s_q: int, s_k: int, block_q: Optional[int],
                 block_k: Optional[int],
                 auto=None, what: str = "blocks") -> Tuple[int, int]:
    """Resolve (block_q, block_k): explicit override, else ``auto``
    (default ``_auto_block``), clamped to the sequence and checked for
    divisibility — the ONE block-resolution invariant, shared by the fwd
    and bwd paths."""
    auto = auto or _auto_block
    bq = min(block_q or auto(s_q), s_q)
    bk = min(block_k or auto(s_k), s_k)
    if s_q % bq or s_k % bk:
        raise ValueError(f"seq lengths ({s_q},{s_k}) must divide into "
                         f"{what} ({bq},{bk})")
    return bq, bk


def _check_flash_blocks(bh: int, s_q: int, s_k: int, d: int,
                        bq: int, bk: int, with_partials: bool,
                        what: str, dtype=jnp.float32) -> None:
    """The three distinct (block, array) pairs every flash pallas_call in
    this module uses; see check_tpu_block. ``dtype`` is the q/k/v storage
    dtype (the sublane tile is dtype-dependent); m/l/lse/delta are always
    f32."""
    check_tpu_block((1, bq, d), (bh, s_q, d), f"{what} q/o", dtype)
    check_tpu_block((1, bk, d), (bh, s_k, d), f"{what} k/v", dtype)
    if with_partials:
        check_tpu_block((1, bq, 1), (bh, s_q, 1), f"{what} m/l/lse/delta",
                        jnp.float32)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  kv_steps: int, q_off: int, kv_off: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    qi = pl.program_id(1)
    # causal: a kv block fully above the diagonal contributes nothing —
    # skip its MXU work entirely (the ~2× flop saving causal promises;
    # the block DMA still happens, which is why the saving shows as ~1.7×)
    visible = True
    if causal:
        last_row = q_off + (qi + 1) * block_q - 1
        first_col = kv_off + ki * block_k
        visible = last_row >= first_col

    @pl.when(visible)
    def _compute():
        # operands stay in their storage dtype: on the MXU a bf16xbf16
        # dot with float32 accumulation (preferred_element_type) runs at
        # full rate, while upcasting inputs to f32 first quarters it (and
        # doubles VMEM); f32 inputs keep exact f32 math as before
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = (q_off + qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
            cols = (kv_off + ki * block_k
                    + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = (acc_ref[...] * alpha[:, None]
                        + jax.lax.dot_general(
                            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_ref[...] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur[:, None], l_ref.shape)

    @pl.when(ki == kv_steps - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, 0], 1e-20)
        o_ref[0] = (acc_ref[...] / denom[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Attention over (batch, seq, heads, head_dim) inputs.

    q may have a different sequence length than k/v (cross attention);
    ``causal`` assumes both sequences start at position 0.
    """
    if interpret is None:
        interpret = _default_interpret()
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    bq, bk = _block_sizes(s_q, s_k, block_q, block_k)
    _check_flash_blocks(b * h, s_q, s_k, d, bq, bk, False,
                        "flash_attention", q.dtype)
    kv_steps = s_k // bk

    qf = jnp.moveaxis(q, 2, 1).reshape(b * h, s_q, d)
    # kernels run uniform-dtype dots (lax.dot_general does not promote)
    kf = jnp.moveaxis(k, 2, 1).reshape(b * h, s_k, d).astype(q.dtype)
    vf = jnp.moveaxis(v, 2, 1).reshape(b * h, s_k, d).astype(q.dtype)

    kernel = functools.partial(
        _flash_kernel, scale=float(scale), causal=bool(causal), block_q=bq,
        block_k=bk, kv_steps=kv_steps, q_off=0, kv_off=0)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s_q // bq, kv_steps),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return jnp.moveaxis(out.reshape(b, h, s_q, d), 1, 2)


def _partials_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, m_out, l_out,
                     m_ref, l_ref, acc_ref, *, scale: float, causal: bool,
                     block_q: int, block_k: int, kv_steps: int):
    """Same state machine, but emits un-normalized (o, m, l).

    ``off_ref`` is an SMEM (2,) int32 holding the (q, kv) global position
    offsets — *runtime* values, so ring attention can feed it the traced
    per-hop shard origin (lax.axis_index arithmetic)."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    qi = pl.program_id(1)
    # same fully-masked-block skip as _flash_kernel, with RUNTIME offsets:
    # on a ring hop whose kv shard sits entirely in this q block's future,
    # every block is skipped and the hop costs only its DMA
    visible = True
    if causal:
        last_row = off_ref[0] + (qi + 1) * block_q - 1
        first_col = off_ref[1] + ki * block_k
        visible = last_row >= first_col

    @pl.when(visible)
    def _compute():
        q = q_ref[0]                 # native dtype -> full-rate MXU
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = (off_ref[0] + qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
            cols = (off_ref[1] + ki * block_k
                    + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = (acc_ref[...] * alpha[:, None]
                        + jax.lax.dot_general(
                            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_ref[...] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur[:, None], l_ref.shape)

    @pl.when(ki == kv_steps - 1)
    def _finish():
        o_ref[0] = acc_ref[...].astype(o_ref.dtype)
        # m/l blocks are (1, bq, 1): TPU tiling requires the last two block
        # dims be (8k, 128k) or equal to the array dims, so a flat (1, bq)
        # row block is unlowerable — the trailing singleton satisfies the
        # "equal to the array dim" arm while bq covers the sublane arm
        m_out[0] = m_ref[:, :1]
        l_out[0] = l_ref[:, :1]


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "interpret", "vma"))
def flash_attention_partials(q: jax.Array, k: jax.Array, v: jax.Array,
                             causal: bool = False,
                             scale: Optional[float] = None,
                             q_offset=0, kv_offset=0,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             interpret: Optional[bool] = None,
                             vma=None):
    """Un-normalized flash partials for ring attention's merge step.

    q/k/v: (bh, seq, head_dim) — already folded (batch*heads) as in the ring
    loop. ``q_offset``/``kv_offset`` are the *global* positions of the local
    Q shard and the visiting K/V shard — python ints or traced int scalars
    (ring attention passes lax.axis_index arithmetic). Returns (o, m, l):
    o un-normalized (bh, s_q, d) float32, m/l (bh, s_q) float32.
    """
    if interpret is None:
        interpret = _default_interpret()
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    bq, bk = _block_sizes(s_q, s_k, block_q, block_k)
    _check_flash_blocks(bh, s_q, s_k, d, bq, bk, True,
                        "flash_attention_partials", q.dtype)
    kv_steps = s_k // bk
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(kv_offset, jnp.int32)])

    k = k.astype(q.dtype)      # uniform-dtype dots (no promotion in lax)
    v = v.astype(q.dtype)
    kernel = functools.partial(
        _partials_kernel, scale=float(scale), causal=bool(causal),
        block_q=bq, block_k=bk, kv_steps=kv_steps)
    o, m, l = pl.pallas_call(
        kernel,
        grid=(bh, s_q // bq, kv_steps),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(offs, q, k, v)
    return o, m[..., 0], l[..., 0]


# ---------------------------------------------------------------------------
# differentiable flash attention (FlashAttention-2 backward as Pallas kernels)
# ---------------------------------------------------------------------------

def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                     causal: bool, block_q: int, block_k: int, q_steps: int):
    """dK/dV for one KV block: grid = (batch*heads, kv_blocks, q_blocks),
    q innermost-sequential so the (bk, d) accumulators live in VMEM scratch.
    Probabilities are recomputed from the saved logsumexp — no O(s²)
    residual."""
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros(dk_acc.shape, dk_acc.dtype)
        dv_acc[...] = jnp.zeros(dv_acc.shape, dv_acc.dtype)

    ki = pl.program_id(1)
    visible = True
    if causal:
        # any (row ≥ col) pair in this tile?  rows are q, cols are kv
        last_row = (qi + 1) * block_q - 1
        first_col = ki * block_k
        visible = last_row >= first_col

    @pl.when(visible)
    def _compute():
        q = q_ref[0]                 # native dtype -> full-rate MXU
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                                    # (bq, 1)
        delta = delta_ref[0]                                # (bq, 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = (qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
            cols = (ki * block_k
                    + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                                # (bq, bk) f32
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # pᵀ·dO
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # dsᵀ·Q

    @pl.when(qi == q_steps - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale: float, causal: bool,
                   block_q: int, block_k: int, kv_steps: int):
    """dQ for one Q block: grid = (batch*heads, q_blocks, kv_blocks), kv
    innermost-sequential."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros(dq_acc.shape, dq_acc.dtype)

    qi = pl.program_id(1)
    visible = True
    if causal:
        last_row = (qi + 1) * block_q - 1
        first_col = ki * block_k
        visible = last_row >= first_col

    @pl.when(visible)
    def _compute():
        q = q_ref[0]                 # native dtype -> full-rate MXU
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                                    # (bq, 1)
        delta = delta_ref[0]                                # (bq, 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = (qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
            cols = (ki * block_k
                    + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == kv_steps - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def flash_mha(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = False, scale: Optional[float] = None,
              block_q: Optional[int] = None, block_k: Optional[int] = None,
              interpret: Optional[bool] = None,
              bwd_block_q: Optional[int] = None,
              bwd_block_k: Optional[int] = None) -> jax.Array:
    """Differentiable flash attention over (batch, seq, heads, head_dim).

    The train-step entry point: identical math to ``flash_attention`` but
    with a FlashAttention-2 backward (blockwise recompute from the saved
    logsumexp), so ``jax.grad`` through it never materializes the score
    matrix. Residuals are q, k, v, o, logsumexp — O(batch·seq·heads·d).

    ``bwd_block_q``/``bwd_block_k`` tile the BACKWARD kernels
    independently of the forward (None = the fwd override if set, else
    ``_auto_block_bwd`` — so existing callers passing only
    block_q/block_k keep their pre-split behavior): the dq and dk/dv
    kernels hold extra VMEM accumulators, so their optimum block need
    not match the forward's — the A/B harness sweeps them separately
    (the reference's per-path segsize-tuning discipline,
    coll_tuned_dynamic_file.c:58, applied to kernel blocks)."""
    out, _ = _flash_mha_fwd(q, k, v, causal, scale, block_q, block_k,
                            interpret, bwd_block_q, bwd_block_k)
    return out


def _flash_mha_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                   bwd_block_q=None, bwd_block_k=None):
    if interpret is None:
        interpret = _default_interpret()
    if k.dtype != q.dtype or v.dtype != q.dtype:
        # custom_vjp cotangents must match the primal input avals; a cast
        # here would hand jax.grad dk/dv in q.dtype and fail downstream.
        raise TypeError(
            f"flash_mha requires uniform q/k/v dtype, got q={q.dtype} "
            f"k={k.dtype} v={v.dtype}; cast inputs before calling")
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    qf = jnp.moveaxis(q, 2, 1).reshape(b * h, s_q, d)
    kf = jnp.moveaxis(k, 2, 1).reshape(b * h, s_k, d)
    vf = jnp.moveaxis(v, 2, 1).reshape(b * h, s_k, d)
    o_un, m, l = flash_attention_partials(
        qf, kf, vf, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret)
    l = jnp.maximum(l, 1e-20)
    of = (o_un / l[..., None]).astype(q.dtype)
    lse = m + jnp.log(l)                                    # (bh, s_q)
    out = jnp.moveaxis(of.reshape(b, h, s_q, d), 1, 2)
    return out, (qf, kf, vf, of, lse, (b, h))


def _flash_mha_bwd(causal, scale, block_q, block_k, interpret,
                   bwd_block_q, bwd_block_k, residuals, g):
    qf, kf, vf, of, lse, (b, h) = residuals
    if interpret is None:
        interpret = _default_interpret()
    bh, s_q, d = qf.shape
    s_k = kf.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    # bwd tiles independently of fwd: explicit bwd override, else the fwd
    # override (pre-split behavior for callers that only set
    # block_q/block_k), else the bwd auto-pick
    bq, bk = _block_sizes(s_q, s_k, bwd_block_q or block_q,
                          bwd_block_k or block_k,
                          auto=_auto_block_bwd, what="bwd blocks")
    _check_flash_blocks(bh, s_q, s_k, d, bq, bk, True, "flash_mha_bwd",
                        qf.dtype)
    dof = jnp.moveaxis(g, 2, 1).reshape(bh, s_q, d).astype(qf.dtype)
    # δ_i = Σ_d dO·O — the dS correction term (FlashAttention-2 eq. 4).
    # lse/delta carry a trailing singleton so their blocks are (1, bq, 1)
    # (TPU-lowerable; see _partials_kernel._finish)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # (bh, s_q, 1)
    lse3 = lse[..., None]                                   # (bh, s_q, 1)

    dkdv = functools.partial(
        _bwd_dkdv_kernel, scale=float(scale), causal=bool(causal),
        block_q=bq, block_k=bk, q_steps=s_q // bq)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, s_k // bk, s_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh_, ki, qi: (bh_, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh_, ki, qi: (bh_, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh_, ki, qi: (bh_, ki, 0)),
            pl.BlockSpec((1, bq, d), lambda bh_, ki, qi: (bh_, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh_, ki, qi: (bh_, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh_, ki, qi: (bh_, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh_, ki, qi: (bh_, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh_, ki, qi: (bh_, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_k, d), kf.dtype),
            jax.ShapeDtypeStruct((bh, s_k, d), vf.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse3, delta)

    dqk = functools.partial(
        _bwd_dq_kernel, scale=float(scale), causal=bool(causal),
        block_q=bq, block_k=bk, kv_steps=s_k // bk)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, s_q // bq, s_k // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh_, qi, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh_, qi, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, bq, d), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh_, qi, ki: (bh_, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh_, qi, ki: (bh_, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), qf.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse3, delta)

    unfold = lambda x, s: jnp.moveaxis(x.reshape(b, h, s, d), 1, 2)
    return unfold(dq, s_q), unfold(dk, s_k), unfold(dv, s_k)


flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)
