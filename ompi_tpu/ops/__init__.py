"""ops — Pallas/XLA kernels for the hot paths.

The reference's only hand-tuned compute is the AVX reduction kernels
(ompi/mca/op/avx/op_avx_component.c:45-47) — on TPU the analogous "do the
math where the data is" components are Pallas kernels:

  * ``attention`` — block flash attention (VMEM-resident online softmax),
    plus a partials variant that plugs into ring attention's merge step;
  * ``collective_matmul`` — latency-hiding allgather-matmul and
    matmul-reduce-scatter (comm/compute overlap on ICI), the TPU-native
    answer to the reference's segmented/pipelined collectives
    (coll_base_allreduce.c:344,621).
"""

from .attention import (flash_attention, flash_attention_partials,  # noqa: F401
                        flash_mha)
from .collective_matmul import allgather_matmul, matmul_reduce_scatter  # noqa: F401
