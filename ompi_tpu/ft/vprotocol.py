"""Pessimist-style message logging for replay-based recovery.

≙ the reference's vprotocol framework (ompi/mca/vprotocol/pessimist/,
interposed on pml via the ``pml/v`` wrapper; event log
vprotocol_pessimist_eventlog.c): the nondeterministic outcomes of a rank's
execution are its receive matches (which message satisfied which receive —
ANY_SOURCE/ANY_TAG resolution) and their payloads. A *pessimist* protocol
logs each outcome to stable storage before the application consumes it, so
a crashed rank can be re-executed deterministically: replayed receives
return exactly the logged messages in the logged order, without the
original senders.

Scope (vs the reference): event + payload logging at the RECEIVER (the
reference logs payloads at the sender and events at an event-logger rank;
a single stable log per rank gives the same replay power for fail-stop
recovery of that rank, at the cost of logging bandwidth — an explicit
trade, not an omission). Replay drives the application's receive sequence;
sends during replay are suppressed (their effects are already reflected in
the survivors, the standard pessimist discipline).

Usage:
    log = vprotocol.attach(ctx, logdir)          # wraps the live pml
    ... run; crash ...
    rp = vprotocol.Replayer(logdir, rank)        # restarted process
    rp.recv(buf, src, tag) → replays the logged message stream
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Any, Dict, Optional

import numpy as np

_MAGIC = b"OTPUVLG1"


def _log_path(logdir: str, rank: int) -> str:
    return os.path.join(logdir, f"msglog.{rank}.bin")


class MessageLog:
    """Append-only stable log of delivered receives (event + payload),
    flushed per record — the 'pessimist' property: the event is durable
    before the application can act on it."""

    def __init__(self, ctx, logdir: str) -> None:
        os.makedirs(logdir, exist_ok=True)
        self.path = _log_path(logdir, ctx.rank)
        self._fh = open(self.path, "wb")
        self._fh.write(_MAGIC)
        self._lock = threading.Lock()
        self.events = 0

    def record(self, src: int, tag: int, cid: int, payload: bytes) -> None:
        rec = pickle.dumps({"src": src, "tag": tag, "cid": cid,
                            "data": payload})
        with self._lock:
            self._fh.write(struct.pack("!I", len(rec)))
            self._fh.write(rec)
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.events += 1

    def close(self) -> None:
        with self._lock:
            self._fh.close()


def attach(ctx, logdir: str) -> MessageLog:
    """Interpose on the live pml (the pml/v position): every completed
    receive is logged before its request completes. Idempotent."""
    existing = getattr(ctx, "_msglog", None)
    if existing is not None:
        return existing
    log = MessageLog(ctx, logdir)
    ctx._msglog = log
    p2p = ctx.p2p
    orig_irecv, orig_imrecv = p2p.irecv, p2p.imrecv

    def _logged_cb(buf, cid):
        def logged(r):
            # runs at the pml layer, BEFORE comm-level source remapping:
            # logged sources are WORLD ranks (translate comm-local ranks
            # through the group when replaying sub-communicator code)
            if r.error is None and r.status.source >= 0:
                log.record(r.status.source, r.status.tag, cid,
                           _snapshot(buf, r.status.count))
        return logged

    def irecv(buf, src=-1, *a, **kw):
        # pass positionals through untouched — pml.recv calls with 6
        cid = a[1] if len(a) > 1 else kw.get("cid", 0)
        req = orig_irecv(buf, src, *a, **kw)
        req.add_completion_callback(_logged_cb(buf, cid))
        return req

    def imrecv(msg, buf, *a, **kw):
        # matched-message receives are deliveries too (mprobe/mrecv path);
        # the message's cid travels in its wire header — read it before
        # consume() empties the handle
        cid = msg._u.header.get("cid", 0) if msg._u is not None else 0
        req = orig_imrecv(msg, buf, *a, **kw)
        req.add_completion_callback(_logged_cb(buf, cid))
        return req

    p2p.irecv, p2p.imrecv = irecv, imrecv
    ctx._msglog_orig = (orig_irecv, orig_imrecv)
    return log


def detach(ctx) -> None:
    orig = getattr(ctx, "_msglog_orig", None)
    if orig is not None:
        ctx.p2p.irecv, ctx.p2p.imrecv = orig
        del ctx._msglog_orig
    log = getattr(ctx, "_msglog", None)
    if log is not None:
        log.close()
        del ctx._msglog


def _snapshot(buf, count: int) -> bytes:
    from ..accelerator import DeviceBuffer
    if isinstance(buf, DeviceBuffer):
        arr = np.asarray(buf.array)
    else:
        arr = np.asarray(buf)
    return arr.reshape(-1).view(np.uint8).tobytes()[:count]


class Replayer:
    """Deterministic re-execution source for a restarted rank: receives
    return the logged messages in logged order (matching src/tag when
    named; ANY_SOURCE/ANY_TAG resolve to whatever was logged — that IS the
    recorded nondeterminism). Sends are no-ops (suppressed, pessimist
    replay discipline)."""

    ANY = -1

    def __init__(self, logdir: str, rank: int) -> None:
        self.records = []
        path = _log_path(logdir, rank)
        with open(path, "rb") as fh:
            if fh.read(len(_MAGIC)) != _MAGIC:
                raise ValueError(f"{path}: not a message log")
            while True:
                hdr = fh.read(4)
                if len(hdr) < 4:
                    break
                (n,) = struct.unpack("!I", hdr)
                self.records.append(pickle.loads(fh.read(n)))
        self._pos = 0

    @property
    def remaining(self) -> int:
        return len(self.records) - self._pos

    def recv(self, buf, src: int = ANY, tag: int = ANY, cid: int = 0
             ) -> Dict[str, Any]:
        """Replay the next logged receive; validates that a named src/tag
        matches the log (a mismatch means the re-execution diverged, which
        pessimist recovery must detect, not paper over). ``src`` is a
        WORLD rank — the log records at the pml layer, below the
        communicator's rank remapping."""
        if self._pos >= len(self.records):
            raise RuntimeError("replay log exhausted")
        rec = self.records[self._pos]
        self._pos += 1
        if src != self.ANY and src != rec["src"]:
            raise RuntimeError(
                f"replay divergence: recv from {src}, log has {rec['src']}")
        if tag != self.ANY and tag != rec["tag"]:
            raise RuntimeError(
                f"replay divergence: recv tag {tag}, log has {rec['tag']}")
        if cid != rec["cid"]:
            raise RuntimeError(
                f"replay divergence: recv cid {cid}, log has {rec['cid']}")
        arr = np.asarray(buf)
        flat = arr.reshape(-1).view(np.uint8)
        data = np.frombuffer(rec["data"], np.uint8)
        flat[:len(data)] = data
        return {"source": rec["src"], "tag": rec["tag"],
                "count": len(data)}

    def send(self, *a, **kw) -> None:
        """Suppressed during replay (survivors already saw the original)."""
