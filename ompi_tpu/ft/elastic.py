"""Elastic fault-tolerant training: survive a rank death end-to-end.

The stack *detects* everything — heartbeat ring (ft/detector), desync
sentinel + watchdog (health), revoke/shrink/agree (ft/ulfm), per-shard
checkpoint checksums (ckpt) — and this module is the first subsystem
that *acts* on those observations.  The recovery choreography:

    trip ──────► shrink ─────► reshard ─────► resume
    watchdog /   ULFM revoke   cross-mesh     same step fn on the
    ProcFailed   + shrink      reshard from   survivor mesh, rolled
    verdict      (agree)       peer shadows   back to the shadow epoch

State never touches the filesystem on the way through: every device
keeps (a) a SNAPSHOT of its own state shards from the last shadow epoch
and (b) its LEFT NEIGHBOR's snapshot shards, refreshed by a low-rate
``ring_shift`` (one ppermute hop) piggybacked on the training loop.
When position ``p`` dies, its block survives on position ``(p+1) % n``,
and ``parallel.reshard.cross_reshard`` re-lays the whole tree onto the
survivor mesh sourcing dead blocks from those shadows — zero checkpoint
reads, wire and peak bytes under the same contracts as any reshard.

Memory cost of the shadows, per device: one snapshot shard + one
neighbor shard per dp-sharded leaf ≈ ``2/n`` of total state (replicated
leaves add one snapshot replica).  An adjacent double failure — ``p``
and ``(p+1) % n`` dead inside one shadow epoch — defeats the single
ring hop and is reported loudly (that is the checkpoint plane's job).

Every recovery emits one audited ``ft_recovery`` decision naming the
dead rank, bracketed by ``ft_trip`` / ``ft_shrink`` / ``ft_reshard`` /
``ft_resume`` trace instants, and banks a timeline record comm_doctor
--ft renders.  Deterministic fault injection lives in ft/chaos.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import importlib

from .. import jaxcompat as _compat, trace
from ..parallel.mesh import make_mesh

# the parallel package re-exports the reshard FUNCTION under the same
# name as the submodule — resolve the module itself
_reshard = importlib.import_module("ompi_tpu.parallel.reshard")
from .ulfm import (
    ProcFailedError,
    ProcFailedPendingError,
    WatchdogTimeoutError,
    failed_ranks,
    revoke,
    shrink,
)

PVARS = ("ft_recoveries", "ft_steps_lost", "ft_shadow_refreshes")

_lock = threading.Lock()
_counts: Dict[str, int] = {"ft_recoveries": 0, "ft_steps_lost": 0,
                           "ft_shadow_refreshes": 0}
_recovery_log: List[Dict[str, Any]] = []
_last_recovery: Optional[Dict[str, Any]] = None


def pvar_value(name: str) -> float:
    with _lock:
        return float(_counts[name])


def report() -> Dict[str, Any]:
    """Structured snapshot for comm_doctor --ft / the bench probe: the
    recovery timeline records plus the shadow/recovery counters."""
    with _lock:
        return {"counters": dict(_counts),
                "recoveries": [dict(r) for r in _recovery_log],
                "last": dict(_last_recovery) if _last_recovery else None}


def reset() -> None:
    global _last_recovery
    with _lock:
        for k in _counts:
            _counts[k] = 0
        _recovery_log.clear()
        _last_recovery = None


# ---------------------------------------------------------------------------
# elastic sharding: the ZeRO-style dim-0 layout every mesh size can host
# ---------------------------------------------------------------------------

def elastic_spec(leaf, n: int, axis: str = "dp") -> P:
    """dim-0 sharding over ``axis`` when it divides evenly, else
    replicated — the layout rule applied uniformly to params AND
    optimizer state so any divisor-sized survivor mesh can host the
    same tree."""
    shape = getattr(leaf, "shape", ())
    if len(shape) >= 1 and shape[0] >= n and shape[0] % n == 0:
        return P(axis)
    return P()


def elastic_shard(tree, mesh, axis: str = "dp"):
    n = int(np.asarray(mesh.devices).size)
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, elastic_spec(x, n, axis))), tree)


def survivor_positions(n: int, dead: Sequence[int]) -> List[int]:
    """The largest divisor-of-n prefix of surviving flat positions: a
    divisor keeps every elastic-sharded dim 0 evenly divisible on the
    smaller mesh (n | dim0 and m | n ⇒ m | dim0)."""
    ds = set(int(p) for p in dead)
    alive = [i for i in range(n) if i not in ds]
    if not alive:
        raise ProcFailedError(-1, "elastic: no survivors left")
    m = max(d for d in range(1, n + 1) if n % d == 0 and d <= len(alive))
    return alive[:m]


def survivor_mesh(mesh, dead: Sequence[int], axis: str = "dp"):
    """Shrink a 1-D mesh to its survivor subset (divisor-sized)."""
    devs = list(np.asarray(mesh.devices).flat)
    keep = survivor_positions(len(devs), dead)
    return make_mesh({axis: len(keep)}, devices=[devs[i] for i in keep])


# ---------------------------------------------------------------------------
# trip classification: any wait-interrupting ft error -> one verdict shape
# ---------------------------------------------------------------------------

def trip_verdict(exc: BaseException) -> Dict[str, Any]:
    """Classify a failure signal into the audited trip verdict.  The
    watchdog arm carries the blocked op's (cid, seq, op) attribution
    plus the desync sentinel's suspect rank when the report named one;
    the detector arm carries the failed rank directly."""
    if isinstance(exc, WatchdogTimeoutError):
        return {"kind": "watchdog", "rank": int(getattr(exc, "suspect", -1)),
                "cid": int(exc.cid), "seq": int(exc.seq), "op": str(exc.op),
                "msg": str(exc)}
    if isinstance(exc, (ProcFailedError, ProcFailedPendingError)):
        return {"kind": "proc_failed", "rank": int(exc.rank),
                "msg": str(exc)}
    return {"kind": "unknown", "rank": -1, "msg": str(exc)}


def comm_recover(comm, verdict: Optional[Dict[str, Any]] = None):
    """The host-plane half of a recovery: ULFM revoke (reliable flood)
    then shrink to the survivor communicator via the agree consensus.
    Returns ``(new_comm, dead_world_ranks, info)``; every survivor gets
    the same cid and membership out of the agreement."""
    try:
        revoke(comm)
    except Exception:
        pass                      # a revoked/failed comm still shrinks
    new_comm = shrink(comm)
    dead = sorted(set(comm.group.world_ranks)
                  - set(new_comm.group.world_ranks))
    info = {"old_cid": int(comm.cid), "cid": int(new_comm.cid),
            "name": new_comm.name,
            "survivors": list(new_comm.group.world_ranks),
            "dead": dead}
    if verdict is not None:
        info["verdict"] = dict(verdict)
    return new_comm, dead, info


# ---------------------------------------------------------------------------
# peer-replicated shadows
# ---------------------------------------------------------------------------

class ShadowStore:
    """In-memory peer-replicated shadows of the training state.

    ``refresh(state, step)`` banks (a) ``snap`` — a private copy of the
    whole tree (the training step donates its inputs, so references
    into the live tree would dangle) and (b) ``shifted`` — each
    dp-sharded leaf pushed one ring hop (+1) by a compiled shard_map
    ppermute, so position ``j`` holds block ``(j-1) % n``.  Dead
    position ``p``'s block is then the ``shifted`` shard resident on
    ``(p+1) % n``."""

    def __init__(self, mesh, axis: str = "dp", spc=None):
        self.mesh = mesh
        self.axis = axis
        self.spc = spc
        self.n = int(np.asarray(mesh.devices).size)
        self.epoch = -1
        self.snap = None
        self.shifted = None
        self._shift_fns: Dict[tuple, Callable] = {}

    def _is_ring_sharded(self, leaf) -> bool:
        s = getattr(leaf, "sharding", None)
        if not isinstance(s, NamedSharding) or self.n < 2:
            return False
        spec = tuple(s.spec)
        return bool(spec) and spec[0] == self.axis

    def _shift(self, leaf):
        key = (tuple(leaf.shape), str(leaf.dtype))
        fn = self._shift_fns.get(key)
        if fn is None:
            n, ax = self.n, self.axis
            perm = [(i, (i + 1) % n) for i in range(n)]
            # comm-lint: disable=CL001 the +1 ring shift IS the shadow-replication scheme (each device parks its block on its ring neighbor), not an engine-dispatchable collective; wire bytes attributed at the eager boundary via note_ppermute (coll ft_shadow) in refresh()
            fn = jax.jit(_compat.shard_map(
                lambda v: lax.ppermute(v, ax, perm=perm),  # comm-lint: disable=CL001 same ring shift, kernel body
                mesh=self.mesh, in_specs=P(ax), out_specs=P(ax)))
            self._shift_fns[key] = fn
        return fn(leaf)

    @staticmethod
    def _copy(leaf):
        if not isinstance(leaf, jax.Array):
            return leaf
        out = jnp.copy(leaf)
        s = getattr(leaf, "sharding", None)
        if s is not None and not out.sharding.is_equivalent_to(s, leaf.ndim):
            out = jax.device_put(out, s)
        return out

    def refresh(self, state, step: int) -> None:
        from .. import traffic
        snap = jax.tree.map(self._copy, state)
        wire = 0
        leaves = 0

        def shadow(leaf):
            nonlocal wire, leaves
            if not self._is_ring_sharded(leaf):
                return leaf       # replicated: snap's live replicas suffice
            leaves += 1
            wire += int(leaf.nbytes) // self.n
            return self._shift(leaf)

        shifted = jax.tree.map(shadow, snap)
        if traffic.enabled and wire and self.n >= 2:
            # the refresh IS a ppermute ring hop: attribute its edges so
            # the conservation invariant covers shadow traffic too
            traffic.note_ppermute(
                self.mesh, self.axis,
                [(i, (i + 1) % self.n) for i in range(self.n)],
                wire, spc=self.spc, coll="ft_shadow")
        self.snap = snap
        self.shifted = shifted
        self.epoch = int(step)
        with _lock:
            _counts["ft_shadow_refreshes"] += 1
        if trace.enabled:
            trace.instant("ft_shadow_refresh", "ft",
                          args={"step": int(step), "leaves": leaves,
                                "wire_bytes": wire, "mesh": self.n})

    def replacement(self, shifted_leaf, dead_pos: int):
        """The single-device array holding dead position ``dead_pos``'s
        block: the shifted leaf's shard on ``(dead_pos+1) % n``."""
        holder = (int(dead_pos) + 1) % self.n
        devs = list(np.asarray(self.mesh.devices).flat)
        for sh in shifted_leaf.addressable_shards:
            if sh.device == devs[holder]:
                return sh.data
        raise ProcFailedError(
            dead_pos, f"elastic: shadow holder position {holder} has no "
                      "resident shard")


# ---------------------------------------------------------------------------
# the trainer
# ---------------------------------------------------------------------------

def default_data_fn(cfg, batch: int = 8):
    """Deterministic per-step token batches: a resumed run replays the
    exact stream an uninterrupted run saw, so post-recovery loss is
    comparable step-for-step."""
    def fn(step: int):
        r = np.random.default_rng(1_000_003 + int(step))
        return jnp.asarray(
            r.integers(0, cfg.vocab, size=(batch, cfg.seq + 1)),
            dtype=jnp.int32)
    return fn


class ElasticTrainer:
    """``make_train_step`` wrapped in the trip → shrink → reshard →
    resume choreography.

    Two planes, independently optional: the DEVICE plane (the 1-D dp
    mesh carrying params/opt/shadows — always present) and the HOST
    plane (``comm=`` a Communicator whose detector-observed failures
    are polled each step and answered with revoke+shrink via
    :func:`comm_recover`).  Without a comm, failure signals arrive as
    exceptions out of the step body — ``ProcFailedError`` (chaos or the
    detector), ``WatchdogTimeoutError`` (a blocked wait's watchdog
    trip), ``ProcFailedPendingError`` — which is what makes the whole
    loop CI-drivable single-controller on the 8-dev CPU mesh."""

    ERRORS = (ProcFailedError, ProcFailedPendingError, WatchdogTimeoutError)

    def __init__(self, cfg, mesh=None, *, axis: str = "dp",
                 learning_rate: float = 1e-3, shadow_interval: int = 4,
                 data_fn: Optional[Callable[[int], jax.Array]] = None,
                 batch: int = 8, comm=None, chaos=None, spc=None,
                 recovery_budget: Optional[int] = None, seed: int = 0):
        from ..models import transformer as _tf
        if mesh is None:
            mesh = make_mesh({axis: len(jax.devices())})
        if tuple(mesh.axis_names) != (axis,):
            raise ValueError(
                "ElasticTrainer needs a 1-D mesh over its data axis "
                f"(got axes {tuple(mesh.axis_names)}, want ({axis!r},))")
        self.cfg = cfg
        self.axis = axis
        self.lr = float(learning_rate)
        self.shadow_interval = max(int(shadow_interval), 1)
        self.recovery_budget = (int(recovery_budget)
                                if recovery_budget is not None
                                else self.shadow_interval)
        self.comm = comm
        self.chaos = chaos
        self.spc = spc
        self.batch = int(batch)
        self.data_fn = data_fn or default_data_fn(cfg, self.batch)
        self._tf = _tf
        self.step = 0
        self.losses: List[tuple] = []          # (step, loss) append log
        self.loss_by_step: Dict[int, float] = {}
        self.recoveries: List[Dict[str, Any]] = []
        params = elastic_shard(
            _tf.init_params(jax.random.key(seed), cfg), mesh, axis)
        self._bind(mesh, params, None)

    # -- mesh (re)binding ---------------------------------------------------

    def _bind(self, mesh, params, opt_state) -> None:
        self.mesh = mesh
        self.n = int(np.asarray(mesh.devices).size)
        init_opt, self._step_fn = self._tf.make_train_step(
            self.cfg, mesh, self.lr)
        if opt_state is None:
            opt_state = elastic_shard(init_opt(params), mesh, self.axis)
        self.params = params
        self.opt_state = opt_state
        self.shadows = ShadowStore(mesh, self.axis, spc=self.spc)

    def _enforce(self, tree):
        """Pin the elastic layout after a step: jit leaves output
        shardings to GSPMD, and a drifted leaf would starve the shadow
        ring.  Equivalent shardings pass through untouched."""
        def fix(x):
            if not isinstance(x, jax.Array):
                return x
            want = NamedSharding(self.mesh,
                                 elastic_spec(x, self.n, self.axis))
            s = getattr(x, "sharding", None)
            if s is not None and s.is_equivalent_to(want, x.ndim):
                return x
            return jax.device_put(x, want)
        return jax.tree.map(fix, tree)

    # -- failure polling (host plane) ---------------------------------------

    def _poll_comm(self) -> None:
        if self.comm is None:
            return
        ctx = self.comm.ctx
        try:
            ctx.engine.progress()
        except Exception:
            pass
        dead = sorted(set(failed_ranks(ctx))
                      & set(self.comm.group.world_ranks))
        if dead:
            raise ProcFailedError(
                dead[0], f"detector: rank {dead[0]} failed")

    # -- the loop -----------------------------------------------------------

    def run(self, n_steps: int) -> "ElasticTrainer":
        target = self.step + int(n_steps)
        while self.step < target:
            try:
                self._poll_comm()
                if (self.shadows.epoch < 0
                        or self.step - self.shadows.epoch
                        >= self.shadow_interval):
                    self.shadows.refresh((self.params, self.opt_state),
                                         self.step)
                if self.chaos is not None:
                    self.chaos.on_step(self, self.step)
                tokens = self.data_fn(self.step)
                p, o, loss = self._step_fn(self.params, self.opt_state,
                                           tokens)
                self.params = self._enforce(p)
                self.opt_state = self._enforce(o)
                val = float(loss)
                self.losses.append((self.step, val))
                self.loss_by_step[self.step] = val
                self.step += 1
            except self.ERRORS as exc:
                self._recover(exc)
        return self

    # -- recovery choreography ----------------------------------------------

    def _recover(self, exc: BaseException) -> None:
        from .. import ckpt as _ckpt
        t0 = time.perf_counter()
        trip_step = self.step
        verdict = trip_verdict(exc)
        reads0 = _ckpt.restore_count()
        if trace.enabled:
            trace.instant("ft_trip", "ft",
                          args=dict(verdict, step=trip_step))
        if self.shadows.epoch < 0 or self.shadows.snap is None:
            raise ProcFailedError(
                verdict.get("rank", -1),
                "elastic: trip before the first shadow epoch — nothing "
                "to recover from (kill injected at step 0?)") from exc
        # 1. host plane: revoke + shrink to the survivor comm
        shrink_info: Dict[str, Any] = {}
        if self.comm is not None:
            new_comm, dead_world, shrink_info = comm_recover(self.comm,
                                                             verdict)
            self.comm = new_comm
            dead_pos = [w for w in dead_world if w < self.n]
        else:
            dead_pos = ([int(verdict["rank"])]
                        if int(verdict.get("rank", -1)) >= 0 else [])
        if not dead_pos:
            raise ProcFailedError(
                -1, "elastic: trip carries no attributable dead rank "
                    f"(verdict {verdict})") from exc
        bad = [p for p in dead_pos if (p + 1) % self.n in dead_pos]
        if bad:
            raise ProcFailedError(
                bad[0], "elastic: adjacent double failure defeats the "
                        f"single-hop shadow ring (dead {sorted(dead_pos)})"
                        " — fall back to checkpoint restore") from exc
        t_shrink = time.perf_counter()
        if trace.enabled:
            trace.instant("ft_shrink", "ft",
                          args=dict(shrink_info, dead=sorted(dead_pos)))
        # 2. device plane: survivor mesh + cross-mesh reshard from shadows
        new_mesh = survivor_mesh(self.mesh, dead_pos, self.axis)
        epoch = self.shadows.epoch
        bytes0 = _reshard.pvar_value("reshard_bytes")
        leaves = 0

        def migrate(snap_leaf, shifted_leaf):
            nonlocal leaves
            if not isinstance(snap_leaf, jax.Array):
                return snap_leaf
            leaves += 1
            new_n = int(np.asarray(new_mesh.devices).size)
            dst = NamedSharding(
                new_mesh, elastic_spec(snap_leaf, new_n, self.axis))
            repl = {}
            if self.shadows._is_ring_sharded(snap_leaf):
                for p in dead_pos:
                    repl[p] = self.shadows.replacement(shifted_leaf, p)
            return _reshard.cross_reshard(
                snap_leaf, dst, dead=dead_pos, replacements=repl,
                spc=self.spc)

        snap_params, snap_opt = self.shadows.snap
        shifted_params, shifted_opt = self.shadows.shifted
        new_params = jax.tree.map(migrate, snap_params, shifted_params)
        new_opt = jax.tree.map(migrate, snap_opt, shifted_opt)
        moved = int(_reshard.pvar_value("reshard_bytes") - bytes0)
        t_reshard = time.perf_counter()
        if trace.enabled:
            trace.instant("ft_reshard", "ft",
                          args={"leaves": leaves, "wire_bytes": moved,
                                "mesh_before": self.n,
                                "mesh_after":
                                    int(np.asarray(new_mesh.devices).size),
                                "epoch_step": epoch})
        # 3. rebind + roll back to the shadow epoch and resume
        old_n = self.n
        self._bind(new_mesh, new_params, new_opt)
        steps_lost = trip_step - epoch
        self.step = epoch
        t_resume = time.perf_counter()
        reads = _ckpt.restore_count() - reads0
        rec = {
            "dead_rank": int(dead_pos[0]), "dead": sorted(dead_pos),
            "kind": verdict["kind"], "verdict": verdict,
            "trip_step": trip_step, "epoch_step": epoch,
            "resume_step": epoch, "steps_lost": steps_lost,
            "budget_steps": self.recovery_budget,
            "mesh_before": old_n, "mesh_after": self.n,
            "survivors": survivor_positions(old_n, dead_pos),
            "leaves": leaves, "wire_bytes": moved, "ckpt_reads": reads,
            "shrink": shrink_info,
            "t_trip_ms": 0.0,
            "t_shrink_ms": round((t_shrink - t0) * 1e3, 3),
            "t_reshard_ms": round((t_reshard - t0) * 1e3, 3),
            "t_resume_ms": round((t_resume - t0) * 1e3, 3),
        }
        with _lock:
            _counts["ft_recoveries"] += 1
            _counts["ft_steps_lost"] += int(steps_lost)
            _recovery_log.append(rec)
            global _last_recovery
            _last_recovery = rec
        self.recoveries.append(rec)
        if trace.enabled:
            trace.decision(
                "ft_recovery", arm="shrink",
                reason=f"{verdict['kind']}:rank{dead_pos[0]}",
                verdict=dict(verdict),
                nbytes=moved, dead_rank=int(dead_pos[0]),
                dead=sorted(dead_pos), survivors=rec["survivors"],
                mesh_before=old_n, mesh_after=self.n,
                steps_lost=steps_lost, resume_step=epoch,
                ckpt_reads=reads, recover_ms=rec["t_resume_ms"])
            trace.instant("ft_resume", "ft",
                          args={"step": epoch, "steps_lost": steps_lost,
                                "mesh": self.n,
                                "recover_ms": rec["t_resume_ms"]})


def run_elastic(cfg, n_steps: int, **kw) -> ElasticTrainer:
    """One-call face: build an :class:`ElasticTrainer` and run it."""
    return ElasticTrainer(cfg, **kw).run(n_steps)
