"""Deterministic fault injection — the CI driver for elastic recovery.

Three injectors, all keyed by explicit (step, rank) coordinates so a
run either reproduces a failure bit-for-bit or doesn't inject at all
(no randomness, no wall-clock coupling):

  * kill-at-step    — single-controller: poison every float shard
                      resident on the victim mesh position (fail-stop:
                      bytes on a dead device are GONE, including its
                      shadow copies) and raise ``ProcFailedError``.
                      Threaded: the victim rank calls ``maybe_die`` and
                      goes silent via ``ft.simulate_failure``.
  * delayed-send    — wrap a rank's transport send with a fixed delay
                      toward (optionally) one destination: watchdog /
                      detector latency-tolerance testing.
  * dropped-revoke  — swallow the first N revoke frames arriving at a
                      rank: exercises the reliable re-flood property
                      (delivery reaches all survivors if any survivor
                      delivers).

Every injection appends an attribution record to ``log`` so tests and
the bench probe can assert exactly what fired where.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..p2p import AM_FT
from .ulfm import ProcFailedError, simulate_failure


def poison_position(tree, mesh, pos: int):
    """Fail-stop a mesh position's resident float shards: every byte it
    held becomes NaN (a dead device's memory is unreadable — any path
    that still consumes it must fail loudly, which is what makes the
    probe's zero-dead-reads assertion real)."""
    devs = list(np.asarray(mesh.devices).flat)
    dev = devs[int(pos)]

    def one(leaf):
        if not isinstance(leaf, jax.Array):
            return leaf
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf
        if dev not in getattr(leaf.sharding, "device_set", ()):
            return leaf
        datas = []
        hit = False
        for sh in leaf.addressable_shards:
            d = sh.data
            if sh.device == dev:
                d = jnp.full_like(d, jnp.nan)
                hit = True
            datas.append(d)
        if not hit:
            return leaf
        return jax.make_array_from_single_device_arrays(
            leaf.shape, leaf.sharding, datas)

    return jax.tree.map(one, tree)


class ChaosMonkey:
    """Holds the injection schedule; one instance drives one scenario."""

    def __init__(self) -> None:
        self._kills: List[tuple] = []      # (step, rank)
        self.log: List[Dict[str, Any]] = []

    # -- kill-at-step -------------------------------------------------------

    def kill_at_step(self, rank: int, step: int) -> "ChaosMonkey":
        self._kills.append((int(step), int(rank)))
        return self

    def on_step(self, trainer, step: int) -> None:
        """Single-controller hook, called by ElasticTrainer at the top
        of every step."""
        for entry in list(self._kills):
            s, r = entry
            if s == int(step):
                self._kills.remove(entry)
                self.kill_now(trainer, r)

    def kill_now(self, trainer, rank: int) -> None:
        """Fail-stop mesh position ``rank``: poison its resident shards
        across ALL live trees (params, opt state, shadow snapshot AND
        shifted shadows — a dead device loses everything it held), then
        raise the failure signal the elastic loop recovers from."""
        mesh = trainer.mesh
        trainer.params = poison_position(trainer.params, mesh, rank)
        trainer.opt_state = poison_position(trainer.opt_state, mesh, rank)
        sh = getattr(trainer, "shadows", None)
        if sh is not None and sh.snap is not None:
            sh.snap = poison_position(sh.snap, mesh, rank)
            sh.shifted = poison_position(sh.shifted, mesh, rank)
        self.log.append({"kind": "kill", "rank": int(rank),
                         "step": int(trainer.step)})
        raise ProcFailedError(
            int(rank), f"chaos: injected kill of mesh position {rank} "
                       f"at step {trainer.step}")

    def maybe_die(self, ctx, step: int) -> bool:
        """Threaded victim hook: when a kill is scheduled for this
        rank/step, go silent (fail-stop) and report True so the rank
        body can park itself."""
        for entry in list(self._kills):
            s, r = entry
            if s == int(step) and r == int(ctx.rank):
                self._kills.remove(entry)
                self.log.append({"kind": "kill", "rank": int(ctx.rank),
                                 "step": int(step)})
                simulate_failure(ctx)
                return True
        return False

    # -- delayed-send -------------------------------------------------------

    def delay_sends(self, ctx, delay_s: float,
                    dst: Optional[int] = None) -> None:
        """Slow this rank's python-side transport sends by ``delay_s``
        (toward ``dst`` only, when given).  Wraps every transport, so
        both ``layer.send`` control frames (heartbeats, revoke, agree —
        the latency this injector exists to stress) and python-path
        payload sends are covered; payloads riding the native shm
        engine's C fragment path are NOT delayed."""
        chaos = self

        for t in ctx.layer.transports:
            def wrapped(to, tag, header, payload=b"", _inner=t.send):
                if dst is None or int(to) == int(dst):
                    chaos.log.append({"kind": "delayed_send",
                                      "rank": int(ctx.rank),
                                      "dst": int(to),
                                      "delay_s": float(delay_s)})
                    time.sleep(delay_s)
                return _inner(to, tag, header, payload)

            t.send = wrapped

    # -- dropped-revoke -----------------------------------------------------

    def drop_revokes(self, ctx, count: int = 1) -> Dict[str, int]:
        """Swallow the first ``count`` revoke frames arriving at this
        rank.  Returns the live drop-budget dict (``state["left"]``
        reaches 0 once the drops fired) so tests can assert the re-flood
        actually had to route around the loss."""
        state = {"left": int(count)}
        chaos = self

        for t in ctx.layer.transports:
            inner = t.dispatch.get(AM_FT)
            if inner is None:
                continue

            def wrapped(src, h, payload, _inner=inner):
                if h.get("k") == "revoke" and state["left"] > 0:
                    state["left"] -= 1
                    chaos.log.append({"kind": "dropped_revoke",
                                      "rank": int(ctx.rank),
                                      "src": int(src)})
                    return
                _inner(src, h, payload)

            t.dispatch[AM_FT] = wrapped
        return state
