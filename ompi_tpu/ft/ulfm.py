"""ULFM operations: revoke / shrink / agree + failure error classes.

≙ ompi/mpiext/ftmpi (MPIX_Comm_revoke / MPIX_Comm_shrink / MPIX_Comm_agree)
with the revoke propagation of comm_ft_revoke.c and a rotating-coordinator
agreement with decided-value adoption (the reference's ftagree implements
ERA consensus; this protocol gives the same uniformity guarantee under
fail-stop failures with an accurate detector, and is documented as such).
"""

from __future__ import annotations

import time
from typing import Optional, Set

from ..p2p import transport as T



class ProcFailedError(RuntimeError):
    """≙ MPIX_ERR_PROC_FAILED."""

    def __init__(self, rank: int, msg: str = "") -> None:
        super().__init__(msg or f"peer rank {rank} has failed")
        self.rank = rank


class ProcFailedPendingError(RuntimeError):
    """≙ MPIX_ERR_PROC_FAILED_PENDING: an ANY_SOURCE receive was interrupted
    by a peer failure but REMAINS active — after ``failure_ack`` it can
    still complete from a surviving sender (docs/features/ulfm.rst:20-60)."""

    def __init__(self, rank: int) -> None:
        super().__init__(
            f"ANY_SOURCE receive interrupted: rank {rank} failed "
            f"(request still active; failure_ack() to resume)")
        self.rank = rank


class RevokedError(RuntimeError):
    """≙ MPIX_ERR_REVOKED."""

    def __init__(self, comm_name: str = "comm") -> None:
        super().__init__(f"communicator {comm_name} has been revoked")


class WatchdogTimeoutError(RuntimeError):
    """Raised out of a blocked wait's progress loop when the health
    watchdog trips with ``health_watchdog_action=raise`` — the in-flight
    op exceeded its timeout envelope (ompi_tpu/health).  Lives in the
    ft error family: like ProcFailedError it interrupts a wait that
    would otherwise never return, and the trip also publishes a
    control-plane event the way the failure detector announces deaths."""

    def __init__(self, msg: str, *, cid: int = -1, seq: int = -1,
                 op: str = "", suspect: int = -1) -> None:
        super().__init__(msg)
        self.cid = int(cid)
        self.seq = int(seq)
        self.op = str(op)
        # suspect rank when the trip evidence names one (detector-
        # declared failure, else the desync sentinel's laggard); -1 =
        # unattributed — ft/elastic.trip_verdict consumes this
        self.suspect = int(suspect)


def enable(ctx) -> "FailureDetector":
    """Start the failure detector for this rank (idempotent)."""
    from .detector import FailureDetector
    det = getattr(ctx, "_ft_detector", None)
    if det is None:
        det = FailureDetector(ctx)
        ctx._ft_detector = det
    return det


def failed_ranks(ctx) -> Set[int]:
    return set(getattr(ctx, "failed", set()))


def simulate_failure(ctx) -> None:
    """Test hook: this rank goes silent — stops heartbeats and stops serving
    traffic (fail-stop). The observation ring then detects it."""
    det = getattr(ctx, "_ft_detector", None)
    if det is not None:
        det.stop()
    for t in ctx.layer.transports:
        t.dispatch.clear()          # stop serving all AMs (silent process)
        t.send = lambda *a, **kw: None   # and stop emitting


# -- revoke -----------------------------------------------------------------

def _mark_revoked(ctx, cid: int, flood: bool) -> None:
    comms = getattr(ctx, "_ft_comms", {})
    comm = comms.get(cid)
    if comm is None or comm.revoked:
        return
    comm.revoked = True
    if flood:
        _flood_revoke(ctx, comm)


def _flood_revoke(ctx, comm) -> None:
    for r in comm.group.world_ranks:
        if r != ctx.rank and r not in getattr(ctx, "failed", set()):
            try:
                ctx.layer.send(r, T.AM_FT, {"k": "revoke", "cid": comm.cid}, b"")
            except Exception:
                pass


def revoke(comm) -> None:
    """MPIX_Comm_revoke: mark locally, flood reliably (every receiver
    re-floods once — comm_ft_revoke.c's reliable bcast property: delivery
    reaches all survivors if any survivor delivers)."""
    ctx = comm.ctx
    enable(ctx)
    if comm.revoked:
        return
    comm.revoked = True
    _flood_revoke(ctx, comm)


# -- failure interaction with pending communication -------------------------

def _fail_pending_recvs(ctx, failed_rank: int) -> None:
    """Complete posted receives naming the failed rank with ProcFailedError
    (ULFM: ops involving a failed process must not hang). ANY_SOURCE
    receives on communicators containing the failed rank get
    MPIX_ERR_PROC_FAILED_PENDING semantics instead: the wait raises
    ProcFailedPendingError once but the receive stays posted, and after
    ``failure_ack`` it completes normally from surviving senders — matching
    the reference (docs/features/ulfm.rst:20-60). Already-acked failures
    don't re-interrupt."""
    comms = getattr(ctx, "_ft_comms", {})
    cids = frozenset(
        cid for cid, c in comms.items()
        if failed_rank in c.group.world_ranks
        and failed_rank not in getattr(c, "_ft_acked", set()))
    ctx.p2p.matching.fail_src(
        failed_rank, ProcFailedError(failed_rank), any_source_cids=cids,
        pending_err=ProcFailedPendingError(failed_rank))
    # in-flight operations too: rndv sends awaiting the corpse's ACK/FIN
    # and fragment trains it was streaming (round-3 verdict item 10 — the
    # C++-engine paths the posted-recv sweep above cannot reach)
    ctx.p2p.fail_peer(failed_rank, ProcFailedError(failed_rank))


def failure_ack(comm) -> None:
    """MPIX_Comm_failure_ack: acknowledge all currently-known failures on
    this communicator; ANY_SOURCE receives are no longer interrupted by
    (and won't re-report) the acknowledged failures."""
    comm._ft_acked = set(failed_ranks(comm.ctx))


def failure_get_acked(comm):
    """MPIX_Comm_failure_get_acked: the group of acknowledged failed
    ranks."""
    from ..comm import Group
    return Group(sorted(getattr(comm, "_ft_acked", set())))


def check_peer(ctx, world_rank: int) -> None:
    if world_rank in getattr(ctx, "failed", set()):
        raise ProcFailedError(world_rank)


# -- agreement (coordinator-based, ≙ ompi/mca/coll/ftagree) -----------------
#
# MPIX_Comm_agree must return the SAME value on every rank that returns
# (uniformity), even when ranks fail mid-operation. A plain all-to-all
# cannot give that (rank P may deliver its flag to A but die before reaching
# B). The reference's ftagree implements ERA consensus; here: a rotating
# coordinator protocol with decided-value adoption —
#
#   * the lowest-ranked alive member coordinates: gathers contributions
#     (flag + known-failed set + cid proposal) from every alive member,
#     computes the decision, broadcasts it;
#   * a member waiting on a coordinator that the detector declares failed
#     re-elects the next-lowest and starts over;
#   * a new coordinator first *pulls*: any rank that already holds a
#     decision for this (cid, seq) answers with the decided result, which
#     the new coordinator adopts verbatim instead of recomputing.
#
# Uniform under fail-stop failures with an accurate detector (the heartbeat
# ring, detector.py): two different decisions would require a coordinator to
# be declared failed while still delivering results, which accuracy rules
# out. The decision also carries the agreed failed-set and the agreed next
# communicator id, so shrink() gets a uniform survivor list and a collision-
# free cid from the same decision.


class _AgState:
    """Per-context agreement state, serviced from the AM handler so ranks
    that already returned can still answer pulls."""

    def __init__(self) -> None:
        self.results: dict = {}    # (cid, seq) -> decided result frame
        self.contribs: dict = {}   # (cid, seq) -> {world_rank: contrib frame}
        self.mine: dict = {}       # (cid, seq) -> this rank's contribution


def _ag_state(ctx) -> _AgState:
    st = getattr(ctx, "_ag_state", None)
    if st is None:
        st = _AgState()
        ctx._ag_state = st
    return st


def handle_ag(ctx, src: int, h: dict) -> None:
    """Agreement AM dispatch (called from the detector's AM handler)."""
    st = _ag_state(ctx)
    key = (int(h["cid"]), int(h["seq"]))
    k = h["k"]
    if k == "ag_c":                     # a member's contribution
        st.contribs.setdefault(key, {})[src] = h
        # Liveness: if this key is already decided here (e.g. this rank
        # coordinated, returned from agree(), and a waiter just re-elected
        # us after the old coordinator died), nothing will run _coordinate
        # again — answer with the decided frame directly so the waiter's
        # decided-value adoption path actually fires.
        if key in st.results:
            try:
                ctx.layer.send(src, T.AM_FT, st.results[key], b"")
            except Exception:
                pass
    elif k == "ag_r":                   # a coordinator's decision
        st.results[key] = h
    elif k == "ag_p":                   # pull from a (new) coordinator
        if key in st.results:
            reply = st.results[key]
        elif key in st.mine:
            reply = st.mine[key]
        else:
            return                      # not entered yet; coordinator re-pulls
        try:
            ctx.layer.send(src, T.AM_FT, reply, b"")
        except Exception:
            pass


def _agreement(comm, flag: int) -> dict:
    """Run one agreement instance; returns the decided frame
    {value, failed, cid_next} applied uniformly on every returning rank."""
    ctx = comm.ctx
    enable(ctx)
    st = _ag_state(ctx)
    seq = getattr(comm, "_ag_seq", 0)
    comm._ag_seq = seq + 1
    key = (comm.cid, seq)
    members = list(comm.group.world_ranks)
    mine = {"k": "ag_c", "cid": comm.cid, "seq": seq, "flag": int(flag),
            "failed": sorted(int(f) for f in getattr(ctx, "failed", set())),
            "cidprop": int(comm._cid_counter)}
    st.mine[key] = mine
    deadline = time.monotonic() + 120.0
    sent_to = None
    result = None
    while result is None:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"agreement on {comm.name}: no decision within 120s")
        alive = [w for w in members if w not in ctx.failed]
        coord = alive[0]
        if coord == ctx.rank:
            result = _coordinate(comm, key, members)
        else:
            if sent_to != coord:        # (re-)submit to the current coordinator
                try:
                    ctx.layer.send(coord, T.AM_FT, mine, b"")
                except Exception:
                    pass
                sent_to = coord
            ctx.engine.progress()
            result = st.results.get(key)
            # coordinator died undecided → loop re-elects
    st.results[key] = result
    # apply the uniform knowledge
    if not hasattr(ctx, "failed"):
        ctx.failed = set()
    ctx.failed.update(int(f) for f in result["failed"])
    comm._cid_counter = max(comm._cid_counter, int(result["cid_next"]))
    st.contribs.pop(key, None)
    return result


def _coordinate(comm, key, members) -> dict:
    """Coordinator body: adopt any existing decision, else gather from all
    alive members, decide, broadcast."""
    ctx = comm.ctx
    st = _ag_state(ctx)
    cid, seq = key
    last_pull = 0.0
    deadline = time.monotonic() + 60.0
    while True:
        contribs = st.contribs.setdefault(key, {})
        contribs[ctx.rank] = st.mine[key]
        alive = [w for w in members if w not in ctx.failed]
        decided = st.results.get(key)
        if decided is None and all(w in contribs for w in alive):
            flags_and = ~0
            failed = set(int(f) for f in getattr(ctx, "failed", set()))
            cid_next = int(comm._cid_counter)
            for w in alive:
                c = contribs[w]
                flags_and &= int(c["flag"])
                failed.update(int(f) for f in c["failed"])
                cid_next = max(cid_next, int(c["cidprop"]))
            failed.update(w for w in members if w not in alive)
            decided = {"k": "ag_r", "cid": cid, "seq": seq,
                       "value": int(flags_and),
                       "failed": sorted(f for f in failed if f in members),
                       "cid_next": cid_next}
        if decided is not None:
            for w in alive:
                if w != ctx.rank:
                    try:
                        ctx.layer.send(w, T.AM_FT, decided, b"")
                    except Exception:
                        pass
            return decided
        now = time.monotonic()
        if now > deadline:
            raise TimeoutError(
                f"agreement on {comm.name}: coordinator gathered "
                f"{sorted(contribs)} of {alive} within 60s")
        if now - last_pull > 0.05:
            last_pull = now
            for w in alive:
                if w != ctx.rank and w not in contribs:
                    try:
                        ctx.layer.send(
                            w, T.AM_FT,
                            {"k": "ag_p", "cid": cid, "seq": seq}, b"")
                    except Exception:
                        pass
        ctx.engine.progress()


def agree(comm, flag: int) -> int:
    """MPIX_Comm_agree: uniform bitwise AND of ``flag`` over surviving
    ranks (ompi/mpiext/ftmpi semantics)."""
    return int(_agreement(comm, int(flag))["value"])


# -- shrink -----------------------------------------------------------------

def shrink(comm, name: Optional[str] = None):
    """MPIX_Comm_shrink: agree (uniformly) on the failed set and return a
    new communicator of the survivors, same relative rank order. The new
    cid comes out of the same agreement, drawn from the parent's shared cid
    counter (the allocator split() uses), so it cannot collide with split
    children."""
    ctx = comm.ctx
    res = _agreement(comm, ~0)
    failed = set(int(f) for f in res["failed"])
    survivors = [w for w in comm.group.world_ranks if w not in failed]
    cid = int(res["cid_next"])
    comm._cid_counter = max(comm._cid_counter, cid + 1)   # consume it
    from ..comm import Communicator, Group
    return Communicator(ctx, Group(survivors), cid,
                        name or f"{comm.name}.shrink")
