"""ULFM operations: revoke / shrink / agree + failure error classes.

≙ ompi/mpiext/ftmpi (MPIX_Comm_revoke / MPIX_Comm_shrink / MPIX_Comm_agree)
with the revoke propagation of comm_ft_revoke.c and a simplified agreement
(the reference's ftagree implements ERA consensus; here agreement is an
all-to-all exchange with failure-detector-backed timeouts — weaker than ERA
under partitions, sufficient for fail-stop ranks, and documented as such).
"""

from __future__ import annotations

import time
from typing import Optional, Set

import numpy as np

from ..p2p import transport as T
from ..p2p.request import ANY_SOURCE

# reserved tag space for FT internals (user ≥ 0, coll -100.., nbc -200..)
T_SHRINK = -1001
T_AGREE = -1002


class ProcFailedError(RuntimeError):
    """≙ MPIX_ERR_PROC_FAILED."""

    def __init__(self, rank: int, msg: str = "") -> None:
        super().__init__(msg or f"peer rank {rank} has failed")
        self.rank = rank


class RevokedError(RuntimeError):
    """≙ MPIX_ERR_REVOKED."""

    def __init__(self, comm_name: str = "comm") -> None:
        super().__init__(f"communicator {comm_name} has been revoked")


def enable(ctx) -> "FailureDetector":
    """Start the failure detector for this rank (idempotent)."""
    from .detector import FailureDetector
    det = getattr(ctx, "_ft_detector", None)
    if det is None:
        det = FailureDetector(ctx)
        ctx._ft_detector = det
    return det


def failed_ranks(ctx) -> Set[int]:
    return set(getattr(ctx, "failed", set()))


def simulate_failure(ctx) -> None:
    """Test hook: this rank goes silent — stops heartbeats and stops serving
    traffic (fail-stop). The observation ring then detects it."""
    det = getattr(ctx, "_ft_detector", None)
    if det is not None:
        det.stop()
    for t in ctx.layer.transports:
        t.dispatch.clear()          # stop serving all AMs (silent process)
        t.send = lambda *a, **kw: None   # and stop emitting


# -- revoke -----------------------------------------------------------------

def _mark_revoked(ctx, cid: int, flood: bool) -> None:
    comms = getattr(ctx, "_ft_comms", {})
    comm = comms.get(cid)
    if comm is None or comm.revoked:
        return
    comm.revoked = True
    if flood:
        _flood_revoke(ctx, comm)


def _flood_revoke(ctx, comm) -> None:
    for r in comm.group.world_ranks:
        if r != ctx.rank and r not in getattr(ctx, "failed", set()):
            try:
                ctx.layer.send(r, T.AM_FT, {"k": "revoke", "cid": comm.cid}, b"")
            except Exception:
                pass


def revoke(comm) -> None:
    """MPIX_Comm_revoke: mark locally, flood reliably (every receiver
    re-floods once — comm_ft_revoke.c's reliable bcast property: delivery
    reaches all survivors if any survivor delivers)."""
    ctx = comm.ctx
    enable(ctx)
    _track(comm)
    if comm.revoked:
        return
    comm.revoked = True
    _flood_revoke(ctx, comm)


def _track(comm) -> None:
    """Register comm for revoke-by-cid lookup from the AM handler."""
    ctx = comm.ctx
    if not hasattr(ctx, "_ft_comms"):
        ctx._ft_comms = {}
    ctx._ft_comms[comm.cid] = comm


# -- failure interaction with pending communication -------------------------

def _fail_pending_recvs(ctx, failed_rank: int) -> None:
    """Complete posted receives naming the failed rank with ProcFailedError
    (ULFM: ops involving a failed process must not hang)."""
    ctx.p2p.matching.fail_src(failed_rank, ProcFailedError(failed_rank))


def check_peer(ctx, world_rank: int) -> None:
    if world_rank in getattr(ctx, "failed", set()):
        raise ProcFailedError(world_rank)


# -- shrink -----------------------------------------------------------------

def shrink(comm, name: Optional[str] = None):
    """MPIX_Comm_shrink: agree on the failed set, return a new communicator
    of the survivors (same relative rank order)."""
    ctx = comm.ctx
    enable(ctx)
    # agreement over the failed set: exchange bitmaps until consensus
    failed = _agree_failed_set(comm)
    survivors = [w for w in comm.group.world_ranks if w not in failed]
    from ..comm import Communicator, Group
    # deterministic CID: survivors all derive the same child id
    seq = getattr(comm, "_shrink_seq", 0)
    comm._shrink_seq = seq + 1
    cid = (comm.cid + 1) * 4096 + 512 + seq
    newcomm = Communicator(ctx, Group(survivors), cid,
                           name or f"{comm.name}.shrink")
    _track(newcomm)
    return newcomm


def _agree_failed_set(comm) -> Set[int]:
    """All-to-all exchange of locally-known failed sets with timeouts; two
    sweeps so second-hand knowledge converges (fail-stop model)."""
    ctx = comm.ctx
    # exactly two sweeps on every rank — an early exit would desynchronize
    # the per-instance exchange tags across ranks and deadlock
    for _ in range(2):
        known = np.zeros(ctx.size, np.int8)
        for f in getattr(ctx, "failed", set()):
            known[f] = 1
        gathered = _exchange(comm, known, T_SHRINK)
        merged = np.clip(np.sum(gathered, axis=0), 0, 1)
        ctx.failed.update(int(i) for i in np.nonzero(merged)[0])
    return set(int(i) for i in np.nonzero(merged)[0])


# -- agreement --------------------------------------------------------------

def agree(comm, flag: int) -> int:
    """MPIX_Comm_agree: returns the bitwise AND of ``flag`` over surviving
    ranks; uniform among survivors under fail-stop failures."""
    ctx = comm.ctx
    enable(ctx)
    mine = np.array([flag, 0], np.int64)
    rows = _exchange(comm, mine, T_AGREE)
    out = ~np.int64(0)
    for row in rows:
        out &= np.int64(row[0])
    return int(out)


def _exchange(comm, vec: np.ndarray, tag: int):
    """All-to-all with per-peer failure awareness: sends to everyone, waits
    for each peer until it answers or is declared failed. Needs the failure
    detector running (enable()) so dead peers eventually time out."""
    ctx = comm.ctx
    seq = getattr(comm, "_ft_xchg_seq", 0)
    comm._ft_xchg_seq = seq + 1
    xtag = tag - 10 * (seq % 90)       # per-instance tag isolation
    rows = [None] * comm.size
    rows[comm.rank] = vec.copy()
    reqs = {}
    for r in range(comm.size):
        w = comm.group.world_of_rank(r)
        if r == comm.rank or w in getattr(ctx, "failed", set()):
            continue
        inbox = np.zeros_like(vec)
        reqs[r] = (comm.irecv(inbox, r, xtag), inbox)
        comm.isend(vec, r, xtag)
    deadline = time.monotonic() + 30.0
    pending = dict(reqs)
    while pending:
        for r in list(pending):
            req, inbox = pending[r]
            w = comm.group.world_of_rank(r)
            if req.done:
                if req.error is None:
                    rows[r] = inbox.copy()
                del pending[r]
            elif w in getattr(ctx, "failed", set()):
                del pending[r]       # declared dead while we waited
        if pending:
            ctx.engine.progress()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"ft exchange: no progress and no failure verdict for "
                    f"peers {sorted(pending)}")
    return [r for r in rows if r is not None]
