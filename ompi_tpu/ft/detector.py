"""Heartbeat-ring failure detector (≙ comm_ft_detector.c:49-86).

Design kept from the reference: ranks form an observation ring — each rank
*emits* heartbeats to its right neighbor and *observes* its left neighbor;
an observer that sees no heartbeat for ``timeout`` declares the observed
rank dead and floods the verdict. The reference runs this off the progress
engine with RDMA-put or send heartbeats and configurable period/timeout
(detector period/timeout MCA vars); here it is a low-priority progress
callback over the AM_FT active-message channel.

On detection:
  * the failed rank joins ``ctx.failed`` everywhere (flooded reliably);
  * pending receives posted from that rank — and ANY_SOURCE receives on
    communicators containing it — complete with ProcFailedError
    (matching.fail_src, driven by ulfm._fail_pending_recvs);
  * a bootstrap event is published for RTE-level observers
    (≙ PMIx event handler registration, instance.c:440-466).

When a rank's transport reports send failures to a peer (tcp failed_peers),
the observer treats that as immediate evidence rather than waiting for the
timeout.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Set

from ..core import var as _var
from ..core.output import output
from ..p2p import transport as T

_var.register("ft", "detector", "period", 0.05, type=float, level=4,
              help="Heartbeat emission period, seconds "
                   "(≙ mpi_ft_detector_period).")
_var.register("ft", "detector", "timeout", 0.5, type=float, level=4,
              help="Silence after which the observed rank is declared dead "
                   "(≙ mpi_ft_detector_timeout).")


class FailureDetector:
    """One per Context; started by ft.enable()."""

    def __init__(self, ctx) -> None:
        self.ctx = ctx
        self.period = float(_var.get("ft_detector_period", 0.05))
        self.timeout = float(_var.get("ft_detector_timeout", 0.5))
        self.rank = ctx.rank
        self.size = ctx.size
        # heartbeat ring runs over THIS job's world ranks (a spawned child
        # job observes its own members, not the parents' global rank space)
        self.members = list(getattr(ctx, "world_ranks", range(ctx.size)))
        self._pos = self.members.index(ctx.rank)
        self._alive = True
        self._lock = threading.Lock()
        if not hasattr(ctx, "failed"):
            ctx.failed = set()
        self.failed: Set[int] = ctx.failed
        now = time.monotonic()
        self._last_emit = 0.0
        self._last_seen: Dict[int, float] = {}
        self._grace_until = now + self.timeout   # startup grace period
        for t in ctx.layer.transports:
            t.dispatch[T.AM_FT] = self._am_handler
        ctx.engine.register(self._progress, low_priority=True)
        self._on_failure = []      # callbacks(rank)

    # ring neighbors skip already-dead ranks

    def _observed(self) -> Optional[int]:
        i = (self._pos - 1) % self.size
        while i != self._pos:
            if self.members[i] not in self.failed:
                return self.members[i]
            i = (i - 1) % self.size
        return None

    def _emit_to(self) -> Optional[int]:
        i = (self._pos + 1) % self.size
        while i != self._pos:
            if self.members[i] not in self.failed:
                return self.members[i]
            i = (i + 1) % self.size
        return None

    def add_failure_callback(self, cb) -> None:
        self._on_failure.append(cb)

    def stop(self) -> None:
        self._alive = False
        self.ctx.engine.unregister(self._progress)

    # -- progression ---------------------------------------------------------

    def _progress(self) -> int:
        if not self._alive or self.size == 1:
            return 0
        now = time.monotonic()
        if now - self._last_emit >= self.period:
            self._last_emit = now
            to = self._emit_to()
            if to is not None:
                try:
                    self.ctx.layer.send(to, T.AM_FT, {"k": "hb"}, b"")
                except Exception:
                    pass    # send failure surfaces via transport failed_peers
        obs = self._observed()
        if obs is None:
            return 0
        seen = self._last_seen.get(obs)
        deadline = (seen if seen is not None else self._grace_until)
        # transport-level send failure to the observed peer = hard evidence
        hard = any(obs in getattr(t, "failed_peers", ())
                   for t in self.ctx.layer.transports)
        if hard or now - deadline > self.timeout:
            self._declare_failed(obs, local=True)
        return 0

    def _am_handler(self, src: int, h: Dict[str, Any], payload: bytes) -> None:
        k = h["k"]
        if k == "hb":
            self._last_seen[src] = time.monotonic()
        elif k == "failed":
            self._declare_failed(int(h["rank"]), local=False)
        elif k == "revoke":
            from .ulfm import _mark_revoked
            _mark_revoked(self.ctx, int(h["cid"]), flood=True)
        elif k in ("ag_c", "ag_r", "ag_p"):
            from .ulfm import handle_ag
            handle_ag(self.ctx, src, h)
        else:  # pragma: no cover
            output.verbose(1, "ft", f"unknown ft frame {k!r} from {src}")

    def _declare_failed(self, rank: int, local: bool) -> None:
        with self._lock:
            if rank in self.failed or rank == self.rank:
                return
            self.failed.add(rank)
        output.verbose(1, "ft", f"rank {self.rank}: declaring {rank} FAILED")
        # a newly observed peer gets a fresh grace window
        self._grace_until = time.monotonic() + self.timeout
        # reliable flood on FIRST learn, local or relayed — every first-time
        # receiver re-floods once, the same property the revoke path has
        # (≙ comm_ft_propagator reliable bcast: reaches all survivors if any
        # survivor delivers, even when the original detector dies mid-flood)
        for r in self.members:
            if r not in self.failed and r != self.rank:
                try:
                    self.ctx.layer.send(r, T.AM_FT,
                                        {"k": "failed", "rank": rank}, b"")
                except Exception:
                    pass
        if local:
            try:
                self.ctx.bootstrap.publish_event(
                    {"kind": "proc_failed", "rank": rank})
            except Exception:
                pass
        from .ulfm import _fail_pending_recvs
        _fail_pending_recvs(self.ctx, rank)
        for cb in self._on_failure:
            # a raising callback must not kill the progress loop — the
            # detector IS the recovery path's eyes; swallow with
            # attribution (callback name + failed rank) instead
            try:
                cb(rank)
            except Exception as err:
                name = getattr(cb, "__qualname__",
                               getattr(cb, "__name__", repr(cb)))
                output.verbose(
                    1, "ft",
                    f"rank {self.rank}: failure callback {name} raised "
                    f"{type(err).__name__} for failed rank {rank}: {err}")
                from .. import trace
                if trace.enabled:
                    trace.instant(
                        "ft_callback_error", "ft", rank=self.rank,
                        args={"callback": name, "failed_rank": int(rank),
                              "error": f"{type(err).__name__}: {err}"})
