"""Fault tolerance — ULFM-style failure detection and recovery.

≙ the reference's ULFM stack (docs/features/ulfm.rst:20-60, compiled under
OPAL_ENABLE_FT_MPI):
  * heartbeat-ring failure detector    ≙ ompi/communicator/ft/comm_ft_detector.c:49-86
  * reliable revoke propagation        ≙ ompi/communicator/ft/comm_ft_revoke.c
  * shrink (drop failed ranks)         ≙ ompi/communicator/ft/comm_ft.c shrink
  * agreement (FT consensus)           ≙ ompi/mca/coll/ftagree
  * error classes PROC_FAILED/REVOKED  ≙ MPIX_ERR_PROC_FAILED / MPIX_ERR_REVOKED

TPU-first note: on a pod, in-slice chip failure takes down the whole XLA
program — the unit of failure is the *slice/host*, detected here over the
DCN control plane exactly where the reference detects peer processes over
its RTE. Recovery composes with checkpointing (ompi_tpu.ckpt): detect →
revoke → shrink → rebuild mesh from survivors → restore.
"""

from .ulfm import (  # noqa: F401
    ProcFailedError,
    ProcFailedPendingError,
    RevokedError,
    agree,
    enable,
    failed_ranks,
    failure_ack,
    failure_get_acked,
    revoke,
    shrink,
    simulate_failure,
)
from .ulfm import WatchdogTimeoutError  # noqa: F401
from .detector import FailureDetector  # noqa: F401
from .chaos import ChaosMonkey  # noqa: F401
from .elastic import (  # noqa: F401
    ElasticTrainer,
    ShadowStore,
    comm_recover,
    run_elastic,
    survivor_mesh,
    trip_verdict,
)

__all__ = [
    "ProcFailedError", "ProcFailedPendingError", "RevokedError",
    "WatchdogTimeoutError",
    "FailureDetector", "enable", "revoke", "shrink", "agree", "failed_ranks",
    "failure_ack", "failure_get_acked", "simulate_failure",
    "ChaosMonkey", "ElasticTrainer", "ShadowStore", "comm_recover",
    "run_elastic", "survivor_mesh", "trip_verdict",
]
