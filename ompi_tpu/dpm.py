"""Dynamic process management: spawn / open_port / connect / accept.

≙ ompi/dpm/dpm.c — the MPI-2 dynamic-process chapter, built on the control
plane the way the reference builds on PMIx:

  * ``spawn``: the parent communicator collectively launches ``maxprocs``
    new processes. The coordinator reserves a block of new GLOBAL ranks in
    its own fence group (Coordinator GROW ≙ PMIx_Spawn's slot request), the
    root fork/execs the children with the standard env contract plus
    WORLD_BASE/WORLD_SIZE (children get their OWN COMM_WORLD — MPI
    semantics), every parent widens its transports to the grown rank space,
    and both sides assemble the same intercommunicator; children reach it
    via :func:`get_parent`.
  * ``open_port``/``connect``/``accept``: client/server rendezvous WITHIN a
    running global rank space (two disjoint communicators of the same job
    or of a parent+spawned-job family), carried over control-plane events —
    the reference's ports are PMIx-published strings the same way
    (dpm.c MPI_Open_port). Cross-launcher connects (two independent tpurun
    invocations) are out of scope: their rank spaces collide by
    construction, exactly why the reference needs a PMIx server mesh there.

Sequencing guarantee for shm: ring creators are receivers, so children may
only send to parents after every parent ran ``add_peers``; spawn's root
publishes the ``dpm_ready`` key after the parent-side barrier, and
``get_parent`` blocks on it before returning.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional, Sequence

import numpy as np

from .comm import Communicator, Group

_SPAWN_CID_BASE = 1 << 44        # intercomm cids for spawn, out of all ranges
_PORT_CID_BASE = 1 << 45         # intercomm cids for connect/accept


def spawn(comm: Communicator, command: Sequence[str], maxprocs: int,
          root: int = 0, env_extra: Optional[dict] = None,
          info=None) -> Communicator:
    """MPI_Comm_spawn: collective over ``comm``; returns the parent side of
    the parent↔children intercommunicator. Honored MPI_Info hints: ``wdir``
    (children's working directory), ``path`` (prepended to the child's
    PATH); others are advisory."""
    ctx = comm.ctx
    if comm.rank == root:
        base, gid = ctx.bootstrap.grow(maxprocs)
        meta = np.array([base, gid], np.int64)
    else:
        meta = np.zeros(2, np.int64)
    meta = np.asarray(comm.coll.bcast(comm, meta, root=root))
    base, gid = int(meta[0]), int(meta[1])
    total = base + maxprocs
    children = list(range(base, base + maxprocs))

    ok = np.zeros(1, np.int64)
    if comm.rank == root:
        try:
            cmd = list(command)
            if cmd[0].endswith(".py"):
                cmd = [sys.executable] + cmd
            coord = ctx.bootstrap.coord_address
            for i, child in enumerate(children):
                env = dict(os.environ)
                # chip and CPU binding do NOT inherit: the children are a
                # new job placement the caller controls via env_extra
                # (≙ the MPI_Info keys of MPI_Comm_spawn) — inheriting the
                # parent's cpuset would pile every child onto one core
                env.pop("TPU_VISIBLE_DEVICES", None)
                env.pop("OMPI_TPU_BIND_CPUS", None)
                if env_extra:
                    env.update(env_extra)
                env.update({
                    "OMPI_TPU_RANK": str(child),
                    "OMPI_TPU_SIZE": str(total),
                    "OMPI_TPU_COORD": f"{coord[0]}:{coord[1]}",
                    "OMPI_TPU_JOB": ctx.bootstrap.job_id,
                    "OMPI_TPU_LOCAL_RANK": str(i),
                    "OMPI_TPU_NUM_LOCAL": str(maxprocs),
                    "OMPI_TPU_WORLD_BASE": str(base),
                    "OMPI_TPU_WORLD_SIZE": str(maxprocs),
                    "OMPI_TPU_SPAWN_GROUP": str(gid),
                    "OMPI_TPU_PARENT_RANKS": ",".join(
                        map(str, comm.group.world_ranks)),
                    "OMPI_TPU_PARENT_ROOT": str(
                        comm.group.world_of_rank(root)),
                    "OMPI_TPU_PARENT_CID": str(_SPAWN_CID_BASE | gid),
                })
                wdir = info.get("wdir") if info is not None else None
                if info is not None and info.get("path"):
                    env["PATH"] = (info.get("path") + os.pathsep
                                   + env.get("PATH", ""))
                subprocess.Popen(cmd, env=env, cwd=wdir)
            # children's ring-ready keys appear once their shm rx rings
            # exist; waiting here closes the add_peers/first-send race
            # (only the shm transport publishes them)
            if any(t.name == "shm" for t in ctx.layer.transports):
                for child in children:
                    ctx.bootstrap.get(child, "transport_shm_rings",
                                      timeout=60.0)
            ok[0] = 1
        except Exception as exc:   # surface collectively, not a hang
            ok[0] = 0
            err = exc
    ok = np.asarray(comm.coll.bcast(comm, ok, root=root))
    if not int(ok[0]):
        if comm.rank == root:
            raise RuntimeError(f"spawn failed to launch: {err!r}") from err
        raise RuntimeError("spawn failed to launch (see root rank)")
    comm.coll.barrier(comm)
    ctx.layer.add_peers(total)       # every parent can now serve children
    comm.coll.barrier(comm)
    if comm.rank == root:
        ctx.bootstrap.put(f"dpm_ready:{gid}", True)   # children may send
    return comm._inherit(Communicator(
        ctx, Group(list(comm.group.world_ranks)), _SPAWN_CID_BASE | gid,
        f"{comm.name}.spawn{gid}", remote_group=Group(children),
        local_comm=comm))


def get_parent(ctx) -> Optional[Communicator]:
    """MPI_Comm_get_parent: on a spawned child, the child side of the spawn
    intercommunicator (None in a non-spawned process). Blocks until the
    parents finished widening their transports."""
    ranks = os.environ.get("OMPI_TPU_PARENT_RANKS")
    if not ranks:
        return None
    gid = int(os.environ.get("OMPI_TPU_SPAWN_GROUP", "0"))
    parents = [int(r) for r in ranks.split(",")]
    spawn_root = int(os.environ.get("OMPI_TPU_PARENT_ROOT", parents[0]))
    ctx.bootstrap.get(spawn_root, f"dpm_ready:{gid}", timeout=60.0)
    world = ctx.comm_world
    return Communicator(
        ctx, Group(list(world.group.world_ranks)),
        int(os.environ["OMPI_TPU_PARENT_CID"]),
        "parent", remote_group=Group(parents), local_comm=world)


# -- port-based client/server (MPI_Open_port / connect / accept) ------------

def open_port(ctx) -> str:
    """MPI_Open_port: a name the accept side publishes and the connect side
    dials."""
    seq = getattr(ctx, "_dpm_port_seq", 0)
    ctx._dpm_port_seq = seq + 1
    return f"ompi-tpu-port:{ctx.rank}:{seq}"


def accept(port: str, comm: Communicator, root: int = 0,
           timeout: float = 60.0) -> Communicator:
    """MPI_Comm_accept: collective over ``comm``; pairs with one connect()
    on the same port name."""
    return _rendezvous(port, comm, root, timeout, accepting=True)


def connect(port: str, comm: Communicator, root: int = 0,
            timeout: float = 60.0) -> Communicator:
    """MPI_Comm_connect."""
    return _rendezvous(port, comm, root, timeout, accepting=False)


def _rendezvous(port: str, comm: Communicator, root: int, timeout: float,
                accepting: bool) -> Communicator:
    """Both sides' roots exchange (group, cid proposal) via control-plane
    events keyed by the port name; everyone else learns via local bcast.
    cid = max(both proposals) | PORT base — identical on every rank of both
    communicators without a global collective (the comm.py intercomm
    discipline)."""
    ctx = comm.ctx
    me_root = comm.rank == root
    props = np.asarray(comm.coll.allgather(
        comm, np.array([comm._cid_counter], np.int64)))
    my_prop = int(props.max())
    if me_root:
        kind = "acc" if accepting else "con"
        ctx.bootstrap.publish_event({
            "dpm": kind, "port": port, "prop": my_prop,
            "ranks": list(comm.group.world_ranks)})
        other = _wait_event(ctx, port, "con" if accepting else "acc",
                            timeout)
        payload = np.array([other["prop"], len(other["ranks"])]
                           + list(other["ranks"]), np.int64)
    else:
        payload = None
    n = np.zeros(1, np.int64)
    if me_root:
        n[0] = len(payload)
    n = np.asarray(comm.coll.bcast(comm, n, root=root))
    if payload is None:
        payload = np.zeros(int(n[0]), np.int64)
    payload = np.asarray(comm.coll.bcast(comm, payload, root=root))
    remote_prop, rn = int(payload[0]), int(payload[1])
    remote = [int(x) for x in payload[2:2 + rn]]
    cid = _PORT_CID_BASE | max(my_prop, remote_prop)
    with comm._lock:
        comm._cid_counter = max(comm._cid_counter,
                                max(my_prop, remote_prop) + 1)
    return comm._inherit(Communicator(
        ctx, Group(list(comm.group.world_ranks)), cid,
        f"{comm.name}.{'accept' if accepting else 'connect'}",
        remote_group=Group(remote), local_comm=comm))


def _wait_event(ctx, port: str, kind: str, timeout: float) -> dict:
    """Drain control-plane events until the matching port event arrives;
    unrelated events are re-queued for their real consumers."""
    stash = getattr(ctx, "_dpm_events", None)
    if stash is None:
        stash = ctx._dpm_events = []
    deadline = time.monotonic() + timeout
    while True:
        for i, ev in enumerate(stash):
            if ev.get("dpm") == kind and ev.get("port") == port:
                return stash.pop(i)
        for ev in ctx.poll_events():
            if ev.get("dpm"):
                stash.append(ev)
            else:
                # not ours (e.g. the detector's proc_failed events): back
                # onto the context's event backlog so the next
                # ctx.poll_events() caller still sees it
                ctx.push_event(ev)
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"dpm: no peer arrived on port {port!r} within {timeout}s")
        ctx.engine.progress()
        time.sleep(0.002)
