"""Intercommunicator collectives (≙ ompi/mca/coll/inter).

MPI-4 §6.8: on an intercommunicator every all-* collective returns the
reduction/concatenation of the REMOTE group's contributions; rooted
collectives (bcast/reduce/...) run from one group's root to the other
group. The reference's coll/inter component implements these by composing
the local intracomm's collectives with leader-to-leader exchanges over the
intercomm — the same structure used here: local collective → leaders swap →
local bcast.

Rooted-op addressing uses the MPI sentinels re-exported by ``comm``:
``ROOT`` (I am the root), ``PROC_NULL`` (in the root group, not the root),
or the root's rank in the remote group (receiving side).
"""

from __future__ import annotations

import numpy as np

from ..op import SUM, Op


class InterColl:
    """Per-intercommunicator collective table."""

    def _lc(self, comm):
        lc = comm.local_comm
        if lc is None:
            raise RuntimeError(
                f"intercomm {comm.name} has no local_comm attached")
        return lc

    def barrier(self, comm) -> None:
        from ..comm import TAG_INTER_COLL
        lc = self._lc(comm)
        lc.barrier()
        if lc.rank == 0:
            tok = np.zeros(1, np.int8)
            comm.sendrecv(tok, 0, tok, 0,
                          sendtag=TAG_INTER_COLL, recvtag=TAG_INTER_COLL)
        lc.barrier()

    def bcast(self, comm, buf, root: int = 0):
        """Rooted: root passes ROOT, other root-group members PROC_NULL,
        receivers pass the root's remote rank."""
        from ..comm import PROC_NULL, ROOT, TAG_INTER_COLL
        lc = self._lc(comm)
        buf = np.asarray(buf)
        if root == PROC_NULL:
            return buf
        if root == ROOT:
            # I am the root: feed the remote side through its leader
            comm.send(buf, 0, TAG_INTER_COLL)
            return buf
        # receiving group: remote rank `root` sent to our leader
        if lc.rank == 0:
            comm.recv(buf, root, TAG_INTER_COLL)
        return lc.coll.bcast(lc, buf, root=0)

    def allreduce(self, comm, sendbuf, recvbuf=None, op: Op = None):
        """Each side receives the reduction of the REMOTE group."""
        from ..comm import TAG_INTER_COLL
        op = op or SUM
        lc = self._lc(comm)
        local_red = np.asarray(lc.coll.allreduce(lc, sendbuf, op=op))
        remote_red = np.empty_like(local_red)
        if lc.rank == 0:
            comm.sendrecv(local_red, 0, remote_red, 0,
                          sendtag=TAG_INTER_COLL, recvtag=TAG_INTER_COLL)
        out = lc.coll.bcast(lc, remote_red, root=0)
        if recvbuf is not None:
            np.copyto(np.asarray(recvbuf), out)
            return recvbuf
        return out

    def reduce(self, comm, sendbuf, recvbuf=None, op: Op = None,
               root: int = 0):
        """Rooted: the REMOTE group's contributions reduce onto the root
        (MPI-4 §6.8 reduce addressing: ROOT / PROC_NULL / remote rank)."""
        from ..comm import PROC_NULL, ROOT, TAG_INTER_COLL
        op = op or SUM
        lc = self._lc(comm)
        if root == PROC_NULL:
            return None
        if root == ROOT:
            # I am the root: the sending group reduced locally and its
            # leader ships one vector
            out = np.empty_like(np.asarray(sendbuf)) if recvbuf is None \
                else recvbuf
            comm.recv(out, 0, TAG_INTER_COLL)
            return out
        # sending group: reduce locally onto our leader, leader sends to
        # the remote root
        part = lc.coll.reduce(lc, sendbuf, op=op, root=0)
        if lc.rank == 0:
            comm.send(np.asarray(part), root, TAG_INTER_COLL)
        return None

    def gather(self, comm, sendbuf, recvbuf=None, root: int = 0):
        """Rooted: the root receives the concatenation of the REMOTE
        group's buffers."""
        from ..comm import PROC_NULL, ROOT, TAG_INTER_COLL
        lc = self._lc(comm)
        if root == PROC_NULL:
            return None
        if root == ROOT:
            if recvbuf is None:
                raise ValueError(
                    "intercomm gather at ROOT needs recvbuf shaped "
                    "(remote_size, *elem) — the remote element shape is "
                    "not inferable here")
            comm.recv(np.asarray(recvbuf), 0, TAG_INTER_COLL)
            return recvbuf
        cat = lc.coll.gather(lc, np.asarray(sendbuf), root=0)
        if lc.rank == 0:
            comm.send(np.ascontiguousarray(cat), root, TAG_INTER_COLL)
        return None

    def scatter(self, comm, sendbuf=None, recvbuf=None, root: int = 0):
        """Rooted: the root scatters one block per REMOTE rank."""
        from ..comm import PROC_NULL, ROOT, TAG_INTER_COLL
        lc = self._lc(comm)
        if root == PROC_NULL:
            return None
        if root == ROOT:
            comm.send(np.ascontiguousarray(sendbuf), 0, TAG_INTER_COLL)
            return None
        if recvbuf is None:
            raise ValueError("intercomm scatter receivers need recvbuf")
        recvbuf = np.asarray(recvbuf)
        blocks = None
        if lc.rank == 0:        # leader-only staging buffer (non-leaders
            # never touch the full matrix, so never allocate it there)
            blocks = np.empty((lc.size,) + recvbuf.shape, recvbuf.dtype)
            comm.recv(blocks, root, TAG_INTER_COLL)
        lc.coll.scatter(lc, blocks, recvbuf, root=0)
        return recvbuf

    def alltoall(self, comm, sendbuf, recvbuf=None):
        """Block i of each rank's sendbuf goes to REMOTE rank i; symmetric
        both ways (MPI-4 §6.8 alltoall on intercomms). Leaders exchange the
        full block matrices, then each side scatters rows locally."""
        from ..comm import TAG_INTER_COLL
        lc = self._lc(comm)
        sendbuf = np.asarray(sendbuf)
        rsize = comm.remote_size
        sp = sendbuf.reshape(rsize, -1)      # one block per REMOTE rank
        # the RECEIVED block shape comes from recvbuf (the MPI contract:
        # recvcount describes the remote side's sends and may differ from
        # ours per direction); symmetric fallback without one
        if recvbuf is None:
            recvbuf = np.empty((rsize,) + sp.shape[1:], sp.dtype)
        rblk = np.asarray(recvbuf).reshape(rsize, -1).shape[1:]
        # gather my side's matrix (local_size, rsize, sblk) onto the leader
        mat = lc.coll.gather(lc, sp, root=0)
        inbox = None
        if lc.rank == 0:        # leader-only staging buffers
            out = np.ascontiguousarray(np.swapaxes(np.asarray(mat), 0, 1))
            # leaders swap transposed matrices; shapes differ when group
            # sizes or per-direction counts differ — each side's inbox is
            # sized from ITS recv contract, and the byte counts agree
            # pairwise because my (rsize, lsize, sblk) send is exactly the
            # remote's (lsize, rsize, rblk') recv
            inbox = np.empty((lc.size, rsize) + rblk,
                             np.asarray(recvbuf).dtype)
            comm.sendrecv(out, 0, inbox, 0,
                          sendtag=TAG_INTER_COLL, recvtag=TAG_INTER_COLL)
        # row r of inbox (after local scatter) = blocks addressed to local
        # rank r, ordered by remote rank
        lc.coll.scatter(lc, inbox, recvbuf, root=0)
        return recvbuf

    def allgather(self, comm, sendbuf, recvbuf=None):
        """Every rank receives the concatenation of the REMOTE group's
        buffers. When the two sides contribute different per-rank counts
        (legal in MPI — recvcount describes the remote side), pass a
        ``recvbuf`` shaped (remote_size, *remote_elem); without one the
        remote shape is assumed symmetric to the local sendbuf."""
        from ..comm import TAG_INTER_COLL
        lc = self._lc(comm)
        sendbuf = np.asarray(sendbuf)
        local_cat = np.asarray(lc.coll.allgather(lc, sendbuf))
        if recvbuf is not None:
            shape, dtype = np.asarray(recvbuf).shape, np.asarray(recvbuf).dtype
        else:
            shape, dtype = (comm.remote_size,) + sendbuf.shape, sendbuf.dtype
        remote_cat = np.empty(shape, dtype)
        if lc.rank == 0:
            comm.sendrecv(local_cat, 0, remote_cat, 0,
                          sendtag=TAG_INTER_COLL, recvtag=TAG_INTER_COLL)
        out = lc.coll.bcast(lc, remote_cat, root=0)
        if recvbuf is not None:
            np.copyto(np.asarray(recvbuf), out)
            return recvbuf
        return out

    def allgatherv(self, comm, sendbuf, recvbuf=None, counts=None,
                   displs=None):
        """All-variant with per-REMOTE-rank counts: ``counts[i]`` is what
        remote rank i contributes (MPI: the recv signature describes the
        remote group). Gap regions of a displs-strided recvbuf are left
        untouched, and strided recvbufs are written through ``.flat``."""
        from ..comm import TAG_INTER_COLL
        lc = self._lc(comm)
        sendbuf = np.asarray(sendbuf).reshape(-1)
        if counts is None:
            raise ValueError("intercomm allgatherv needs counts "
                             "(per REMOTE rank)")
        counts = [int(v) for v in counts]
        if displs is None:
            displs = list(np.concatenate([[0], np.cumsum(counts)[:-1]]))
        # variable gather onto the leader; only the leader needs sizes
        mysize = np.array([sendbuf.size], np.int64)
        sizes_at_leader = lc.coll.gather(lc, mysize, root=0)
        lsizes = None if sizes_at_leader is None else \
            [int(v) for v in np.asarray(sizes_at_leader).reshape(-1)]
        cat = lc.coll.gatherv(lc, sendbuf, counts=lsizes, root=0)
        total_in = int(sum(counts))
        inbox = np.empty(total_in, sendbuf.dtype)
        if lc.rank == 0:
            comm.sendrecv(np.ascontiguousarray(np.asarray(cat)), 0,
                          inbox, 0,
                          sendtag=TAG_INTER_COLL, recvtag=TAG_INTER_COLL)
        inbox = np.asarray(lc.coll.bcast(lc, inbox, root=0))
        span = max(int(d) + int(c) for d, c in zip(displs, counts))
        if recvbuf is None:
            recvbuf = np.empty(span, sendbuf.dtype)
        out = np.asarray(recvbuf)
        off = 0
        for i, c_ in enumerate(counts):
            # .flat slice-assignment works on strided buffers too and
            # touches ONLY the count regions (displs gaps stay intact)
            out.flat[int(displs[i]):int(displs[i]) + c_] = \
                inbox[off:off + c_]
            off += c_
        return recvbuf

    def reduce_scatter_block(self, comm, sendbuf, recvbuf=None,
                             op: Op = None):
        """Each side reduces the REMOTE group's contributions and scatters
        the result across its own ranks in equal blocks (MPI-4 §6.8)."""
        from ..comm import TAG_INTER_COLL
        op = op or SUM
        lc = self._lc(comm)
        sendbuf = np.asarray(sendbuf)
        # my sendbuf is sized for the REMOTE side's scatter; the incoming
        # vector is sized for MINE — only recvbuf can define my block when
        # the two groups differ in size
        if recvbuf is None and comm.remote_size != lc.size:
            raise ValueError(
                "intercomm reduce_scatter_block with asymmetric group "
                "sizes needs recvbuf (the incoming block size is not "
                "derivable from sendbuf)")
        blk = (np.asarray(recvbuf).reshape(-1).size if recvbuf is not None
               else sendbuf.reshape(-1).size // lc.size)
        if recvbuf is None:
            recvbuf = np.empty(blk, sendbuf.dtype)
        red = lc.coll.reduce(lc, sendbuf, op=op, root=0)
        remote_red = None
        if lc.rank == 0:
            remote_red = np.empty(lc.size * blk,
                                  np.asarray(recvbuf).dtype)
            comm.sendrecv(np.ascontiguousarray(np.asarray(red)), 0,
                          remote_red, 0,
                          sendtag=TAG_INTER_COLL, recvtag=TAG_INTER_COLL)
            remote_red = remote_red.reshape(lc.size, -1)
        lc.coll.scatter(lc, remote_red, recvbuf, root=0)
        return recvbuf


class InterXlaColl(InterColl):
    """Device-aware intercomm collectives: the hierarchical ICI/DCN shape
    of two TPU slices bridged by their hosts. When the buffers are device
    arrays and this side's local_comm carries a mesh, the intra-group
    phases run as compiled XLA programs over the local mesh (ICI — the
    expensive O(local_size) part), and only ONE already-reduced buffer
    crosses the group boundary through the leaders' host path (the DCN
    analog). ≙ ompi/mca/coll/inter/coll_inter_allreduce.c:1 composed with
    the coll/xla device dispatch; attach via parallel.attach_mesh on the
    intercommunicator.

    Host buffers fall through to the plain InterColl table unchanged."""

    def _device_ready(self, comm, buf) -> bool:
        from .xla import _is_device
        lc = comm.local_comm
        return (lc is not None and getattr(lc, "device_comm", None)
                is not None and _is_device(buf))

    def allreduce(self, comm, sendbuf, recvbuf=None, op: Op = None):
        """Each side receives the reduction of the REMOTE group; the local
        reduction runs on the mesh, leaders bridge one vector."""
        from ..comm import TAG_INTER_COLL
        # an explicit recvbuf is a host-contract request (the device path
        # returns a fresh device array and never fills one)
        if recvbuf is not None or not self._device_ready(comm, sendbuf):
            return super().allreduce(comm, sendbuf, recvbuf, op)
        import jax
        import jax.numpy as jnp
        op = op or SUM
        lc = self._lc(comm)
        dc = lc.device_comm
        loc = dc.allreduce(sendbuf, op)          # ICI: local reduction
        # leaders swap ONE reduced row on the host bridge (DCN analog)
        row = np.asarray(jax.device_get(loc))[:1]
        remote = np.empty_like(row)
        if lc.rank == 0:
            comm.sendrecv(np.ascontiguousarray(row), 0, remote, 0,
                          sendtag=TAG_INTER_COLL, recvtag=TAG_INTER_COLL)
        remote = lc.coll.bcast(lc, remote, root=0)
        # replicate the remote reduction back across the local mesh rows
        rows = np.broadcast_to(remote, np.asarray(loc).shape)
        return jax.device_put(jnp.asarray(rows), dc.sharding())

    def bcast(self, comm, buf, root: int = 0):
        """Rooted device bcast: the receiving side lands the root's buffer
        in row 0 and broadcasts it across its mesh on ICI."""
        from ..comm import PROC_NULL, ROOT, TAG_INTER_COLL
        if not self._device_ready(comm, buf):
            return super().bcast(comm, buf, root)
        import jax
        import jax.numpy as jnp
        lc = self._lc(comm)
        dc = lc.device_comm
        if root == PROC_NULL:
            return buf
        if root == ROOT:
            comm.send(np.asarray(jax.device_get(buf))[0], 0,
                      TAG_INTER_COLL)
            return buf
        host = np.asarray(jax.device_get(buf))
        if lc.rank == 0:
            row0 = np.empty_like(host[0])
            comm.recv(row0, root, TAG_INTER_COLL)
            host = np.broadcast_to(row0, host.shape)
        host = lc.coll.bcast(lc, np.ascontiguousarray(host), root=0)
        return jax.device_put(jnp.asarray(host), dc.sharding())

    def allgather(self, comm, sendbuf, recvbuf=None):
        """Every rank receives the REMOTE group's concatenation; the local
        gather runs on the mesh, leaders bridge the concatenated matrix."""
        from ..comm import TAG_INTER_COLL
        # recvbuf given → host contract (and the only way to express
        # asymmetric per-side shapes); device path handles the symmetric
        # no-recvbuf case
        if recvbuf is not None or not self._device_ready(comm, sendbuf):
            return super().allgather(comm, sendbuf, recvbuf)
        import jax
        import jax.numpy as jnp
        lc = self._lc(comm)
        dc = lc.device_comm
        # local mesh gather: (r, *e) → every row holds (r, *e) concat
        gathered = dc.allgather(
            sendbuf.reshape(sendbuf.shape[0], 1, *sendbuf.shape[1:]))
        local_cat = np.asarray(jax.device_get(gathered))[0]
        # the device ROWS play the rank role here; the bridge is sized
        # symmetrically (the recvbuf gate above routes asymmetric slices
        # to the host path, which sizes from the recv contract)
        remote_cat = np.empty_like(local_cat)
        if lc.rank == 0:
            comm.sendrecv(np.ascontiguousarray(local_cat), 0,
                          remote_cat, 0,
                          sendtag=TAG_INTER_COLL, recvtag=TAG_INTER_COLL)
        remote_cat = lc.coll.bcast(lc, remote_cat, root=0)
        rows = np.broadcast_to(
            remote_cat.reshape(1, -1),
            (np.asarray(sendbuf).shape[0], remote_cat.size))
        return jax.device_put(jnp.asarray(rows), dc.sharding())
