"""Non-blocking collectives as progress-driven schedules (≙ coll/libnbc).

The reference compiles each non-blocking collective into a round-based
*schedule* of send/recv/op/copy primitives (nbc_internal.h:156-160) advanced
by the progress engine (NBC_Progress, nbc.c:320): a round's operations all
start together; the next round starts when every operation of the current
round has completed. The calling thread never blocks — completion is
observed via the returned request.

Tag isolation: every schedule instance draws a tag from a reserved cycling
space (the reference does the same with its own tag space) so concurrent
collectives on one communicator can't cross-match; ranks agree on the tag
because collectives are issued in the same order everywhere (MPI ordering
rule).

Persistent collectives (MPI-4 *_init, coll.h:580-587) wrap a schedule
factory: each ``start()`` builds and launches a fresh schedule over the same
arguments, reusing buffers.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..core.component import Component, component
from ..op import Op, SUM
from ..p2p.request import Request
from .framework import CollModule

# reserved cycling tag space for nbc schedules (user ≥ 0; comm mgmt -10..;
# blocking coll -100..; nbc -200..-999)
_NBC_TAG_BASE = -200
_NBC_TAG_SPAN = 800


def _nbc_tag(comm) -> int:
    seq = getattr(comm, "_nbc_seq", 0)
    comm._nbc_seq = seq + 1
    return _NBC_TAG_BASE - (seq % _NBC_TAG_SPAN)


class Schedule:
    """Rounds of primitives. Ops:
    ("send", array, peer, tag) / ("recv", array, peer, tag) — comm ops;
    ("copy", src, dst) / ("op", op, src, dst) — local, run when the round
    starts (dst = op(src, dst))."""

    def __init__(self, comm, rounds: List[List[Tuple]],
                 result: Any = None) -> None:
        self.comm = comm
        self.rounds = rounds
        self.request = Request()
        self.request.result = None     # type: ignore[attr-defined]
        self._result = result
        self._round = -1
        self._inflight: List[Request] = []
        self._started = False

    def start(self) -> Request:
        assert not self._started
        self._started = True
        self.comm.ctx.engine.register(self._progress)
        self._advance()
        return self.request

    def _advance(self) -> None:
        while True:
            self._round += 1
            self._inflight = []
            if self._round >= len(self.rounds):
                self.comm.ctx.engine.unregister(self._progress)
                self.request.result = self._result   # type: ignore[attr-defined]
                self.request.complete()
                return
            for op in self.rounds[self._round]:
                kind = op[0]
                if kind == "send":
                    _, buf, peer, tag = op
                    self._inflight.append(self.comm.isend(buf, peer, tag))
                elif kind == "recv":
                    _, buf, peer, tag = op
                    self._inflight.append(self.comm.irecv(buf, peer, tag))
                elif kind == "copy":
                    _, src, dst = op
                    np.copyto(dst, src)
                elif kind == "op":
                    _, theop, src, dst = op
                    dst[...] = theop(src, dst.copy())
                else:
                    raise RuntimeError(f"unknown schedule op {kind!r}")
            if self._inflight:
                return       # wait for this round's comm ops
            # local-only round: fall through to the next immediately

    def _progress(self) -> int:
        if not self._inflight or not all(r.done for r in self._inflight):
            return 0
        for r in self._inflight:
            if r.error is not None:
                self.comm.ctx.engine.unregister(self._progress)
                self.request.complete(r.error)
                return 1
        self._advance()
        return 1


# ---------------------------------------------------------------------------
# schedule builders (round-based classics, ≙ libnbc's algorithm set)
# ---------------------------------------------------------------------------

def sched_barrier(comm) -> Schedule:
    """Dissemination barrier (≙ nbc ibarrier): ceil(log2 p) rounds."""
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    rounds = []
    dist = 1
    token = np.zeros(1, np.int8)
    while dist < size:
        to = (rank + dist) % size
        frm = (rank - dist) % size
        rounds.append([("send", token, to, tag),
                       ("recv", np.zeros(1, np.int8), frm, tag)])
        dist <<= 1
    return Schedule(comm, rounds)


def sched_bcast(comm, buf: np.ndarray, root: int) -> Schedule:
    """Binomial-tree ibcast, one round per doubling step: at round t the
    ranks with vrank < 2^t send to vrank + 2^t — so a rank's sends sit in
    rounds strictly after its receive round."""
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    buf = np.asarray(buf)
    vrank = (rank - root) % size
    nrounds = max(1, (size - 1).bit_length())
    rounds: List[List[Tuple]] = [[] for _ in range(nrounds)]
    if vrank > 0:
        t_recv = vrank.bit_length() - 1          # round of my highest bit
        parent = ((vrank - (1 << t_recv)) + root) % size
        rounds[t_recv].append(("recv", buf, parent, tag))
    for t in range(nrounds):
        if vrank < (1 << t):
            child = vrank + (1 << t)
            if child < size:
                rounds[t].append(("send", buf, (child + root) % size, tag))
    return Schedule(comm, [r for r in rounds if r] or [[]], result=buf)


def sched_reduce(comm, send: np.ndarray, recv: Optional[np.ndarray],
                 root: int, op: Op) -> Schedule:
    """Binomial-tree ireduce (commutative ops): leaves send up each level."""
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    send = np.asarray(send)
    acc = send.copy()
    vrank = (rank - root) % size
    rounds: List[List[Tuple]] = []
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + root) % size
            rounds.append([("send", acc, parent, tag)])
            break
        child = vrank | mask
        if child < size:
            inbox = np.empty_like(acc)
            rounds.append([("recv", inbox, (child + root) % size, tag)])
            rounds.append([("op", op, inbox, acc)])
        mask <<= 1
    result = None
    if rank == root:
        if recv is None:
            recv = np.empty_like(send)
        rounds.append([("copy", acc, recv)])
        result = recv
    return Schedule(comm, rounds or [[]], result=result)


def sched_allreduce(comm, send: np.ndarray, recv: Optional[np.ndarray],
                    op: Op) -> Schedule:
    """Recursive-doubling iallreduce (pads to any p via pre/post phases)."""
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    send = np.asarray(send)
    if recv is None:
        recv = np.empty_like(send)
    rounds: List[List[Tuple]] = [[("copy", send, recv)]]
    pof2 = 1 << (size.bit_length() - 1) if size else 1
    rem = size - pof2
    newrank = -1
    if rank < 2 * rem:
        if rank % 2 == 0:
            rounds.append([("send", recv, rank + 1, tag)])
        else:
            inbox0 = np.empty_like(recv)
            rounds.append([("recv", inbox0, rank - 1, tag)])
            rounds.append([("op", op, inbox0, recv)])
            newrank = rank // 2
    else:
        newrank = rank - rem
    if newrank >= 0:
        mask = 1
        while mask < pof2:
            peer_new = newrank ^ mask
            peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
            inbox = np.empty_like(recv)
            rounds.append([("send", recv, peer, tag),
                           ("recv", inbox, peer, tag)])
            rounds.append([("op", op, inbox, recv)])
            mask <<= 1
    if rank < 2 * rem:
        if rank % 2 == 0:
            rounds.append([("recv", recv, rank + 1, tag)])
        else:
            rounds.append([("send", recv, rank - 1, tag)])
    return Schedule(comm, rounds, result=recv)


def sched_allgather(comm, send: np.ndarray, recv: Optional[np.ndarray]
                    ) -> Schedule:
    """Ring iallgather: p-1 rounds of neighbor exchange."""
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    send = np.asarray(send)
    if recv is None:
        recv = np.empty((size,) + send.shape, send.dtype)
    parts = recv.reshape((size, -1))
    rounds: List[List[Tuple]] = [[("copy", send.reshape(-1), parts[rank])]]
    right, left = (rank + 1) % size, (rank - 1) % size
    for step in range(size - 1):
        blk_send = (rank - step) % size
        blk_recv = (rank - step - 1) % size
        rounds.append([("send", parts[blk_send], right, tag),
                       ("recv", parts[blk_recv], left, tag)])
    return Schedule(comm, rounds, result=recv)


def sched_alltoall(comm, send: np.ndarray, recv: Optional[np.ndarray]
                   ) -> Schedule:
    """Linear ialltoall: one round, all pairs in flight (nbc a2a linear)."""
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    send = np.asarray(send)
    sparts = send.reshape((size, -1))
    if recv is None:
        recv = np.empty_like(send)
    rparts = recv.reshape((size, -1))
    ops: List[Tuple] = [("copy", sparts[rank], rparts[rank])]
    for peer in range(size):
        if peer != rank:
            ops.append(("send", sparts[peer], peer, tag))
            ops.append(("recv", rparts[peer], peer, tag))
    return Schedule(comm, [ops], result=recv)


def sched_gather(comm, send: np.ndarray, recv: Optional[np.ndarray],
                 root: int) -> Schedule:
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    send = np.asarray(send)
    if rank == root:
        if recv is None:
            recv = np.empty((size,) + send.shape, send.dtype)
        parts = recv.reshape((size, -1))
        ops: List[Tuple] = [("copy", send.reshape(-1), parts[root])]
        ops += [("recv", parts[src], src, tag)
                for src in range(size) if src != root]
        return Schedule(comm, [ops], result=recv)
    return Schedule(comm, [[("send", send, root, tag)]])


def sched_scatter(comm, send: Optional[np.ndarray], recv: np.ndarray,
                  root: int) -> Schedule:
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    recv = np.asarray(recv)
    if rank == root:
        assert send is not None
        parts = np.asarray(send).reshape((size, -1))
        ops: List[Tuple] = [("copy", parts[root], recv.reshape(-1))]
        ops += [("send", np.ascontiguousarray(parts[dst]), dst, tag)
                for dst in range(size) if dst != root]
        return Schedule(comm, [ops], result=recv)
    return Schedule(comm, [[("recv", recv, root, tag)]], result=recv)


def sched_reduce_scatter_block(comm, send: np.ndarray,
                               recv: Optional[np.ndarray], op: Op) -> Schedule:
    """ireduce_scatter_block as reduce rounds + scatter round (nonoverlapping
    composition, ≙ coll_base_reduce_scatter.c:47 nonoverlapping)."""
    size, rank = comm.size, comm.rank
    send = np.asarray(send)
    parts = send.reshape((size, -1))
    if recv is None:
        recv = np.empty(parts.shape[1:], send.dtype)
    tag = _nbc_tag(comm)
    # pairwise-exchange reduce-scatter: p-1 single-op rounds (any p)
    acc = parts[rank].copy()
    rounds: List[List[Tuple]] = []
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step) % size
        inbox = np.empty_like(acc)
        rounds.append([("send", np.ascontiguousarray(parts[to]), to, tag),
                       ("recv", inbox, frm, tag)])
        rounds.append([("op", op, inbox, acc)])
    rounds.append([("copy", acc, recv.reshape(-1))])
    return Schedule(comm, rounds, result=recv)


def _displs(counts: Sequence[int], displs) -> List[int]:
    if displs is None:
        displs = list(np.concatenate([[0], np.cumsum(counts)[:-1]]))
    return list(displs)


def sched_gatherv(comm, send: np.ndarray, recv, counts, displs,
                  root: int) -> Schedule:
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    send = np.asarray(send)
    if rank != root:
        return Schedule(comm, [[("send", send, root, tag)]])
    displs = _displs(counts, displs)
    if recv is None:
        recv = np.empty(int(np.sum(counts)), send.dtype)
    flat = recv.reshape(-1)
    ops: List[Tuple] = [("copy", send.reshape(-1),
                         flat[displs[root]:displs[root] + counts[root]])]
    ops += [("recv", flat[displs[s]:displs[s] + counts[s]], s, tag)
            for s in range(size) if s != root]
    return Schedule(comm, [ops], result=recv)


def sched_scatterv(comm, send, recv: np.ndarray, counts, displs,
                   root: int) -> Schedule:
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    recv = np.asarray(recv)
    if rank != root:
        return Schedule(comm, [[("recv", recv, root, tag)]], result=recv)
    displs = _displs(counts, displs)
    flat = np.asarray(send).reshape(-1)
    ops: List[Tuple] = [("copy", flat[displs[root]:displs[root] + counts[root]],
                         recv.reshape(-1))]
    ops += [("send", np.ascontiguousarray(
        flat[displs[d]:displs[d] + counts[d]]), d, tag)
        for d in range(size) if d != root]
    return Schedule(comm, [ops], result=recv)


def sched_allgatherv(comm, send: np.ndarray, recv, counts,
                     displs) -> Schedule:
    """Linear iallgatherv (libnbc's default shape for the v-variants)."""
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    send = np.asarray(send).reshape(-1)
    displs = _displs(counts, displs)
    if recv is None:
        recv = np.empty(int(np.sum(counts)), send.dtype)
    flat = recv.reshape(-1)
    ops: List[Tuple] = [("copy", send,
                         flat[displs[rank]:displs[rank] + counts[rank]])]
    for peer in range(size):
        if peer != rank:
            ops.append(("send", send, peer, tag))
            ops.append(("recv", flat[displs[peer]:displs[peer] + counts[peer]],
                        peer, tag))
    return Schedule(comm, [ops], result=recv)


def sched_alltoallv(comm, send, recv, sendcounts, recvcounts,
                    sdispls, rdispls) -> Schedule:
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    sflat = np.asarray(send).reshape(-1)
    sdispls = _displs(sendcounts, sdispls)
    rdispls = _displs(recvcounts, rdispls)
    rflat = recv.reshape(-1)
    ops: List[Tuple] = [("copy",
                         sflat[sdispls[rank]:sdispls[rank] + sendcounts[rank]],
                         rflat[rdispls[rank]:rdispls[rank] + recvcounts[rank]])]
    for peer in range(size):
        if peer != rank:
            ops.append(("send", np.ascontiguousarray(
                sflat[sdispls[peer]:sdispls[peer] + sendcounts[peer]]),
                peer, tag))
            ops.append(("recv",
                        rflat[rdispls[peer]:rdispls[peer] + recvcounts[peer]],
                        peer, tag))
    return Schedule(comm, [ops], result=recv)


def sched_alltoallw(comm, sendbufs, recvbufs) -> Schedule:
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    ops: List[Tuple] = [("copy", np.asarray(sendbufs[rank]), recvbufs[rank])]
    for peer in range(size):
        if peer != rank:
            ops.append(("send", np.ascontiguousarray(sendbufs[peer]),
                        peer, tag))
            ops.append(("recv", recvbufs[peer], peer, tag))
    return Schedule(comm, [ops], result=recvbufs)


def sched_scan(comm, send: np.ndarray, recv: Optional[np.ndarray],
               op: Op, exclusive: bool) -> Schedule:
    """Recursive-doubling iscan/iexscan: the round structure is static
    (which peers exist per doubling is known at build time), with a copy
    round snapshotting the running total before each send so in-flight
    sends never race the total's update."""
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    send = np.asarray(send)
    if recv is None:
        recv = np.empty_like(send)
    total = send.copy()
    prefix = np.zeros_like(send)
    have_prefix = False
    rounds: List[List[Tuple]] = []
    mask = 1
    while mask < size:
        hi, lo = rank + mask, rank - mask
        comm_ops: List[Tuple] = []
        if hi < size:
            stage = np.empty_like(total)
            rounds.append([("copy", total, stage)])
            comm_ops.append(("send", stage, hi, tag))
        tmp = np.empty_like(total)
        if lo >= 0:
            comm_ops.append(("recv", tmp, lo, tag))
        if comm_ops:
            rounds.append(comm_ops)
        if lo >= 0:
            post: List[Tuple] = []
            if have_prefix:
                post.append(("op", op, tmp, prefix))   # prefix=op(tmp,prefix)
            else:
                post.append(("copy", tmp, prefix))
                have_prefix = True
            post.append(("op", op, tmp, total))        # total=op(tmp,total)
            rounds.append(post)
        mask <<= 1
    final: List[Tuple] = []
    if exclusive:
        if have_prefix:
            final.append(("copy", prefix, recv))
    else:
        final.append(("copy", send, recv))
        if have_prefix:
            final.append(("op", op, prefix, recv))     # op(prefix, own)
    rounds.append(final or [])
    return Schedule(comm, rounds, result=recv)


def sched_reduce_scatter(comm, send: np.ndarray, recv: np.ndarray,
                         counts: Sequence[int], op: Op) -> Schedule:
    """ireduce_scatter (variable counts): binomial reduce to rank 0 of the
    full vector, then linear scatterv — the nonoverlapping composition
    (coll_base_reduce_scatter.c:47) as one schedule."""
    size, rank = comm.size, comm.rank
    tag = _nbc_tag(comm)
    send = np.asarray(send).reshape(-1)
    acc = send.copy()
    rounds: List[List[Tuple]] = []
    mask = 1
    while mask < size:                     # binomial reduce, root 0
        if rank & mask:
            rounds.append([("send", acc, rank & ~mask, tag)])
            break
        child = rank | mask
        if child < size:
            inbox = np.empty_like(acc)
            rounds.append([("recv", inbox, child, tag)])
            rounds.append([("op", op, inbox, acc)])
        mask <<= 1
    displs = _displs(counts, None)
    if rank == 0:
        # slices of acc are views: by the time this round starts, the
        # reduce rounds above have completed, so the sends observe the
        # fully-reduced values
        ops: List[Tuple] = [("copy", acc[displs[0]:displs[0] + counts[0]],
                             recv.reshape(-1))]
        ops += [("send", acc[displs[d]:displs[d] + counts[d]], d, tag)
                for d in range(1, size)]
        rounds.append(ops)
    else:
        rounds.append([("recv", recv.reshape(-1), 0, tag)])
    return Schedule(comm, rounds, result=recv)


def _sched_neighbor(comm, send_list, recv_list, tag,
                    result=None) -> Schedule:
    """One linear round over the topology's in/out edges (≙ nbc ineighbor_*
    linear schedules)."""
    ops: List[Tuple] = []
    for buf, peer in send_list:
        ops.append(("send", buf, peer, tag))
    for buf, peer in recv_list:
        ops.append(("recv", buf, peer, tag))
    return Schedule(comm, [ops] if ops else [[]], result=result)


class NbcModule(CollModule):
    """Registers true-schedule i* entry points; the coll table prefers these
    over the derived eager wrappers."""

    def ibarrier(self, comm):
        return sched_barrier(comm).start()

    def ibcast(self, comm, buf, root: int = 0):
        return sched_bcast(comm, buf, root).start()

    def ireduce(self, comm, sendbuf, recvbuf=None, op: Op = SUM,
                root: int = 0):
        if not op.commutative:
            raise ValueError("nbc ireduce requires a commutative op "
                             "(use the blocking in-order reduce)")
        return sched_reduce(comm, sendbuf, recvbuf, root, op).start()

    def iallreduce(self, comm, sendbuf, recvbuf=None, op: Op = SUM):
        return sched_allreduce(comm, sendbuf, recvbuf, op).start()

    def iallgather(self, comm, sendbuf, recvbuf=None):
        return sched_allgather(comm, sendbuf, recvbuf).start()

    def ialltoall(self, comm, sendbuf, recvbuf=None):
        return sched_alltoall(comm, sendbuf, recvbuf).start()

    def igather(self, comm, sendbuf, recvbuf=None, root: int = 0):
        return sched_gather(comm, sendbuf, recvbuf, root).start()

    def iscatter(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if recvbuf is None:
            if comm.rank != root:   # same contract as the blocking scatter
                raise ValueError("non-root iscatter needs recvbuf")
            sb = np.asarray(sendbuf)
            recvbuf = np.empty(sb.reshape((comm.size, -1)).shape[1:], sb.dtype)
        return sched_scatter(comm, sendbuf, recvbuf, root).start()

    def ireduce_scatter_block(self, comm, sendbuf, recvbuf=None, op: Op = SUM):
        return sched_reduce_scatter_block(comm, sendbuf, recvbuf, op).start()

    # -- v-variants / scan / reduce_scatter / alltoallw ---------------------

    def igatherv(self, comm, sendbuf, recvbuf=None, counts=None, displs=None,
                 root: int = 0):
        return sched_gatherv(comm, sendbuf, recvbuf, counts, displs,
                             root).start()

    def iscatterv(self, comm, sendbuf, recvbuf=None, counts=None, displs=None,
                  root: int = 0):
        if recvbuf is None:
            raise ValueError("iscatterv needs recvbuf (per-rank count)")
        return sched_scatterv(comm, sendbuf, recvbuf, counts, displs,
                              root).start()

    def iallgatherv(self, comm, sendbuf, recvbuf=None, counts=None,
                    displs=None):
        return sched_allgatherv(comm, sendbuf, recvbuf, counts,
                                displs).start()

    def ialltoallv(self, comm, sendbuf, recvbuf, sendcounts, recvcounts,
                   sdispls=None, rdispls=None):
        return sched_alltoallv(comm, sendbuf, recvbuf, sendcounts,
                               recvcounts, sdispls, rdispls).start()

    def ialltoallw(self, comm, sendbufs, recvbufs):
        return sched_alltoallw(comm, sendbufs, recvbufs).start()

    def iscan(self, comm, sendbuf, recvbuf=None, op: Op = SUM):
        return sched_scan(comm, sendbuf, recvbuf, op,
                          exclusive=False).start()

    def iexscan(self, comm, sendbuf, recvbuf=None, op: Op = SUM):
        return sched_scan(comm, sendbuf, recvbuf, op,
                          exclusive=True).start()

    def ireduce_scatter(self, comm, sendbuf, recvbuf, counts, op: Op = SUM):
        return sched_reduce_scatter(comm, sendbuf, recvbuf, counts,
                                    op).start()

    # -- neighborhood (linear schedules over the attached topology) ---------

    @staticmethod
    def _edges(comm):
        topo = getattr(comm, "topo", None)
        if topo is None:
            raise RuntimeError(
                "neighborhood collective on comm without topology")
        return topo.in_neighbors(comm.rank), topo.out_neighbors(comm.rank)

    def ineighbor_allgather(self, comm, sendbuf, recvbuf=None):
        ind, outd = self._edges(comm)
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty((len(ind),) + sendbuf.shape, sendbuf.dtype)
        tag = _nbc_tag(comm)
        return _sched_neighbor(
            comm, [(sendbuf, d) for d in outd],
            [(recvbuf[i], src) for i, src in enumerate(ind)],
            tag, result=recvbuf).start()

    def ineighbor_alltoall(self, comm, sendbuf, recvbuf=None):
        ind, outd = self._edges(comm)
        sendbuf = np.asarray(sendbuf)
        # a sink vertex (out-degree 0) sends nothing; reshape((0,-1)) is
        # ambiguous in numpy, so shape the empty case explicitly
        parts = (sendbuf.reshape((len(outd), -1)) if outd
                 else np.zeros((0, 0), sendbuf.dtype))
        if recvbuf is None:
            if not outd and ind:
                # no out-edges to infer the block size from: the incoming
                # blocks' size is unknowable here — demand a recvbuf
                raise ValueError(
                    "ineighbor_alltoall on a rank with in-edges but no "
                    "out-edges needs an explicit recvbuf")
            recvbuf = np.empty((len(ind), parts.shape[1]), sendbuf.dtype)
        rparts = recvbuf.reshape((len(ind), -1)) if len(ind) else recvbuf
        tag = _nbc_tag(comm)
        return _sched_neighbor(
            comm,
            [(np.ascontiguousarray(parts[i]), d) for i, d in enumerate(outd)],
            [(rparts[i], src) for i, src in enumerate(ind)], tag,
            result=recvbuf).start()


@component("coll", "nbc", priority=40)
class NbcColl(Component):
    name = "nbc"

    def query(self, comm):
        return self.priority, NbcModule()


# ---------------------------------------------------------------------------
# persistent collectives (MPI-4 *_init, coll.h:580-587)
# ---------------------------------------------------------------------------

class PersistentColl:
    """MPI_*_init analog: ``start()`` launches a fresh schedule over the
    bound arguments; ``wait()``/the returned request completes it. Reusable
    any number of times; inactive between wait and the next start."""

    def __init__(self, factory: Callable[[], Request]) -> None:
        self._factory = factory
        self._active: Optional[Request] = None

    def start(self) -> Request:
        if self._active is not None and not self._active.done:
            raise RuntimeError("persistent collective started while active")
        self._active = self._factory()
        return self._active

    def wait(self):
        assert self._active is not None, "wait() before start()"
        st = self._active.wait()
        result = getattr(self._active, "result", None)
        self._active = None
        return result if result is not None else st

    def test(self) -> bool:
        return self._active is not None and self._active.test()


def persistent(comm, name: str, *args, **kw) -> PersistentColl:
    """Build a persistent handle for any i<name> entry point:
    ``persistent(comm, "allreduce", send, recv)`` ≙ MPI_Allreduce_init."""
    iname = "i" + name

    def factory() -> Request:
        return getattr(comm.coll, iname)(comm, *args, **kw)
    return PersistentColl(factory)
