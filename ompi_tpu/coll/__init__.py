"""Collectives framework (≙ ompi/mca/coll). Importing the package registers
the in-tree components."""

from .framework import COLL_FUNCTIONS, CollModule, CollTable, attach_coll  # noqa: F401
from . import basic  # noqa: F401  (register coll/basic)
from . import selfcoll  # noqa: F401  (register coll/self)
from . import nbc  # noqa: F401  (register coll/nbc — schedule-based i*)

# tuned and xla register on import too; tolerate partial availability during
# bring-up of a reduced build
try:
    from . import tuned  # noqa: F401
except ImportError:  # pragma: no cover
    pass
try:
    from . import xla  # noqa: F401
except ImportError:  # pragma: no cover
    pass
