"""Collectives framework: per-communicator function table + selection.

≙ ompi/mca/coll: the module attached to each communicator is a table of
collective entry points (coll.h:531 — blocking, nonblocking, persistent);
components are queried per communicator and stacked per-function: for every
entry point, the highest-priority component that implements it wins, with
lower-priority components as fallback (coll_base_comm_select.c:233,385,456 —
the subtle contract SURVEY.md calls out).

Components in-tree:
  * ``selfcoll`` — trivial size-1 communicators (≙ coll/self)
  * ``basic``    — linear/correctness algorithms (≙ coll/basic)
  * ``tuned``    — algorithm library + size-based decision rules
                   (≙ coll/base + coll/tuned)
  * ``xla``      — ICI-native device collectives for communicators that map
                   onto a TPU mesh (replaces coll/accelerator host staging);
                   its decision layer also owns the block-quantized tier
                   (``coll/quant``) as a third arm next to native/staged
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..core.component import frameworks
from ..core.output import output, show_help

# the full entry-point inventory (blocking set; i*/persistent variants are
# derived wrappers — see CollTable.__getattr__)
COLL_FUNCTIONS = [
    "allgather", "allgatherv", "allreduce", "alltoall", "alltoallv",
    "alltoallw", "barrier", "bcast", "exscan", "gather", "gatherv",
    "reduce", "reduce_scatter", "reduce_scatter_block", "scan", "scatter",
    "scatterv", "reduce_local",
    # neighborhood collectives (cart/graph topologies, ≙ coll/basic neighbor_*)
    "neighbor_allgather", "neighbor_allgatherv", "neighbor_alltoall",
    "neighbor_alltoallv", "neighbor_alltoallw",
]


class CollModule:
    """Base class for per-communicator collective modules. Implement any
    subset of COLL_FUNCTIONS as methods fn(comm, ...)."""

    def enabled(self, name: str) -> bool:
        return hasattr(self, name)


class CollTable:
    """The per-communicator dispatch table with per-function fallback."""

    def __init__(self, entries: Dict[str, "CollModule"],
                 stack: List[tuple]) -> None:
        self._entries = entries
        self.stack = stack       # [(priority, component_name, module)]

    def provider(self, name: str) -> Optional[str]:
        """Which component serves this entry point (tpu_info introspection)."""
        mod = self._entries.get(name)
        return getattr(mod, "_component_name", None) if mod else None

    def __getattr__(self, name: str):
        entries = object.__getattribute__(self, "_entries")
        if name in entries:
            fn = getattr(entries[name], name)

            def counted(comm, *a, **kw):
                if comm.revoked:
                    from ..ft.ulfm import RevokedError
                    raise RevokedError(comm.name)
                spc = getattr(comm.ctx, "spc", None)
                if spc is not None:
                    spc.inc("collectives")
                    if name == "barrier":
                        spc.inc("barriers")
                from .. import health, monitoring, numerics, perf, trace
                if trace.enabled:
                    # per-rank arrival marker: dispatch time is the entry
                    # timestamp the fleet skew analysis keys on — every
                    # rank records its OWN arrival, unlike the decision
                    # audit which the driving rank emits once
                    trace.instant(
                        f"enter:{name}", "coll-enter", rank=comm.ctx.rank,
                        args={"op": name, "comm": comm.cid,
                              "nbytes": int(getattr(a[0], "nbytes", 0)
                                            or 0) if a else 0})
                if getattr(comm.ctx, "_monitor", None) is not None \
                        or monitoring._hooks:
                    # coll interposition (≙ coll/monitoring component);
                    # PMPI-analog hooks fire even without an installed
                    # Monitor, matching the osc events' gating
                    monitoring.coll_event(comm, name, a[0] if a else None)
                call = fn
                if numerics.enabled:
                    # payload fingerprints: wrap the innermost invocation
                    # so pre/post stats surround the actual collective and
                    # the xla audit's note_arm lands in the in-flight
                    # probe entry (ompi_tpu/numerics/probes.py)
                    def call(comm, *a, **kw):
                        return numerics.probed_coll(fn, comm, name, a, kw)
                if health.enabled:
                    # flight recorder: hold a (cid, seq, signature) entry
                    # while in flight so the watchdog/desync sentinel can
                    # attribute a hang (ompi_tpu/health/registry.py)
                    htok = health.coll_begin(comm, name, a, kw)
                    try:
                        if perf.enabled:
                            # cost-model sample: dispatch timed; the arm
                            # is annotated post-decision by coll/xla's
                            # audit (perf.note_arm) — un-annotated
                            # dispatches are dropped, and a raising
                            # collective contributes nothing
                            return perf.timed_coll(call, comm, name, a, kw)
                        return call(comm, *a, **kw)
                    finally:
                        health.op_end(htok)
                if perf.enabled:
                    return perf.timed_coll(call, comm, name, a, kw)
                return call(comm, *a, **kw)

            return counted
        # nonblocking variants: i<name> falls back to eager execution wrapped
        # in a completed request when no component provides a true schedule
        if name.startswith("i") and name[1:] in entries:
            blocking = getattr(entries[name[1:]], name[1:])

            def nb(comm, *a, **kw):
                from ..p2p.request import CompletedRequest
                result = blocking(comm, *a, **kw)
                req = CompletedRequest()
                req.result = result
                return req

            return nb
        raise AttributeError(f"no collective entry point {name!r}")


def _ensure_components() -> None:
    """Import the in-tree component modules (registration happens at import).

    Selection must not depend on package import order: a thread can reach
    this module through sys.modules while another thread is still executing
    ``coll/__init__.py``, before the component imports there have run — the
    analog of the reference opening a framework's components before any
    selection (mca_base_framework.c:161)."""
    import importlib
    # "quant" is not a Component — importing it registers the quantized
    # tier's vars (block size, scale dtype, OMPI_TPU_COLL_QUANT) so env
    # overrides and tpu_info see them; coll/xla dispatches into it
    for m in ("basic", "selfcoll", "tuned", "xla", "nbc", "adapt", "quant"):
        try:
            importlib.import_module(f"{__package__}.{m}")
        except ImportError:  # pragma: no cover — reduced build
            pass


def attach_coll(comm) -> None:
    """Select and attach the coll table for a new communicator
    (≙ mca_coll_base_comm_select)."""
    _ensure_components()
    rows = frameworks.framework("coll").select_all(comm)
    if not rows:
        show_help.show("no-component", "coll", "coll_select", "")
        raise RuntimeError("no coll components available")
    entries: Dict[str, CollModule] = {}
    for pri, component, module in sorted(rows, key=lambda r: r[0]):
        # ascending priority: higher priorities overwrite → win per-function
        if module is None:
            continue
        module._component_name = component.name
        for fn in COLL_FUNCTIONS:
            if module.enabled(fn):
                entries[fn] = module
            # true non-blocking schedules (coll/nbc) outrank the derived
            # eager i* wrappers in CollTable.__getattr__
            if module.enabled("i" + fn):
                entries["i" + fn] = module
    comm.coll = CollTable(entries, sorted(rows, key=lambda r: -r[0]))
    output.verbose(10, "coll",
                   f"comm {comm.name}: " +
                   ", ".join(f"{f}→{m._component_name}"
                             for f, m in sorted(entries.items())))
