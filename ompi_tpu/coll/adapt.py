"""coll/adapt analog: event-driven collectives with dynamic segmentation.

≙ ompi/mca/coll/adapt (coll_adapt_bcast.c:1, coll_adapt_ireduce.c): the
reference's adapt component progresses a segmented tree through COMPLETION
CALLBACKS — a segment forwards the moment it arrives, no round barrier —
and picks segmentation dynamically. The nbc Schedule engine here
(coll/nbc.py) is round-synchronous by design (a round starts when the
previous round fully completes), so adapt is its event-driven sibling:

  * chain (pipeline) topology in rank order from the root — the
    bandwidth-optimal shape for large messages (the same regime the
    reference routes to adapt);
  * every rank posts the next segment's receive IMMEDIATELY and forwards
    each received segment to its child from the receive's completion
    callback — receive(k+1) overlaps forward(k) at every hop;
  * the ROOT adapts segment size to observed completion latency: a
    segment's send-to-completion time below the low-water mark means
    per-message overhead dominates (segments double, up to max); above
    the high-water mark the pipe is saturated and finer overlap pays
    (segments halve, down to min). Receivers discover sizes from
    status.count — no size pre-agreement, which is what makes the
    segmentation free to adapt mid-message.

Selection: registered as coll component ``adapt`` at priority 5 (below
nbc), so the stock dispatch is unchanged; raise ``coll_adapt_priority``
to let its ibcast/ireduce win selection, or call
``ibcast_adapt``/``ireduce_adapt`` directly.

Status (round-4 measurement, BASELINE.md "coll/adapt on the DCN
stand-in"): on every fabric this box can express — shm+CMA, and
tcp-only 4-rank (the DCN stand-in) at 1/4/16 MB — whole-message
binomial beats adapt by ~1.2-1.6×, because event-driven overlap needs
CONCURRENT cores and this host has one: segment completion callbacks
serialize, leaving only their per-segment overhead. The component is
therefore demoted to a correctness-complete, measurement-pending
implementation: its claimed habitat (multi-host DCN, a core per rank,
per-hop bandwidth dominating) does not exist on this hardware, and the
default priority keeps it unselected until a fabric where it measures a
win is available.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core import var as _var
from ..core.component import Component, component
from ..op import SUM, Op, reduce_local
from ..p2p.request import Request
from .framework import CollModule

_var.register("coll", "adapt", "priority", 5, type=int, level=4,
              help="Selection priority of the event-driven adapt "
                   "collectives (default 5 = available but not selected; "
                   "raise above 40 to outrank the round-based nbc "
                   "schedules for ibcast/ireduce).")
_var.register("coll", "adapt", "seg_min", 64 * 1024, type=int, level=4,
              help="Adaptive segmentation floor (bytes).")
_var.register("coll", "adapt", "seg_max", 1 << 20, type=int, level=4,
              help="Adaptive segmentation ceiling (bytes).")

_ADAPT_TAG_BASE = -1200     # own reserved band (nbc uses -200..-999)
_ADAPT_TAG_SPAN = 200


def _tag(comm) -> int:
    seq = getattr(comm, "_adapt_seq", 0)
    comm._adapt_seq = seq + 1
    return _ADAPT_TAG_BASE - (seq % _ADAPT_TAG_SPAN)


class _AdaptBcast:
    """One in-flight adaptive bcast instance (engine-registered)."""

    # completion-latency water marks for the segment controller: below
    # LO the per-message overhead dominates → coarser; above HI the pipe
    # is backed up → finer (more overlap). Seconds.
    T_LO = 0.0008
    T_HI = 0.008

    def __init__(self, comm, buf: np.ndarray, root: int, tag: int) -> None:
        self.comm = comm
        self.buf = buf.reshape(-1).view(np.uint8)
        self.total = self.buf.nbytes
        self.req = Request()
        self.tag = tag
        n, me = comm.size, comm.rank
        pos = (me - root) % n               # chain position (root = 0)
        self.child = (pos + 1 + root) % n if pos < n - 1 else None
        self.parent = (pos - 1 + root) % n if pos > 0 else None
        self.is_root = pos == 0
        self.seg = int(_var.get("coll_adapt_seg_min", 64 * 1024))
        self.seg_max = int(_var.get("coll_adapt_seg_max", 1 << 20))
        self.seg_min = self.seg
        self.sent = 0                       # root: bytes handed to child
        self.received = 0
        self.forwarded = 0
        self._send_reqs: List[Request] = []
        self._recv_req: Optional[Request] = None
        self._t_send = 0.0
        self.segments_sent = 0

    def start(self) -> Request:
        if self.comm.size == 1 or self.total == 0:
            self.req.complete()
            return self.req
        self.comm.ctx.engine.register(self._progress)
        if self.is_root:
            self._push()
        else:
            self._post_recv()
        return self.req

    # -- root: adaptive segment pump ----------------------------------------

    def _push(self) -> None:
        """Keep ≤2 segments in flight; adapt size from completion times."""
        while self.sent < self.total and len(self._send_reqs) < 2:
            n = min(self.seg, self.total - self.sent)
            view = self.buf[self.sent:self.sent + n]
            r = self.comm.isend(view, self.child, self.tag)
            self._send_reqs.append((r, time.perf_counter()))
            self.sent += n
            self.segments_sent += 1

    def _root_progress(self) -> int:
        done = [(r, t0) for r, t0 in self._send_reqs if r.done]
        for r, t0 in done:
            self._send_reqs.remove((r, t0))
            dt = time.perf_counter() - t0
            # the adaptive controller (the component's namesake): latency
            # per segment tells whether overhead or saturation dominates
            if dt < self.T_LO and self.seg < self.seg_max:
                self.seg = min(self.seg * 2, self.seg_max)
            elif dt > self.T_HI and self.seg > self.seg_min:
                self.seg = max(self.seg // 2, self.seg_min)
        self._push()
        if self.sent >= self.total and not self._send_reqs:
            self._finish()
        return len(done)

    # -- non-root: receive → forward event chain -----------------------------

    def _post_recv(self) -> None:
        view = self.buf[self.received:]     # capacity: whatever arrives
        self._recv_req = self.comm.irecv(view, self.parent, self.tag)

    def _other_progress(self) -> int:
        n = 0
        r = self._recv_req
        if r is not None and r.done:
            n = 1
            got = r.status.count
            seg_start = self.received
            self.received += got
            # forward THIS segment before waiting for the next — the
            # event-driven overlap the round-based schedules cannot do
            if self.child is not None and got:
                sr = self.comm.isend(
                    self.buf[seg_start:seg_start + got], self.child,
                    self.tag)
                self._send_reqs.append((sr, 0.0))
                self.forwarded += got
            if self.received < self.total:
                self._post_recv()
            else:
                self._recv_req = None
        self._send_reqs = [e for e in self._send_reqs if not e[0].done]
        if self._recv_req is None and not self._send_reqs:
            self._finish()
        return n

    def _progress(self) -> int:
        if self.req.done:
            return 0
        return self._root_progress() if self.is_root \
            else self._other_progress()

    def _finish(self) -> None:
        self.comm.ctx.engine.unregister(self._progress)
        self.req.complete()


class _AdaptReduce:
    """Event-driven chain reduce toward the root: each hop combines the
    incoming partial with its local contribution segment-by-segment and
    forwards the running partial — segment k forwards while k+1 is still
    inbound (≙ coll_adapt_ireduce.c's callback-progressed tree)."""

    def __init__(self, comm, send: np.ndarray, recv: Optional[np.ndarray],
                 op: Op, root: int, tag: int) -> None:
        if not op.commutative:
            # the chain combines far-end-first (and rotated for root != 0)
            # — only commutative ops reduce correctly that way (the same
            # guard nbc's recursive-doubling schedules apply)
            raise ValueError(
                "adapt ireduce requires a commutative op (use the "
                "in-order tuned/nbc algorithms for non-commutative ops)")
        self.comm = comm
        self.op = op
        self.tag = tag
        contrib = np.ascontiguousarray(send)
        self.elem = contrib.dtype
        n, me = comm.size, comm.rank
        pos = (me - root) % n
        # chain runs from the far end toward the root: my SOURCE is the
        # next rank out, my SINK is the next rank in
        self.src = (pos + 1 + root) % n if pos < n - 1 else None
        self.dst = (pos - 1 + root) % n if pos > 0 else None
        self.is_root = pos == 0
        # accumulator starts as my contribution (root may write into recv)
        if self.is_root and recv is not None:
            self.acc = np.asarray(recv).reshape(-1)
            np.copyto(self.acc, contrib.reshape(-1))
        else:
            self.acc = contrib.reshape(-1).copy()
        self.nelems = self.acc.size
        self.received = 0                  # elements combined from src
        self.forwarded = 0                 # elements shipped to dst
        self.req = Request()
        self.req.result = None             # type: ignore[attr-defined]
        self._send_reqs: List[Request] = []
        self._recv_req: Optional[Request] = None
        self._recv_view: Optional[np.ndarray] = None
        self.seg_elems = max(int(_var.get("coll_adapt_seg_min",
                                          64 * 1024))
                             // self.elem.itemsize, 1)

    def start(self) -> Request:
        if self.comm.size == 1 or self.nelems == 0:
            self.req.result = self.acc     # type: ignore[attr-defined]
            self.req.complete()
            return self.req
        self.comm.ctx.engine.register(self._progress)
        if self.src is not None:
            self._post_recv()
        else:
            self._forward()                # chain tail starts the flow
        return self.req

    def _post_recv(self) -> None:
        n = min(self.seg_elems, self.nelems - self.received)
        self._recv_view = np.empty(n, self.elem)
        self._recv_req = self.comm.irecv(self._recv_view, self.src,
                                         self.tag)

    def _forward(self) -> None:
        """Ship every fully-combined segment not yet forwarded."""
        ready = self.received if self.src is not None else self.nelems
        while self.dst is not None and self.forwarded < ready:
            n = min(self.seg_elems, ready - self.forwarded)
            sr = self.comm.isend(
                self.acc[self.forwarded:self.forwarded + n], self.dst,
                self.tag)
            self._send_reqs.append(sr)
            self.forwarded += n

    def _progress(self) -> int:
        if self.req.done:
            return 0
        n = 0
        r = self._recv_req
        if r is not None and r.done:
            n = 1
            got = self._recv_view
            view = self.acc[self.received:self.received + got.size]
            reduce_local(self.op, got, view)
            self.received += got.size
            self._forward()                # event-driven: combine → ship
            if self.received < self.nelems:
                self._post_recv()
            else:
                self._recv_req = None
        self._send_reqs = [s for s in self._send_reqs if not s.done]
        if self._recv_req is None and not self._send_reqs and \
                (self.dst is None or self.forwarded >= self.nelems):
            self.comm.ctx.engine.unregister(self._progress)
            if self.is_root:
                self.req.result = self.acc  # type: ignore[attr-defined]
            self.req.complete()
        return n


def ibcast_adapt(comm, buf, root: int = 0) -> Request:
    """Event-driven adaptive-segmentation broadcast (returns a request)."""
    return _AdaptBcast(comm, np.asarray(buf), root, _tag(comm)).start()


def ireduce_adapt(comm, sendbuf, recvbuf=None, op: Op = SUM,
                  root: int = 0) -> Request:
    """Event-driven segmented chain reduce (returns a request; the root's
    ``request.result`` carries the reduction)."""
    return _AdaptReduce(comm, np.asarray(sendbuf), recvbuf, op, root,
                        _tag(comm)).start()


class AdaptModule(CollModule):
    """ibcast/ireduce via the event-driven engine (wins selection only
    when coll_adapt_priority is raised above the nbc schedules)."""

    def ibcast(self, comm, buf, root: int = 0):
        return ibcast_adapt(comm, buf, root)

    def ireduce(self, comm, sendbuf, recvbuf=None, op: Op = SUM,
                root: int = 0):
        return ireduce_adapt(comm, sendbuf, recvbuf, op, root)


@component("coll", "adapt", priority=5)
class AdaptColl(Component):
    name = "adapt"

    def query(self, comm):
        return int(_var.get("coll_adapt_priority", 5)), AdaptModule()
