"""Linear correctness-first collective algorithms (≙ ompi/mca/coll/basic).

Every entry point of the coll table, implemented with straight-line p2p —
the fallback component every communicator can rely on, and the semantic
reference the tuned/xla components are tested against (the reference uses
coll/basic the same way: always available, lowest useful priority).

Buffer conventions (host path): numpy arrays; ``sendbuf=None`` means
MPI_IN_PLACE (operate in recvbuf). Vector variants take per-rank counts and
displacements in *elements*.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.component import Component, component
from ..op import Op, reduce_local
from ..p2p.request import wait_all
from .framework import CollModule

# reserved tag space: -100.. (user tags ≥ 0; comm mgmt -10..; coll -100..)
T_BCAST = -101
T_REDUCE = -102
T_GATHER = -103
T_SCATTER = -104
T_ALLGATHER = -105
T_ALLTOALL = -106
T_BARRIER = -107
T_SCAN = -108
T_RSCAT = -109
T_NEIGHBOR = -110


def _inplace(sendbuf, recvbuf):
    if sendbuf is None:
        return np.asarray(recvbuf).copy()
    return np.asarray(sendbuf)


class BasicModule(CollModule):
    """Linear algorithms. One instance per communicator."""

    # -- data movement ------------------------------------------------------

    def bcast(self, comm, buf, root: int = 0):
        buf = np.asarray(buf)
        if comm.size == 1:
            return buf
        if comm.rank == root:
            reqs = [comm.isend(buf, dst, T_BCAST)
                    for dst in range(comm.size) if dst != root]
            wait_all(reqs)
        else:
            comm.recv(buf, root, T_BCAST)
        return buf

    def gather(self, comm, sendbuf, recvbuf=None, root: int = 0):
        sendbuf = np.asarray(sendbuf)
        if comm.rank == root:
            if recvbuf is None:
                recvbuf = np.empty((comm.size,) + sendbuf.shape, sendbuf.dtype)
            rb = recvbuf.reshape((comm.size, -1))
            rb[root] = sendbuf.reshape(-1)
            reqs = [comm.irecv(rb[src], src, T_GATHER)
                    for src in range(comm.size) if src != root]
            wait_all(reqs)
            return recvbuf
        comm.send(sendbuf, root, T_GATHER)
        return None

    def gatherv(self, comm, sendbuf, recvbuf=None,
                counts: Optional[Sequence[int]] = None,
                displs: Optional[Sequence[int]] = None, root: int = 0):
        sendbuf = np.asarray(sendbuf)
        if comm.rank == root:
            assert counts is not None
            if displs is None:
                displs = np.concatenate([[0], np.cumsum(counts)[:-1]])
            if recvbuf is None:
                total = max(d + c for d, c in zip(displs, counts))
                recvbuf = np.empty(total, sendbuf.dtype)
            flat = recvbuf.reshape(-1)
            reqs = []
            for src in range(comm.size):
                view = flat[displs[src]:displs[src] + counts[src]]
                if src == root:
                    view[:] = sendbuf.reshape(-1)[:counts[src]]
                else:
                    reqs.append(comm.irecv(view, src, T_GATHER))
            wait_all(reqs)
            return recvbuf
        comm.send(sendbuf, root, T_GATHER)
        return None

    def scatter(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if comm.rank == root:
            sendbuf = np.asarray(sendbuf)
            parts = sendbuf.reshape((comm.size, -1))
            if recvbuf is None:
                recvbuf = np.empty_like(parts[0])
            reqs = [comm.isend(parts[dst], dst, T_SCATTER)
                    for dst in range(comm.size) if dst != root]
            recvbuf.reshape(-1)[:] = parts[root]
            wait_all(reqs)
            return recvbuf
        if recvbuf is None:
            raise ValueError("non-root scatter needs recvbuf")
        comm.recv(recvbuf, root, T_SCATTER)
        return recvbuf

    def scatterv(self, comm, sendbuf, recvbuf,
                 counts: Optional[Sequence[int]] = None,
                 displs: Optional[Sequence[int]] = None, root: int = 0):
        if comm.rank == root:
            sendbuf = np.asarray(sendbuf).reshape(-1)
            assert counts is not None
            if displs is None:
                displs = np.concatenate([[0], np.cumsum(counts)[:-1]])
            reqs = []
            for dst in range(comm.size):
                view = sendbuf[displs[dst]:displs[dst] + counts[dst]]
                if dst == root:
                    recvbuf.reshape(-1)[:len(view)] = view
                else:
                    reqs.append(comm.isend(view, dst, T_SCATTER))
            wait_all(reqs)
            return recvbuf
        comm.recv(recvbuf, root, T_SCATTER)
        return recvbuf

    def allgather(self, comm, sendbuf, recvbuf=None):
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty((comm.size,) + sendbuf.shape, sendbuf.dtype)
        self.gather(comm, sendbuf, recvbuf if comm.rank == 0 else None, root=0)
        self.bcast(comm, recvbuf, root=0)
        return recvbuf

    def allgatherv(self, comm, sendbuf, recvbuf=None,
                   counts: Optional[Sequence[int]] = None,
                   displs: Optional[Sequence[int]] = None):
        sendbuf = np.asarray(sendbuf)
        assert counts is not None
        if displs is None:
            displs = list(np.concatenate([[0], np.cumsum(counts)[:-1]]))
        if recvbuf is None:
            total = max(d + c for d, c in zip(displs, counts))
            recvbuf = np.empty(total, sendbuf.dtype)
        self.gatherv(comm, sendbuf, recvbuf if comm.rank == 0 else None,
                     counts, displs, root=0)
        self.bcast(comm, recvbuf, root=0)
        return recvbuf

    def alltoall(self, comm, sendbuf, recvbuf=None):
        sendbuf = np.asarray(sendbuf)
        parts = sendbuf.reshape((comm.size, -1))
        if recvbuf is None:
            recvbuf = np.empty_like(sendbuf)
        rparts = recvbuf.reshape((comm.size, -1))
        rparts[comm.rank] = parts[comm.rank]
        reqs = []
        for peer in range(comm.size):
            if peer == comm.rank:
                continue
            reqs.append(comm.irecv(rparts[peer], peer, T_ALLTOALL))
            reqs.append(comm.isend(parts[peer], peer, T_ALLTOALL))
        wait_all(reqs)
        return recvbuf

    def alltoallv(self, comm, sendbuf, recvbuf,
                  sendcounts: Sequence[int], recvcounts: Sequence[int],
                  sdispls: Optional[Sequence[int]] = None,
                  rdispls: Optional[Sequence[int]] = None):
        sendbuf = np.asarray(sendbuf).reshape(-1)
        if sdispls is None:
            sdispls = list(np.concatenate([[0], np.cumsum(sendcounts)[:-1]]))
        if rdispls is None:
            rdispls = list(np.concatenate([[0], np.cumsum(recvcounts)[:-1]]))
        flat = recvbuf.reshape(-1)
        me = comm.rank
        flat[rdispls[me]:rdispls[me] + recvcounts[me]] = \
            sendbuf[sdispls[me]:sdispls[me] + sendcounts[me]]
        reqs = []
        for peer in range(comm.size):
            if peer == me:
                continue
            rv = flat[rdispls[peer]:rdispls[peer] + recvcounts[peer]]
            reqs.append(comm.irecv(rv, peer, T_ALLTOALL))
            sv = sendbuf[sdispls[peer]:sdispls[peer] + sendcounts[peer]]
            reqs.append(comm.isend(sv, peer, T_ALLTOALL))
        wait_all(reqs)
        return recvbuf

    def alltoallw(self, comm, sendbufs: List[np.ndarray],
                  recvbufs: List[np.ndarray]):
        """Per-peer buffers with independent types (list-of-arrays form)."""
        me = comm.rank
        recvbufs[me][...] = sendbufs[me]
        reqs = []
        for peer in range(comm.size):
            if peer == me:
                continue
            reqs.append(comm.irecv(recvbufs[peer], peer, T_ALLTOALL))
            reqs.append(comm.isend(sendbufs[peer], peer, T_ALLTOALL))
        wait_all(reqs)
        return recvbufs

    # -- reductions ---------------------------------------------------------

    def reduce(self, comm, sendbuf, recvbuf=None, op: Op = None,
               root: int = 0):
        from .. import op as _op
        op = op or _op.SUM
        send = _inplace(sendbuf, recvbuf)
        if comm.rank == root:
            # gather all contributions, fold strictly in rank order —
            # required for non-commutative ops and reproducibility
            # (≙ in-order algorithms, coll_base_reduce.c:514)
            contribs = [np.empty_like(send) for _ in range(comm.size)]
            reqs = [comm.irecv(contribs[src], src, T_REDUCE)
                    for src in range(comm.size) if src != root]
            contribs[root][...] = send
            wait_all(reqs)
            acc = contribs[0].copy()
            for src in range(1, comm.size):
                acc = op(acc, contribs[src])   # acc = acc OP x_src
            if recvbuf is None:
                recvbuf = np.empty_like(send)
            recvbuf[...] = acc
            return recvbuf
        comm.send(send, root, T_REDUCE)
        return None

    def allreduce(self, comm, sendbuf, recvbuf=None, op: Op = None):
        send = _inplace(sendbuf, recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        self.reduce(comm, send, recvbuf if comm.rank == 0 else None, op, root=0)
        self.bcast(comm, recvbuf, root=0)
        return recvbuf

    def reduce_scatter_block(self, comm, sendbuf, recvbuf=None, op: Op = None):
        sendbuf = np.asarray(sendbuf)
        parts = sendbuf.reshape((comm.size, -1))
        full = np.empty_like(sendbuf) if comm.rank == 0 else None
        self.reduce(comm, sendbuf, full, op, root=0)
        if recvbuf is None:
            recvbuf = np.empty_like(parts[0])
        self.scatter(comm, full, recvbuf, root=0)
        return recvbuf

    def reduce_scatter(self, comm, sendbuf, recvbuf, counts: Sequence[int],
                       op: Op = None):
        sendbuf = np.asarray(sendbuf).reshape(-1)
        full = np.empty_like(sendbuf) if comm.rank == 0 else None
        self.reduce(comm, sendbuf, full, op, root=0)
        self.scatterv(comm, full, recvbuf, counts, root=0)
        return recvbuf

    def scan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        from .. import op as _op
        op = op or _op.SUM
        send = _inplace(sendbuf, recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        acc = send.copy()
        if comm.rank > 0:
            prev = np.empty_like(send)
            comm.recv(prev, comm.rank - 1, T_SCAN)
            acc = op(prev, acc)
        recvbuf[...] = acc
        if comm.rank < comm.size - 1:
            comm.send(acc, comm.rank + 1, T_SCAN)
        return recvbuf

    def exscan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        from .. import op as _op
        op = op or _op.SUM
        send = _inplace(sendbuf, recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        if comm.rank == 0:
            acc = send.copy()
            if comm.size > 1:
                comm.send(acc, 1, T_SCAN)
        else:
            prev = np.empty_like(send)
            comm.recv(prev, comm.rank - 1, T_SCAN)
            recvbuf[...] = prev
            if comm.rank < comm.size - 1:
                comm.send(op(prev, send.copy()), comm.rank + 1, T_SCAN)
        return recvbuf

    def reduce_local(self, comm, invec, inoutvec, op: Op = None):
        from .. import op as _op
        reduce_local(op or _op.SUM, np.asarray(invec), inoutvec)
        return inoutvec

    # -- synchronization ----------------------------------------------------

    def barrier(self, comm):
        token = np.zeros(0, np.uint8)
        if comm.rank == 0:
            for src in range(1, comm.size):
                comm.recv(token, src, T_BARRIER)
            reqs = [comm.isend(token, dst, T_BARRIER)
                    for dst in range(1, comm.size)]
            wait_all(reqs)
        else:
            comm.send(token, 0, T_BARRIER)
            comm.recv(token, 0, T_BARRIER)

    # -- neighborhood (cart/graph topologies; ≙ coll/basic neighbor_*) ------

    def _neighbors(self, comm):
        topo = getattr(comm, "topo", None)
        if topo is None:
            raise RuntimeError("neighborhood collective on comm without topology")
        return topo.in_neighbors(comm.rank), topo.out_neighbors(comm.rank)

    def neighbor_allgather(self, comm, sendbuf, recvbuf=None):
        indeg, outdeg = self._neighbors(comm)
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty((len(indeg),) + sendbuf.shape, sendbuf.dtype)
        reqs = [comm.irecv(recvbuf[i], src, T_NEIGHBOR)
                for i, src in enumerate(indeg)]
        reqs += [comm.isend(sendbuf, dst, T_NEIGHBOR) for dst in outdeg]
        wait_all(reqs)
        return recvbuf

    def neighbor_allgatherv(self, comm, sendbuf, recvbuf, counts, displs=None):
        indeg, outdeg = self._neighbors(comm)
        sendbuf = np.asarray(sendbuf)
        if displs is None:
            displs = list(np.concatenate([[0], np.cumsum(counts)[:-1]]))
        if recvbuf is None:
            # same allocate-on-None contract as neighbor_allgather: size
            # by the furthest write (user displs may leave gaps)
            total = max((int(d) + int(c) for d, c in zip(displs, counts)),
                        default=0)
            recvbuf = np.empty(total, sendbuf.dtype)
        flat = recvbuf.reshape(-1)
        reqs = [comm.irecv(flat[displs[i]:displs[i] + counts[i]], src, T_NEIGHBOR)
                for i, src in enumerate(indeg)]
        reqs += [comm.isend(sendbuf, dst, T_NEIGHBOR) for dst in outdeg]
        wait_all(reqs)
        return recvbuf

    def neighbor_alltoall(self, comm, sendbuf, recvbuf=None):
        indeg, outdeg = self._neighbors(comm)
        sendbuf = np.asarray(sendbuf)
        parts = sendbuf.reshape((len(outdeg), -1))
        if recvbuf is None:
            recvbuf = np.empty((len(indeg), parts.shape[1]), sendbuf.dtype)
        rparts = recvbuf.reshape((len(indeg), -1))
        reqs = [comm.irecv(rparts[i], src, T_NEIGHBOR)
                for i, src in enumerate(indeg)]
        reqs += [comm.isend(parts[i], dst, T_NEIGHBOR)
                 for i, dst in enumerate(outdeg)]
        wait_all(reqs)
        return recvbuf

    def neighbor_alltoallv(self, comm, sendbuf, recvbuf, sendcounts, recvcounts,
                           sdispls=None, rdispls=None):
        indeg, outdeg = self._neighbors(comm)
        sendbuf = np.asarray(sendbuf).reshape(-1)
        if sdispls is None:
            sdispls = list(np.concatenate([[0], np.cumsum(sendcounts)[:-1]]))
        if rdispls is None:
            rdispls = list(np.concatenate([[0], np.cumsum(recvcounts)[:-1]]))
        flat = recvbuf.reshape(-1)
        reqs = [comm.irecv(flat[rdispls[i]:rdispls[i] + recvcounts[i]],
                           src, T_NEIGHBOR)
                for i, src in enumerate(indeg)]
        reqs += [comm.isend(sendbuf[sdispls[i]:sdispls[i] + sendcounts[i]],
                            dst, T_NEIGHBOR)
                 for i, dst in enumerate(outdeg)]
        wait_all(reqs)
        return recvbuf

    def neighbor_alltoallw(self, comm, sendbufs, recvbufs):
        indeg, outdeg = self._neighbors(comm)
        reqs = [comm.irecv(recvbufs[i], src, T_NEIGHBOR)
                for i, src in enumerate(indeg)]
        reqs += [comm.isend(sendbufs[i], dst, T_NEIGHBOR)
                 for i, dst in enumerate(outdeg)]
        wait_all(reqs)
        return recvbufs


@component("coll", "basic", priority=10)
class BasicColl(Component):
    name = "basic"

    def query(self, comm):
        return self.priority, BasicModule()
