"""coll/quant — block-quantized device collectives (the EQuARX tier).

Large-message reductions on the device plane are wire-bound: the native
tier moves every payload at full operand precision, so busbw is capped by
raw bytes over ICI.  EQuARX ("Efficient Quantized AllReduce in XLA",
arXiv:2506.17615) shows that symmetric per-block int8 quantization inside
the XLA program recovers near-2x effective bandwidth at negligible quality
loss.  This module is that third arm for the decision layer in coll/xla:

  allreduce       quantize -> reduce_scatter wire phase (each peer
                  contribution dequant-accumulated in f32) ->
                  requantize -> allgather -> dequantize
  reduce_scatter  same ring phase, no allgather (output stays exact f32
                  accumulation of dequantized partials)
  allgather       quantize once -> all_gather payload+scales -> dequantize

Every wire transfer carries int8 payload plus one scale per `block`
elements (default 256, f32 scales), so bytes on the wire are
``(1 + scale_bytes/block) / itemsize`` of the native arm — ~0.25x for f32
operands at block 256 (`wire_bytes` below is the exact accounting the
bench asserts against).

Error model: one quantization step has per-element error bounded by
``amax_block / 254`` (symmetric round-to-nearest over [-127, 127]).  The
allreduce quantizes each ORIGINAL contribution once and the reduced
chunk once more for the allgather phase — two roundings on the data path
regardless of device count (a requantize-per-hop ring would grow the
error linearly in n), keeping measured max-abs-err well under 1e-2
relative on unit-scale data (the numerics suite pins this).  All-zero blocks are exact (scale 0 maps to q 0); outliers only
widen their own 256-element block's step.

Only SUM and AVG over real float operands are expressible: int/bool
payloads have no scale to quantize against, MAX/MIN/PROD do not commute
with per-block rescaling, and MAXLOC/MINLOC carry exact indices.  Anything
else raises ``ValueError`` here rather than silently falling through
(``op.quantizable`` is the single gate).

Programs are jitted shard_map executables cached in the wrapped
DeviceComm's cache, keyed on (collective, op, shape-BUCKET, dtype, block,
scale dtype, ndev): per-rank payloads are flattened and zero-padded to a
power-of-two bucket of whole (ndev x block) units *outside* the cached
program, so all shapes within a 2x band share one executable.
"""

from __future__ import annotations

import math

import numpy as np

from .. import trace
from ..core import var as _var
from ..op import SUM, Op, quantizable

_var.register("coll", "quant", "block", 256, type=int, level=3,
              help="Elements per quantization block (one scale each).")
_var.register("coll", "quant", "scale_dtype", "float32", type=str, level=4,
              help="Dtype of the per-block scales on the wire "
                   "(float32|bfloat16).")

# int8 symmetric range: round() maps to [-127, 127] so the grid is
# symmetric (no -128 asymmetry) and amax round-trips exactly
_QMAX = 127.0


def check_quantizable(op: Op, dtype) -> None:
    """Reject (op, dtype) combos the quantized tier cannot carry."""
    if quantizable(op, dtype):
        return
    if op.name in ("maxloc", "minloc"):
        why = "MAXLOC/MINLOC pairs carry exact indices"
    elif op.name not in ("sum", "avg"):
        why = f"op {op.name!r} does not commute with per-block rescaling"
    else:
        why = f"dtype {np.dtype(dtype).name!r} has no scale to quantize"
    raise ValueError(
        f"quantized collectives support SUM/AVG over float operands only: "
        f"{why} (op={op.name!r}, dtype={np.dtype(dtype).name})")


def _params(block, scale_dtype):
    import jax.numpy as jnp

    block = int(block if block is not None
                else _var.get("coll_quant_block", 256))
    if block < 1:
        raise ValueError(f"quantization block must be >= 1, got {block}")
    sdt = scale_dtype if scale_dtype is not None \
        else _var.get("coll_quant_scale_dtype", "float32")
    if isinstance(sdt, str) and sdt == "bfloat16":
        sdt = jnp.bfloat16          # np.dtype can't parse the name alone
    sdt = np.dtype(sdt)
    if sdt.name not in ("float32", "bfloat16"):
        raise ValueError(
            f"scale_dtype must be float32 or bfloat16, got {sdt.name}")
    return block, sdt


# -- pure block codecs (traceable; usable inside any shard_map) -------------

def quantize_blocks(x, block: int, scale_dtype=None):
    """(..., L) with L % block == 0 -> (int8 (..., L), scales (..., L/block)).

    Symmetric per-block quantization: scale = amax/127 computed in f32;
    all-zero blocks get scale 0 and decode exactly to zero."""
    import jax.numpy as jnp

    scale_dtype = scale_dtype if scale_dtype is not None else jnp.float32
    xb = x.reshape(x.shape[:-1] + (x.shape[-1] // block, block))
    xf = xb.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / _QMAX        # (..., nblk)
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(xf / safe[..., None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8).reshape(x.shape), scale.astype(scale_dtype)


def dequantize_blocks(q, scale, block: int, dtype=None):
    """Inverse of :func:`quantize_blocks`; accumulation stays in f32
    unless `dtype` narrows it at the end."""
    import jax.numpy as jnp

    qb = q.reshape(q.shape[:-1] + (q.shape[-1] // block, block))
    x = qb.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]
    x = x.reshape(q.shape)
    return x if dtype is None else x.astype(dtype)


# -- named-axis primitives (for use INSIDE shard_map programs) --------------

def _reduce_scatter_quant(chunks, axis: str, n: int, block: int,
                          scale_dtype):
    """chunks: (n, C) f32 with C % block == 0 -> (C,) f32: this device's
    fully reduced chunk (device d owns chunk d).

    The original local contributions are quantized exactly ONCE, the
    int8 payload + scales travel the all_to_all wire phase, and every
    peer's contribution is dequantized and accumulated in f32.  Unlike a
    requantize-per-hop ring (whose error grows linearly in n because
    partial SUMS get re-rounded n-1 times), the data path here pays a
    single rounding regardless of device count — same (n-1)*C quantized
    elements on the wire per device.
    """
    import jax.numpy as jnp
    from jax import lax

    if n == 1:
        return chunks[0]
    q, s = quantize_blocks(chunks, block, scale_dtype)
    q = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    s = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=True)
    return jnp.sum(dequantize_blocks(q, s, block), axis=0)


def _all_gather_quant(x, axis: str, n: int, block: int, scale_dtype):
    """x: (C,) f32 with C % block == 0 -> (n, C) f32: row j = device j's
    vector, moved over the wire as int8+scales."""
    from jax import lax

    q, s = quantize_blocks(x, block, scale_dtype)
    qg = lax.all_gather(q, axis, axis=0)              # (n, C) int8
    sg = lax.all_gather(s, axis, axis=0)              # (n, C/block)
    return dequantize_blocks(qg, sg, block)


def psum_quant(x, axis: str, n: int, avg: bool = False, block: int = None,
               scale_dtype=None, op: Op = None):
    """Block-quantized allreduce of `x` over mesh axis `axis`, for use
    inside shard_map (the gradient-sync primitive).

    quantize -> reduce_scatter wire phase (peer contributions
    dequant-accumulated in f32) -> requantize -> allgather ->
    dequantize.  `n` is the static axis size
    (shard_map bodies cannot read it dynamically on every jax version).
    """
    import jax.numpy as jnp

    if op is not None:
        check_quantizable(op, x.dtype)
        avg = avg or op.name == "avg"
    block, sdt = _params(block, scale_dtype)
    if n == 1:
        return x / n if avg else x
    shape, dtype = x.shape, x.dtype
    L = int(np.prod(shape)) if shape else 1
    unit = n * block
    Lpad = unit * max(1, math.ceil(L / unit))
    flat = x.reshape(-1).astype(jnp.float32)
    if Lpad != L:
        flat = jnp.pad(flat, (0, Lpad - L))
    chunks = flat.reshape(n, Lpad // n)
    acc = _reduce_scatter_quant(chunks, axis, n, block, sdt)
    if avg:
        acc = acc / n
    full = _all_gather_quant(acc, axis, n, block, sdt)   # (n, C)
    return full.reshape(-1)[:L].reshape(shape).astype(dtype)


# -- wire-byte accounting ---------------------------------------------------

def padded_len(count: int, n: int, block: int) -> int:
    """Flattened per-rank element count after padding to whole
    (n x block) units (what actually travels)."""
    unit = n * block
    return unit * max(1, math.ceil(int(count) / unit))


def wire_bytes(coll: str, count: int, n: int, dtype, block: int = None,
               scale_dtype=None) -> dict:
    """Exact per-device wire bytes of the quantized vs native arm for
    `count` elements of `dtype` over an `n`-device axis.

    Ring costs: allreduce = 2(n-1) chunk transfers (reduce_scatter +
    allgather phases), reduce_scatter/allgather = (n-1).  The quantized
    chunk carries int8 payload + one scale per block; the native chunk
    carries full-precision elements.  Returns quant/native byte totals
    and their ratio (the bench's byte-accounting column).
    """
    block, sdt = _params(block, scale_dtype)
    esize = np.dtype(dtype).itemsize
    ssize = sdt.itemsize
    hops = {"allreduce": 2 * (n - 1), "reduce_scatter": n - 1,
            "allgather": n - 1}.get(coll)
    if hops is None:
        raise ValueError(f"no quantized arm for collective {coll!r}")
    chunk = padded_len(count, n, block) // n
    quant = hops * chunk * (1 + ssize / block)
    native = hops * math.ceil(int(count) / n) * esize
    return {"quant_bytes": int(round(quant)), "native_bytes": int(native),
            "ratio": quant / native if native else float("inf")}


# -- canonical-layout engine (mirrors DeviceComm's entry points) ------------

def _span_args(wb: dict, block: int, sdt, roundings: int,
               requantize_count: int) -> dict:
    """Trace-span payload for one quantized execution: the EQuARX
    accounting (wire bytes, block config, how many stochastic roundings
    touch each element, whether an accumulated value is requantized)."""
    ratio = wb["ratio"]
    return {"wire_bytes": wb["quant_bytes"],
            "native_bytes": wb["native_bytes"],
            "ratio": round(ratio, 4) if math.isfinite(ratio) else None,
            "block": block, "scale_dtype": sdt.name,
            "roundings": roundings, "requantize_count": requantize_count}


def grad_bucket_span_args(nbytes: int, n: int, dtype, block: int = None,
                          scale_dtype=None) -> dict:
    """EQuARX accounting for ONE quantized grad-sync bucket of `nbytes`
    raw gradient bytes allreduced over `n` devices — the detail payload
    attached to parallel/overlap's per-bucket decision events and spans.
    psum_quant's path rounds each element twice (quantize + the
    post-accumulate requantize) and requantizes the accumulated value
    once, hence the fixed counts."""
    block, sdt = _params(block, scale_dtype)
    count = max(1, int(nbytes) // np.dtype(dtype).itemsize)
    wb = wire_bytes("allreduce", count, n, dtype, block, sdt)
    return _span_args(wb, block, sdt, roundings=2, requantize_count=1)


class QuantDeviceComm:
    """Quantized collectives over a DeviceComm's mesh axis, same
    canonical (R, *elem) dim-0-sharded layout and executable cache
    (reached as ``dc.quant``)."""

    def __init__(self, dc) -> None:
        self.dc = dc

    # local rows fold in f32 before any wire quantization, so the r
    # co-resident ranks' contribution is exact
    @staticmethod
    def _fold32(xs):
        import jax.numpy as jnp

        return jnp.sum(xs.astype(jnp.float32), axis=0)

    def _padded(self, x, L: int, Lpad: int):
        """Flatten rows + zero-pad OUTSIDE the cached program (cheap ops;
        the heavy executable is shared across every shape in the
        bucket), re-pinned to the canonical sharding."""
        import jax
        import jax.numpy as jnp

        flat = x.reshape((x.shape[0], -1))
        if Lpad != L:
            flat = jnp.pad(flat, ((0, 0), (0, Lpad - L)))
        return jax.device_put(flat, self.dc.sharding())

    def _spc(self, name):
        if self.dc.spc is not None:
            self.dc.spc.inc(name)

    def allreduce(self, x, op: Op = SUM, block: int = None,
                  scale_dtype=None):
        """(R, *e) -> (R, *e): every row <- quantized op over all rows."""
        import jax.numpy as jnp

        check_quantizable(op, x.dtype)
        block, sdt = _params(block, scale_dtype)
        dc, n = self.dc, self.dc.n
        R, elem = x.shape[0], x.shape[1:]
        L = int(np.prod(elem)) if elem else 1
        Lpad = padded_len(L, n, block)
        avg = op.name == "avg"
        key = ("quant_allreduce", op.name, R, Lpad, str(x.dtype),
               block, sdt.name, n)

        def build():
            def inner(xs):                       # (r, Lpad) local rows
                folded = self._fold32(xs)
                if n == 1:
                    out = folded / R if avg else folded
                else:
                    chunks = folded.reshape(n, Lpad // n)
                    acc = _reduce_scatter_quant(chunks, dc.axis, n,
                                                     block, sdt)
                    if avg:
                        # average over CONTRIBUTIONS: R ranks total,
                        # r = R/n of them folded locally per device
                        acc = acc / R
                    out = _all_gather_quant(acc, dc.axis, n, block,
                                            sdt).reshape(-1)
                out = out.astype(x.dtype)
                return jnp.broadcast_to(out[None], xs.shape)
            return dc._shard_map(inner, dc._spec, dc._spec)

        self._spc("device_quant_collectives")
        from .. import numerics
        if numerics.enabled:
            # live SNR of the same per-block rounding the wire applies,
            # measured on the actual payload (numerics quant-SNR sentry)
            numerics.observe_quant_snr("allreduce", x, block, sdt)
        xp = self._padded(x, L, Lpad)
        if trace.enabled:
            # allreduce = quantized reduce_scatter ring (accumulate in
            # f32, requantize once per forward) + quantized allgather
            with trace.span("quant:allreduce", "quant", args=_span_args(
                    wire_bytes("allreduce", L, n, x.dtype, block, sdt),
                    block, sdt, roundings=2, requantize_count=1)):
                out = dc._compiled(key, build)(xp)
        else:
            out = dc._compiled(key, build)(xp)
        return out[:, :L].reshape((R,) + elem)

    def reduce_scatter(self, x, op: Op = SUM, block: int = None,
                       scale_dtype=None):
        """(R, R*b, *e) -> (R, b, *e): row i = quantized-reduced block i
        (the ring phase alone; result is the f32 accumulation of the
        dequantized per-hop partials, never requantized)."""
        import jax.numpy as jnp

        check_quantizable(op, x.dtype)
        block, sdt = _params(block, scale_dtype)
        dc, n = self.dc, self.dc.n
        R = x.shape[0]
        if x.shape[1] % R:
            raise ValueError(
                f"reduce_scatter needs dim 1 divisible by {R} rows, "
                f"got {x.shape}")
        b, elem = x.shape[1] // R, x.shape[2:]
        r = R // n
        E = int(np.prod(elem)) if elem else 1
        # pad per-CHUNK (a chunk = one device's r result rows) so rank
        # boundaries survive the padding
        C = r * b * E
        Cpad = block * max(1, math.ceil(C / block))
        avg = op.name == "avg"
        key = ("quant_reduce_scatter", op.name, R, b, E, Cpad,
               str(x.dtype), block, sdt.name, n)

        def build():
            def inner(xs):                       # (r, R*b*E) flat rows
                folded = self._fold32(xs)        # (R*b*E,)
                chunks = folded.reshape(n, C)
                if Cpad != C:
                    chunks = jnp.pad(chunks, ((0, 0), (0, Cpad - C)))
                acc = _reduce_scatter_quant(chunks, dc.axis, n,
                                                 block, sdt)
                if avg:
                    # R contributions total (r folded locally x n devices)
                    acc = acc / R
                return acc[:C].reshape((r, b * E)).astype(x.dtype)
            return dc._shard_map(inner, dc._spec, dc._spec)

        self._spc("device_quant_collectives")
        from .. import numerics
        if numerics.enabled:
            numerics.observe_quant_snr("reduce_scatter", x, block, sdt)
        flat = self._padded(x, R * b * E, R * b * E)
        if trace.enabled:
            # ring phase alone: one rounding per element, accumulation
            # stays f32 (never requantized)
            with trace.span("quant:reduce_scatter", "quant",
                            args=_span_args(
                    wire_bytes("reduce_scatter", R * b * E, n, x.dtype,
                               block, sdt),
                    block, sdt, roundings=1, requantize_count=0)):
                out = dc._compiled(key, build)(flat)
        else:
            out = dc._compiled(key, build)(flat)
        return out.reshape((R, b) + elem)

    def allgather(self, x, block: int = None, scale_dtype=None):
        """(R, b, *e) -> (R, R*b, *e): every row = concat of all rows,
        each contribution quantized exactly once on the wire."""
        import jax.numpy as jnp

        check_quantizable(SUM, x.dtype)     # dtype gate only
        if x.ndim < 2:
            raise ValueError(
                f"allgather needs the canonical (R, b, *e) layout, "
                f"got shape {x.shape}")
        block, sdt = _params(block, scale_dtype)
        dc, n = self.dc, self.dc.n
        R, b, e = x.shape[0], x.shape[1], x.shape[2:]
        L = b * (int(np.prod(e)) if e else 1)    # elements per rank row
        Lpad = block * max(1, math.ceil(L / block))
        key = ("quant_allgather", R, Lpad, str(x.dtype), block,
               sdt.name, n)

        def build():
            def inner(xs):                       # (r, Lpad)
                flat = xs.reshape(-1)            # r rank rows end to end
                full = _all_gather_quant(flat, dc.axis, n, block, sdt)
                full = full.reshape(-1).astype(x.dtype)   # (R*Lpad,)
                # stay fully padded inside the program: the unpadded L
                # is NOT in the cache key, so two shapes sharing a pad
                # bucket must share this executable verbatim (the trim
                # happens outside, like allreduce)
                return jnp.broadcast_to(full[None],
                                        (xs.shape[0],) + full.shape)
            return dc._shard_map(inner, dc._spec, dc._spec)

        self._spc("device_quant_collectives")
        from .. import numerics
        if numerics.enabled:
            numerics.observe_quant_snr("allgather", x, block, sdt)
        xp = self._padded(x, L, Lpad)
        if trace.enabled:
            # each contribution quantized exactly once on the wire
            with trace.span("quant:allgather", "quant", args=_span_args(
                    wire_bytes("allgather", L, n, x.dtype, block, sdt),
                    block, sdt, roundings=1, requantize_count=0)):
                out = dc._compiled(key, build)(xp)
        else:
            out = dc._compiled(key, build)(xp)
        out = out.reshape(R, R, Lpad)[:, :, :L]
        return out.reshape((R, R * b) + e)
