"""Trivial collectives for size-1 communicators (≙ ompi/mca/coll/self)."""

from __future__ import annotations

import numpy as np

from ..core.component import Component, component
from ..op import Op
from .framework import CollModule


class SelfModule(CollModule):
    def barrier(self, comm):
        pass

    def bcast(self, comm, buf, root: int = 0):
        return buf

    def reduce(self, comm, sendbuf, recvbuf=None, op: Op = None, root: int = 0):
        send = np.asarray(sendbuf if sendbuf is not None else recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        recvbuf[...] = send
        return recvbuf

    def allreduce(self, comm, sendbuf, recvbuf=None, op: Op = None):
        return self.reduce(comm, sendbuf, recvbuf, op)

    def gather(self, comm, sendbuf, recvbuf=None, root: int = 0):
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty((1,) + sendbuf.shape, sendbuf.dtype)
        recvbuf.reshape(1, -1)[0] = sendbuf.reshape(-1)
        return recvbuf

    def allgather(self, comm, sendbuf, recvbuf=None):
        return self.gather(comm, sendbuf, recvbuf)

    def scatter(self, comm, sendbuf, recvbuf=None, root: int = 0):
        parts = np.asarray(sendbuf).reshape(1, -1)
        if recvbuf is None:
            recvbuf = np.empty_like(parts[0])
        recvbuf.reshape(-1)[:] = parts[0]
        return recvbuf

    def alltoall(self, comm, sendbuf, recvbuf=None):
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(sendbuf)
        recvbuf[...] = sendbuf
        return recvbuf

    def scan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        return self.reduce(comm, sendbuf, recvbuf, op)


@component("coll", "self", priority=75)
class SelfColl(Component):
    name = "self"

    def query(self, comm):
        if getattr(comm, "size", 0) == 1:
            return self.priority, SelfModule()
        return None, None
