"""Optimized host collective algorithms + decision rules.

≙ the reference's algorithm library ompi/mca/coll/base/ (SURVEY.md Appendix A)
plus coll/tuned's decision machinery (coll_tuned_decision_fixed.c:55-104,
dynamic rules file coll_tuned_dynamic_file.c:58).

Algorithms implemented (reference file:line for the original):
  allreduce: recursive-doubling (coll_base_allreduce.c:133), ring (:344),
             segmented/pipelined ring (:621),
             Rabenseifner reduce-scatter+allgather (:973)
  bcast:     binomial tree (coll_base_bcast.c:333), pipeline (:277),
             chain (:305), knomial (:720), scatter+allgather (:774)
  reduce:    binomial tree (coll_base_reduce.c:476),
             in-order binary for non-commutative ops (:514)
  allgather: recursive-doubling (coll_base_allgather.c:85), ring (:330),
             neighbor-exchange (:456), bruck (:767 k=2)
  reduce_scatter_block: recursive-halving (coll_base_reduce_scatter.c:132),
             butterfly for any comm size (:691)
  alltoall:  pairwise (coll_base_alltoall.c:180), bruck (:239)
  barrier:   recursive-doubling (coll_base_barrier.c:188), bruck (:269)
  scan/exscan: recursive-doubling prefix (coll_base_scan.c:157)

Selection: fixed size/msg-size rules, overridable per-collective with the
``coll_tuned_<name>_algorithm`` variable and via a dynamic rules file named
by ``coll_tuned_dynamic_rules`` (lines: ``<coll> <min_comm> <min_bytes>
<algorithm>``, later lines win — the user-tunable escape hatch the reference
ships for cluster-specific tuning).

Non-commutative ops fall back to the in-order linear algorithms
(≙ coll_base_reduce.c:514 in-order binary for non-commutative).
"""

from __future__ import annotations

import math
import os
from typing import Optional, Sequence

import numpy as np

from ..core import var as _var
from ..core.component import Component, component
from ..op import Op
from ..p2p.request import wait_all
from .basic import BasicModule, T_ALLGATHER, T_ALLTOALL, T_BARRIER, T_BCAST, \
    T_GATHER, T_REDUCE, T_RSCAT, T_SCAN, T_SCATTER, _inplace
from .framework import CollModule


def _sum_default(op):
    from .. import op as _op
    return op or _op.SUM


# ---------------------------------------------------------------------------
# allreduce algorithms
# ---------------------------------------------------------------------------

def allreduce_recursive_doubling(comm, send: np.ndarray, recv: np.ndarray,
                                 op: Op) -> None:
    """coll_base_allreduce.c:133 — log2(p) rounds, full vector each round.
    Best for small messages. Non-power-of-2 handled with the standard
    fold-in/fold-out of extra ranks."""
    size, rank = comm.size, comm.rank
    recv[...] = send
    pof2 = 1 << (size.bit_length() - 1)
    rem = size - pof2
    tmp = np.empty_like(recv)
    # fold extras: ranks [0, 2*rem) pair up (even sends to odd)
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.send(recv, rank + 1, T_REDUCE)
            newrank = -1
        else:
            comm.recv(tmp, rank - 1, T_REDUCE)
            recv[...] = op(tmp, recv)
            newrank = rank // 2
    else:
        newrank = rank - rem
    if newrank >= 0:
        mask = 1
        while mask < pof2:
            peer_new = newrank ^ mask
            peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
            comm.sendrecv(recv, peer, tmp, peer, T_REDUCE, T_REDUCE)
            if op.commutative or peer < rank:
                recv[...] = op(tmp, recv)
            else:
                recv[...] = op(recv.copy(), tmp)
            mask <<= 1
    # unfold
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.recv(recv, rank + 1, T_REDUCE)
        else:
            comm.send(recv, rank - 1, T_REDUCE)


def _ring_bounds(n: int, size: int) -> np.ndarray:
    """Chunk boundaries of the ring schedule (np.array_split convention:
    the first n%size chunks get the extra element) — the ONE partitioning
    both ring allreduce variants and their allgather phases share."""
    base, extra = divmod(n, size)
    sizes = np.full(size, base, np.int64)
    sizes[:extra] += 1
    return np.concatenate([[0], np.cumsum(sizes)])


def _ring_allgather_phase(comm, flat: np.ndarray, bounds: np.ndarray,
                          tag: int) -> None:
    """The p-1 allgather rounds shared by ring and segmented-ring
    allreduce: each step forwards the chunk received last step."""
    size, rank = comm.size, comm.rank
    right, left = (rank + 1) % size, (rank - 1) % size
    for step in range(size - 1):
        s = (rank + 1 - step) % size
        r = (rank - step) % size
        inbox = np.empty(int(bounds[r + 1] - bounds[r]), flat.dtype)
        comm.sendrecv(flat[bounds[s]:bounds[s + 1]], right, inbox, left,
                      tag, tag)
        flat[bounds[r]:bounds[r + 1]] = inbox


def allreduce_ring(comm, send: np.ndarray, recv: np.ndarray, op: Op) -> None:
    """coll_base_allreduce.c:344 — reduce-scatter ring then allgather ring;
    bandwidth-optimal 2(p-1)/p·n bytes per rank. The identical neighbor-
    exchange schedule ring attention uses (SURVEY.md §5.7)."""
    size, rank = comm.size, comm.rank
    recv[...] = send
    if size == 1:
        return
    flat = recv.reshape(-1)
    bounds = _ring_bounds(flat.size, size)
    right, left = (rank + 1) % size, (rank - 1) % size
    # reduce-scatter phase
    for step in range(size - 1):
        s = (rank - step) % size
        r = (rank - step - 1) % size
        inbox = np.empty(int(bounds[r + 1] - bounds[r]), flat.dtype)
        comm.sendrecv(flat[bounds[s]:bounds[s + 1]], right, inbox, left,
                      T_REDUCE, T_REDUCE)
        seg = flat[bounds[r]:bounds[r + 1]]
        seg[...] = op(inbox, seg)
    _ring_allgather_phase(comm, flat, bounds, T_ALLGATHER)


def allreduce_rabenseifner(comm, send: np.ndarray, recv: np.ndarray,
                           op: Op) -> None:
    """coll_base_allreduce.c:973 — recursive-halving reduce-scatter followed
    by recursive-doubling allgather; best large-message algorithm on trees."""
    size, rank = comm.size, comm.rank
    recv[...] = send
    if size == 1:
        return
    flat = recv.reshape(-1)
    pof2 = 1 << (size.bit_length() - 1)
    rem = size - pof2
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.send(flat, rank + 1, T_REDUCE)
            newrank = -1
        else:
            tmp = np.empty_like(flat)
            comm.recv(tmp, rank - 1, T_REDUCE)
            flat[...] = op(tmp, flat)
            newrank = rank // 2
    else:
        newrank = rank - rem

    def block_span(nr: int, down_to_mask: int):
        """Span nr holds after the halving decisions for masks ≥ down_to_mask
        (halving may be uneven when the vector doesn't split in two exactly,
        so spans must be recomputed per rank, never assumed equal)."""
        blo, bhi = 0, flat.size
        m = pof2 >> 1
        while m >= down_to_mask:
            mid = blo + (bhi - blo) // 2
            if nr & m:
                blo = mid
            else:
                bhi = mid
            m >>= 1
        return blo, bhi

    if newrank >= 0:
        # recursive halving reduce-scatter over pof2 ranks
        mask = pof2 >> 1
        lo, hi = 0, flat.size
        while mask > 0:
            peer_new = newrank ^ mask
            peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
            mid = lo + (hi - lo) // 2
            if newrank & mask:
                keep_lo, keep_hi = mid, hi
                send_lo, send_hi = lo, mid
            else:
                keep_lo, keep_hi = lo, mid
                send_lo, send_hi = mid, hi
            inbox = np.empty(keep_hi - keep_lo, flat.dtype)
            comm.sendrecv(flat[send_lo:send_hi], peer, inbox, peer,
                          T_RSCAT, T_RSCAT)
            seg = flat[keep_lo:keep_hi]
            if op.commutative or peer < rank:
                seg[...] = op(inbox, seg)
            else:
                seg[...] = op(seg.copy(), inbox)
            lo, hi = keep_lo, keep_hi
            mask >>= 1
        # recursive doubling allgather, retracing in reverse; the peer's
        # current span is its own halving-path block, which can differ from
        # ours by one element per level on non-power-of-two vector sizes
        mask = 1
        while mask < pof2:
            peer_new = newrank ^ mask
            peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
            plo, phi = block_span(peer_new, mask)
            inbox = np.empty(phi - plo, flat.dtype)
            comm.sendrecv(flat[lo:hi], peer, inbox, peer,
                          T_ALLGATHER, T_ALLGATHER)
            flat[plo:phi] = inbox
            lo, hi = min(lo, plo), max(hi, phi)
            mask <<= 1
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.recv(flat, rank + 1, T_BCAST)
        else:
            comm.send(flat, rank - 1, T_BCAST)


def allreduce_segmented_ring(comm, send: np.ndarray, recv: np.ndarray,
                             op: Op, segsize: int) -> None:
    """coll_base_allreduce.c:621 — ring reduce-scatter+allgather where each
    per-step chunk transfer is pipelined in ``segsize``-byte segments: the
    next segment's sendrecv is posted (isend+irecv) before the current
    segment's reduction runs, overlapping wire time with compute. This is
    the segmented/pipelined discipline the whole coll/base library applies
    to large messages (segsize parameters throughout, SURVEY.md §5.7)."""
    size, rank = comm.size, comm.rank
    recv[...] = send
    if size == 1:
        return
    flat = recv.reshape(-1)
    seg_items = max(1, segsize // flat.dtype.itemsize)
    bounds = _ring_bounds(flat.size, size)
    right, left = (rank + 1) % size, (rank - 1) % size

    def spans(chunk):
        lo, hi = int(bounds[chunk]), int(bounds[chunk + 1])
        return [(s, min(s + seg_items, hi)) for s in range(lo, hi, seg_items)] \
            or [(lo, lo)]

    # reduce-scatter phase, depth-2 pipelined per chunk
    for step in range(size - 1):
        s_spans = spans((rank - step) % size)
        r_spans = spans((rank - step - 1) % size)
        n = max(len(s_spans), len(r_spans))
        inboxes = [np.empty(b - a, flat.dtype) for a, b in r_spans]
        sreqs, rreqs = {}, {}

        def post(j):
            if j < len(r_spans):
                rreqs[j] = comm.irecv(inboxes[j], left, T_REDUCE)
            if j < len(s_spans):
                a, b = s_spans[j]
                sreqs[j] = comm.isend(flat[a:b], right, T_REDUCE)

        post(0)
        for j in range(n):
            post(j + 1)             # next segment in flight…
            if j in rreqs:
                rreqs[j].wait()     # …while this one reduces
                a, b = r_spans[j]
                seg = flat[a:b]
                seg[...] = op(inboxes[j], seg)
            if j in sreqs:
                sreqs[j].wait()
    # allgather phase: pure copy — single-segment pipelining gains nothing
    _ring_allgather_phase(comm, flat, bounds, T_ALLGATHER)


# ---------------------------------------------------------------------------
# bcast / reduce trees
# ---------------------------------------------------------------------------

def _binomial_children(rank: int, size: int, root: int):
    """Binomial tree rooted at root (≙ coll_base_topo.c:331 bmtree)."""
    vrank = (rank - root) % size
    children = []
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + root) % size
            return parent, children
        child = vrank | mask
        if child < size:
            children.append((child + root) % size)
        mask <<= 1
    return None, children


def bcast_binomial(comm, buf: np.ndarray, root: int) -> None:
    """coll_base_bcast.c:333."""
    parent, children = _binomial_children(comm.rank, comm.size, root)
    if parent is not None:
        comm.recv(buf, parent, T_BCAST)
    reqs = [comm.isend(buf, c, T_BCAST) for c in reversed(children)]
    wait_all(reqs)


def bcast_scatter_allgather(comm, buf: np.ndarray, root: int) -> None:
    """coll_base_bcast.c:774 — binomial scatter then ring allgather;
    bandwidth-optimal for large messages."""
    size, rank = comm.size, comm.rank
    flat = buf.reshape(-1)
    counts = [len(c) for c in np.array_split(np.arange(flat.size), size)]
    displs = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(int)
    vrank = (rank - root) % size
    # binomial scatter of segments
    parent, _children = _binomial_children(rank, size, root)
    mask = 1 << max(0, size.bit_length() - 1)
    # receive my subtree's span from parent
    def span(vr, m):
        lo = displs[vr]
        hi_rank = min(size - 1, vr + m - 1)
        hi = displs[hi_rank] + counts[hi_rank]
        return lo, hi
    if parent is not None:
        m = 1
        while not (vrank & m):
            m <<= 1
        lo, hi = span(vrank, m)
        comm.recv(flat[lo:hi], parent, T_BCAST)
    m = 1
    while m < size:
        if vrank & m:
            break
        m <<= 1
    m >>= 1
    while m >= 1:
        vchild = vrank | m
        if vchild < size:
            lo, hi = span(vchild, m)
            comm.send(flat[lo:hi], (vchild + root) % size, T_BCAST)
        m >>= 1
    # ring allgather of segments
    right, left = (rank + 1) % size, (rank - 1) % size
    for step in range(size - 1):
        sv = (vrank - step) % size
        rv = (vrank - step - 1) % size
        s_lo, s_hi = displs[sv], displs[sv] + counts[sv]
        r_lo, r_hi = displs[rv], displs[rv] + counts[rv]
        inbox = np.empty(r_hi - r_lo, flat.dtype)
        comm.sendrecv(flat[s_lo:s_hi], right, inbox, left,
                      T_ALLGATHER, T_ALLGATHER)
        flat[r_lo:r_hi] = inbox


def _segments(flat: np.ndarray, segsize: int):
    seg_items = max(1, segsize // flat.dtype.itemsize)
    return [flat[i:i + seg_items] for i in range(0, flat.size, seg_items)] \
        or [flat]


def bcast_pipeline(comm, buf: np.ndarray, root: int, segsize: int,
                   chains: int = 1) -> None:
    """coll_base_bcast.c:277 (pipeline) / :305 (chain): non-root ranks form
    ``chains`` chains hanging off the root; the message streams down each
    chain in segsize segments, every rank forwarding segment j to its child
    while segment j+1 is still arriving (all receives pre-posted). pipeline
    = chain with chains=1."""
    size, rank = comm.size, comm.rank
    if size == 1:
        return
    flat = buf.reshape(-1)
    segs = _segments(flat, segsize)
    chains = max(1, min(chains, size - 1))
    clen = -(-(size - 1) // chains)          # ceil chain length
    if rank == root:
        heads = [(root + 1 + c * clen) % size
                 for c in range(chains) if c * clen < size - 1]
        sreqs = []
        for s in segs:
            for h in heads:
                sreqs.append(comm.isend(s, h, T_BCAST))
        wait_all(sreqs)
        return
    idx = (rank - root) % size - 1           # position among non-root ranks
    pos = idx % clen
    parent = root if pos == 0 else (rank - 1 + size) % size
    nxt = idx + 1
    child = None
    if pos + 1 < clen and nxt < size - 1:
        child = (rank + 1) % size
    rreqs = [comm.irecv(s, parent, T_BCAST) for s in segs]
    sreqs = []
    for j, s in enumerate(segs):
        rreqs[j].wait()
        if child is not None:
            sreqs.append(comm.isend(s, child, T_BCAST))
    wait_all(sreqs)


def _knomial_tree(rank: int, size: int, root: int, radix: int):
    """K-nomial tree (≙ coll_base_topo.c:479 kmtree): a vrank's parent
    clears its least-significant nonzero base-radix digit; its children add
    d*mask for every level below that digit."""
    vrank = (rank - root) % size
    children = []
    mask = 1
    parent = None
    while mask < size:
        digit = (vrank // mask) % radix
        if digit:
            parent = ((vrank - digit * mask) + root) % size
            break
        for d in range(1, radix):
            child = vrank + d * mask
            if child < size:
                children.append((child + root) % size)
        mask *= radix
    return parent, children


def bcast_knomial(comm, buf: np.ndarray, root: int, radix: int) -> None:
    """coll_base_bcast.c:720 — radix-k binomial tree: shallower than
    binomial (log_k p rounds) at the cost of k-1 sends per internal node;
    wins for small messages where latency dominates."""
    parent, children = _knomial_tree(comm.rank, comm.size, root,
                                     max(2, radix))
    if parent is not None:
        comm.recv(buf, parent, T_BCAST)
    # farthest (largest-subtree) children first, like the reference
    reqs = [comm.isend(buf, c, T_BCAST) for c in reversed(children)]
    wait_all(reqs)


def reduce_inorder_binary(comm, send: np.ndarray, recv: Optional[np.ndarray],
                          op: Op, root: int) -> Optional[np.ndarray]:
    """coll_base_reduce.c:514 — in-order binary tree for NON-commutative
    ops: the reduction combines rank ranges strictly as
    op(ranks lo..mid-1, ranks mid..hi), so the result equals the canonical
    left-to-right fold regardless of tree shape."""
    rank = comm.rank

    def reduce_range(lo: int, hi: int):
        """Value of fold(lo..hi), landing on rank lo; None elsewhere."""
        if lo == hi:
            return send.copy() if rank == lo else None
        mid = (lo + hi + 1) // 2
        if rank < mid:
            v = reduce_range(lo, mid - 1)
            if rank == lo:
                tmp = np.empty_like(send)
                comm.recv(tmp, mid, T_REDUCE)
                return op(v, tmp)        # left range before right range
            return None
        v = reduce_range(mid, hi)
        if rank == mid:
            comm.send(v, lo, T_REDUCE)
        return None

    acc = reduce_range(0, comm.size - 1)
    if root != 0:                        # relocate the fold to the root
        if rank == 0:
            comm.send(acc, root, T_REDUCE)
            return None
        if rank == root:
            acc = np.empty_like(send)
            comm.recv(acc, 0, T_REDUCE)
    if rank != root:
        return None
    if recv is None:
        recv = np.empty_like(send)
    recv[...] = acc
    return recv


def reduce_binomial(comm, send: np.ndarray, recv: Optional[np.ndarray],
                    op: Op, root: int) -> Optional[np.ndarray]:
    """coll_base_reduce.c:476 — commutative ops only (callers guard)."""
    acc = send.copy()
    rank, size = comm.rank, comm.size
    vrank = (rank - root) % size
    tmp = np.empty_like(acc)
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + root) % size
            comm.send(acc, parent, T_REDUCE)
            return None
        vchild = vrank | mask
        if vchild < size:
            comm.recv(tmp, (vchild + root) % size, T_REDUCE)
            acc = op(tmp, acc)
        mask <<= 1
    if recv is None:
        recv = np.empty_like(send)
    recv[...] = acc
    return recv


def reduce_pipeline(comm, send: np.ndarray, recv: Optional[np.ndarray],
                    op: Op, root: int, segsize: int) -> Optional[np.ndarray]:
    """coll_base_reduce.c:414 — segmented chain toward the root: each rank
    receives its child's partial segment, folds it (own value as the LEFT
    operand, so the fold is associativity-equivalent to the canonical
    order), and forwards — segment k+1 arrives while segment k reduces.
    Like every segmented algorithm, valid for ELEMENTWISE ops only (all
    MPI predefined ops are; whole-buffer user ops go through the in-order
    tree instead)."""
    size, rank = comm.size, comm.rank
    vrank = (rank - root) % size
    acc = np.asarray(send).copy()
    flat = acc.reshape(-1)
    segs = _segments(flat, segsize)
    child = ((vrank + 1) + root) % size if vrank + 1 < size else None
    parent = ((vrank - 1) + root) % size if vrank > 0 else None
    rreqs = []
    if child is not None:
        inboxes = [np.empty_like(s) for s in segs]
        rreqs = [comm.irecv(b, child, T_REDUCE) for b in inboxes]
    sreqs = []
    for j, s in enumerate(segs):
        if child is not None:
            rreqs[j].wait()
            s[...] = op(s.copy(), inboxes[j])   # own left, child right
        if parent is not None:
            sreqs.append(comm.isend(s, parent, T_REDUCE))
    wait_all(sreqs)
    if rank != root:
        return None
    if recv is None:
        recv = np.empty_like(np.asarray(send))
    recv[...] = acc
    return recv


def gather_binomial(comm, send: np.ndarray, recv: Optional[np.ndarray],
                    root: int) -> Optional[np.ndarray]:
    """coll_base_gather.c:41 — binomial tree: each internal node forwards
    its whole contiguous vrank-subtree block in one message (log p rounds,
    vs p-1 messages at the linear root)."""
    size, rank = comm.size, comm.rank
    vrank = (rank - root) % size
    row = np.asarray(send).reshape(-1)
    # scratch = only MY subtree (lowbit(vrank) rows; the root holds all):
    # a leaf allocates 1 row, not O(p·n) (r2 review finding)
    subtree = size if vrank == 0 else min(vrank & -vrank, size - vrank)
    work = np.empty((subtree, row.size), row.dtype)
    work[0] = row
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + root) % size
            comm.send(work[:min(mask, size - vrank)], parent, T_GATHER)
            return None
        vchild = vrank | mask
        if vchild < size:
            cnt = min(mask, size - vchild)
            comm.recv(work[mask:mask + cnt], (vchild + root) % size,
                      T_GATHER)
        mask <<= 1
    if recv is None:
        recv = np.empty((size,) + np.asarray(send).shape, row.dtype)
    out = recv.reshape(size, -1)
    for v in range(size):            # un-rotate vrank order → global ranks
        out[(v + root) % size] = work[v]
    return recv


def scatter_binomial(comm, send: Optional[np.ndarray], recv: np.ndarray,
                     root: int) -> np.ndarray:
    """coll_base_scatter.c:63 — the gather tree reversed: the root peels
    off subtree blocks; each internal node forwards its children's."""
    size, rank = comm.size, comm.rank
    vrank = (rank - root) % size
    recv = np.asarray(recv)
    blk = recv.reshape(-1).size
    if vrank == 0:
        parts = np.asarray(send).reshape(size, -1)
        work = np.empty((size, blk), parts.dtype)
        for g in range(size):        # rotate global ranks → vrank order
            work[(g - root) % size] = parts[g]
    else:
        # my subtree block arrives from the parent in one message
        sub = 1
        while not (vrank & sub):
            sub <<= 1
        cnt = min(sub, size - vrank)
        work = np.empty((cnt, blk), recv.dtype)
        parent = ((vrank & ~sub) + root) % size
        comm.recv(work, parent, T_SCATTER)
    mask = 1
    while mask < size and not (vrank & mask):
        mask <<= 1
    m = mask >> 1
    while m >= 1:                    # forward sub-blocks, farthest first
        vchild = vrank | m
        if vchild < size:
            cnt = min(m, size - vchild)
            comm.send(np.ascontiguousarray(work[m:m + cnt]),
                      (vchild + root) % size, T_SCATTER)
        m >>= 1
    recv.reshape(-1)[:] = work[0]
    return recv


def barrier_double_ring(comm) -> None:
    """coll_base_barrier.c:116 — the token circles twice; 2p messages but
    only nearest-neighbor links (the topology-friendliest barrier)."""
    size, rank = comm.size, comm.rank
    token = np.zeros(0, np.uint8)
    right, left = (rank + 1) % size, (rank - 1) % size
    for _round in range(2):
        if rank == 0:
            comm.send(token, right, T_BARRIER)
            comm.recv(token, left, T_BARRIER)
        else:
            comm.recv(token, left, T_BARRIER)
            comm.send(token, right, T_BARRIER)


def allgatherv_ring(comm, send: np.ndarray, recv: np.ndarray,
                    counts: Sequence[int], displs: Sequence[int]) -> None:
    """coll_base_allgatherv.c:371 — the ring schedule with per-rank block
    sizes; p-1 neighbor exchanges instead of the basic component's p-1
    point-to-point pairs per rank."""
    size, rank = comm.size, comm.rank
    flat = recv.reshape(-1)
    flat[displs[rank]:displs[rank] + counts[rank]] = \
        np.asarray(send).reshape(-1)
    right, left = (rank + 1) % size, (rank - 1) % size
    for step in range(size - 1):
        s = (rank - step) % size
        d = (rank - step - 1) % size
        inbox = np.empty(counts[d], flat.dtype)
        comm.sendrecv(flat[displs[s]:displs[s] + counts[s]], right,
                      inbox, left, T_ALLGATHER, T_ALLGATHER)
        flat[displs[d]:displs[d] + counts[d]] = inbox


# ---------------------------------------------------------------------------
# allgather / alltoall / reduce_scatter / barrier
# ---------------------------------------------------------------------------

def allgather_recursive_doubling(comm, send: np.ndarray,
                                 recv: np.ndarray) -> None:
    """coll_base_allgather.c:85 — power-of-2 comms."""
    size, rank = comm.size, comm.rank
    parts = recv.reshape(size, -1)
    parts[rank] = send.reshape(-1)
    mask = 1
    while mask < size:
        peer = rank ^ mask
        block = (rank // mask) * mask
        peer_block = (peer // mask) * mask
        outbox = parts[block:block + mask]
        inbox = np.empty_like(parts[peer_block:peer_block + mask])
        comm.sendrecv(outbox, peer, inbox, peer, T_ALLGATHER, T_ALLGATHER)
        parts[peer_block:peer_block + mask] = inbox
        mask <<= 1


def allgather_ring(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_allgather.c:330 — the uniform-counts case of the ring
    schedule (one implementation, see allgatherv_ring)."""
    n = recv.reshape(comm.size, -1).shape[1]
    allgatherv_ring(comm, send, recv, [n] * comm.size,
                    [i * n for i in range(comm.size)])


def allgather_bruck(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_allgather.c:767 (k=2 Bruck): log2(p) rounds, any p."""
    size, rank = comm.size, comm.rank
    parts = recv.reshape(size, -1)
    # local rotation: my block first
    work = np.empty_like(parts)
    work[0] = send.reshape(-1)
    have = 1
    dist = 1
    while dist < size:
        peer_to = (rank - dist) % size
        peer_from = (rank + dist) % size
        blkcount = min(have, size - have)
        inbox = np.empty((blkcount, parts.shape[1]), parts.dtype)
        comm.sendrecv(work[:blkcount], peer_to, inbox, peer_from,
                      T_ALLGATHER, T_ALLGATHER)
        work[have:have + blkcount] = inbox[:min(blkcount, size - have)]
        have += blkcount
        dist <<= 1
    # un-rotate: work[i] holds block (rank + i) mod size
    for i in range(size):
        parts[(rank + i) % size] = work[i]


def alltoall_pairwise(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_alltoall.c:180 — p-1 exchange rounds with xor/offset pairing."""
    size, rank = comm.size, comm.rank
    sp = send.reshape(size, -1)
    rp = recv.reshape(size, -1)
    rp[rank] = sp[rank]
    for step in range(1, size):
        sendto = (rank + step) % size
        recvfrom = (rank - step) % size
        comm.sendrecv(sp[sendto], sendto, rp[recvfrom], recvfrom,
                      T_ALLTOALL, T_ALLTOALL)


def alltoall_bruck(comm, send: np.ndarray, recv: np.ndarray) -> None:
    """coll_base_alltoall.c:239 — log2(p) rounds for small messages."""
    size, rank = comm.size, comm.rank
    sp = send.reshape(size, -1)
    # phase 1: local rotation so block i is for rank (rank+i)%size
    work = np.roll(sp, -rank, axis=0).copy()
    pof = 1
    while pof < size:
        mask_blocks = [i for i in range(size) if i & pof]
        outbox = work[mask_blocks].copy()
        inbox = np.empty_like(outbox)
        comm.sendrecv(outbox, (rank + pof) % size, inbox, (rank - pof) % size,
                      T_ALLTOALL, T_ALLTOALL)
        work[mask_blocks] = inbox
        pof <<= 1
    # phase 3: inverse rotation + reversal
    rp = recv.reshape(size, -1)
    for i in range(size):
        rp[(rank - i) % size] = work[i]


def reduce_scatter_block_recursive_halving(comm, send: np.ndarray,
                                           recv: np.ndarray, op: Op) -> None:
    """coll_base_reduce_scatter.c:132 adapted to equal blocks (pof2 only)."""
    size, rank = comm.size, comm.rank
    flat = send.reshape(-1).copy()
    blk = flat.size // size
    lo, hi = 0, flat.size
    mask = size >> 1
    while mask > 0:
        peer = rank ^ mask
        mid = lo + (hi - lo) // 2
        if rank & mask:
            keep_lo, keep_hi, send_lo, send_hi = mid, hi, lo, mid
        else:
            keep_lo, keep_hi, send_lo, send_hi = lo, mid, mid, hi
        inbox = np.empty(keep_hi - keep_lo, flat.dtype)
        comm.sendrecv(flat[send_lo:send_hi], peer, inbox, peer,
                      T_RSCAT, T_RSCAT)
        seg = flat[keep_lo:keep_hi]
        if op.commutative or peer < rank:
            seg[...] = op(inbox, seg)
        else:
            seg[...] = op(seg.copy(), inbox)
        lo, hi = keep_lo, keep_hi
        mask >>= 1
    recv.reshape(-1)[:] = flat[rank * blk:(rank + 1) * blk]


def allgather_neighbor_exchange(comm, send: np.ndarray,
                                recv: np.ndarray) -> None:
    """coll_base_allgather.c:456 — even comm sizes: p/2 rounds alternating
    between the two ring neighbors; each round forwards the pair of blocks
    learned in the previous round. Half the rounds of ring for the same
    per-round payload shape."""
    size, rank = comm.size, comm.rank
    parts = recv.reshape(size, -1)
    parts[rank] = send.reshape(-1)
    sched = _neighbor_exchange_schedule(size)[rank]
    for peer, send_blocks, recv_blocks in sched:
        outbox = parts[send_blocks].copy()
        inbox = np.empty((len(recv_blocks), parts.shape[1]), parts.dtype)
        comm.sendrecv(outbox, peer, inbox, peer, T_ALLGATHER, T_ALLGATHER)
        parts[recv_blocks] = inbox


_NE_SCHED_CACHE: dict = {}


def _neighbor_exchange_schedule(size: int):
    """Per-rank [(peer, send_block_ids, recv_block_ids)] for the
    neighbor-exchange rounds; deterministic, cached per comm size."""
    sched = _NE_SCHED_CACHE.get(size)
    if sched is not None:
        return sched
    recent = {r: [r] for r in range(size)}
    sched = {r: [] for r in range(size)}
    for step in range(size // 2):
        peers = {}
        for r in range(size):
            if (r % 2 == 0) == (step % 2 == 0):
                peers[r] = (r + 1) % size
            else:
                peers[r] = (r - 1) % size
        nxt = {}
        for r in range(size):
            p = peers[r]
            sched[r].append((p, list(recent[r]), list(recent[p])))
            nxt[r] = [r, p] if step == 0 else list(recent[p])
        recent = nxt
    _NE_SCHED_CACHE[size] = sched
    return sched


def reduce_scatter_block_butterfly(comm, send: np.ndarray,
                                   recv: np.ndarray, op: Op) -> None:
    """coll_base_reduce_scatter.c:691 — butterfly for ANY comm size:
    non-power-of-two remainders fold their full vector into a partner
    first, the 2^k survivors run recursive vector halving along original-
    block boundaries, then folded-out ranks get their block back."""
    size, rank = comm.size, comm.rank
    flat = send.reshape(-1).astype(send.dtype, copy=True)
    blk = flat.size // size
    pof2 = 1 << (size.bit_length() - 1)
    rem = size - pof2
    if rank < 2 * rem:
        if rank % 2 == 0:           # folds out; receives its block at the end
            comm.send(flat, rank + 1, T_RSCAT)
            comm.recv(recv.reshape(-1), rank + 1, T_RSCAT)
            return
        tmp = np.empty_like(flat)
        comm.recv(tmp, rank - 1, T_RSCAT)
        flat[...] = op(tmp, flat)
        newrank = rank // 2
    else:
        newrank = rank - rem

    def start_block(nr: int) -> int:      # first original block nr represents
        return 2 * nr if nr < rem else nr + rem

    glo, ghi = 0, pof2
    mask = pof2 >> 1
    while mask > 0:
        peer_new = newrank ^ mask
        peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
        gmid = glo + mask
        if newrank & mask:
            keep = (gmid, ghi)
            send_rng = (glo, gmid)
        else:
            keep = (glo, gmid)
            send_rng = (gmid, ghi)
        k_lo, k_hi = start_block(keep[0]) * blk, start_block(keep[1]) * blk
        s_lo, s_hi = start_block(send_rng[0]) * blk, \
            start_block(send_rng[1]) * blk
        inbox = np.empty(k_hi - k_lo, flat.dtype)
        comm.sendrecv(flat[s_lo:s_hi], peer, inbox, peer, T_RSCAT, T_RSCAT)
        seg = flat[k_lo:k_hi]
        seg[...] = op(inbox, seg)
        glo, ghi = keep
        mask >>= 1
    # newrank now holds the reduced segment for its original block(s)
    b0 = start_block(newrank)
    if newrank < rem:                     # deliver the even partner's block
        comm.send(flat[b0 * blk:(b0 + 1) * blk], rank - 1, T_RSCAT)
        recv.reshape(-1)[:] = flat[(b0 + 1) * blk:(b0 + 2) * blk]
    else:
        recv.reshape(-1)[:] = flat[b0 * blk:(b0 + 1) * blk]


def barrier_recursive_doubling(comm) -> None:
    """coll_base_barrier.c:188; bruck (:269) handles non-pof2 the same way
    here because sendrecv pairs are symmetric per round."""
    size, rank = comm.size, comm.rank
    token = np.zeros(0, np.uint8)
    mask = 1
    while mask < size:
        to = (rank + mask) % size
        frm = (rank - mask) % size
        comm.sendrecv(token, to, token, frm, T_BARRIER, T_BARRIER)
        mask <<= 1


def scan_recursive_doubling(comm, send: np.ndarray, recv: np.ndarray,
                            op: Op, exclusive: bool) -> None:
    """coll_base_scan.c:157 — log2(p) rounds; ok for non-commutative because
    partner ordering is preserved."""
    size, rank = comm.size, comm.rank
    total = send.copy()        # running op over my prefix window
    have_prefix = False
    prefix = np.empty_like(send)
    tmp = np.empty_like(send)
    mask = 1
    while mask < size:
        lo_peer = rank - mask
        hi_peer = rank + mask
        reqs = []
        if hi_peer < size:
            reqs.append(comm.isend(total, hi_peer, T_SCAN))
        if lo_peer >= 0:
            comm.recv(tmp, lo_peer, T_SCAN)
            if have_prefix:
                prefix[...] = op(tmp, prefix)
            else:
                prefix[...] = tmp
                have_prefix = True
            total = op(tmp.copy(), total)
        wait_all(reqs)
        mask <<= 1
    if exclusive:
        if have_prefix:
            recv[...] = prefix
    else:
        recv[...] = op(prefix, send.copy()) if have_prefix else send


# ---------------------------------------------------------------------------
# the tuned module: decision rules + dispatch
# ---------------------------------------------------------------------------

_var.register("coll", "tuned", "dynamic_rules", "", type=str, level=4,
              help="Path to a dynamic rules file: lines of "
                   "'<coll> <min_comm_size> <min_bytes> <algorithm>'.")

for _coll, _algs in {
    "allreduce": "recursive_doubling|ring|segmented_ring|rabenseifner",
    "bcast": "binomial|knomial|pipeline|chain|scatter_allgather",
    "reduce": "binomial|inorder_binary|pipeline",
    "allgather": "recursive_doubling|ring|neighbor_exchange|bruck",
    "alltoall": "pairwise|bruck",
    "reduce_scatter_block": "recursive_halving|butterfly",
    "gather": "binomial|linear",
    "scatter": "binomial|linear",
    "allgatherv": "ring|linear",
    "barrier": "recursive_doubling|double_ring",
}.items():
    _var.register("coll", "tuned", f"{_coll}_algorithm", "", type=str, level=3,
                  help=f"Force the {_coll} algorithm ({_algs}; empty = auto).")

# segmentation / tree-shape knobs (≙ coll_tuned_*_segment_size / radix /
# chains MCA vars). Defaults below come from the recorded host sweep in
# TUNE_SWEEP.json (tools/coll_tune.py), not guesses.
_var.register("coll", "tuned", "allreduce_segsize", 256 << 10, type=int,
              level=4, help="Segment bytes for segmented-ring allreduce.")
_var.register("coll", "tuned", "reduce_segsize", 256 << 10, type=int,
              level=4, help="Segment bytes for pipeline reduce.")
_var.register("coll", "tuned", "bcast_segsize", 128 << 10, type=int,
              level=4, help="Segment bytes for pipeline/chain bcast.")
_var.register("coll", "tuned", "bcast_chains", 4, type=int, level=4,
              help="Number of chains for chain bcast.")
_var.register("coll", "tuned", "bcast_knomial_radix", 4, type=int, level=4,
              help="Radix for knomial bcast.")


def _load_dynamic_rules():
    path = _var.get("coll_tuned_dynamic_rules", "")
    rules = []
    if path and os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                coll, min_comm, min_bytes, alg = line.split()
                rules.append((coll, int(min_comm), int(min_bytes), alg))
    return rules


class TunedModule(CollModule):
    """Per-communicator tuned module; falls back to BasicModule for entry
    points without a tuned algorithm (per-function stacking does the same at
    the framework level; the inner fallback keeps semantics like in-order
    reduction in one place)."""

    def __init__(self, comm) -> None:
        self.basic = BasicModule()
        self._rules = _load_dynamic_rules()

    def _pick(self, coll: str, comm, nbytes: int, default: str) -> str:
        forced = _var.get(f"coll_tuned_{coll}_algorithm", "")
        if forced:
            return forced
        pick = default
        for c, mc, mb, alg in self._rules:
            if c == coll and comm.size >= mc and nbytes >= mb:
                pick = alg
        return pick

    # -- allreduce (decision table ≙ coll_tuned_decision_fixed.c:69-104) ----

    def allreduce(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = _sum_default(op)
        send = _inplace(sendbuf, recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        if comm.size == 1:
            recvbuf[...] = send
            return recvbuf
        if not op.commutative:
            return self.basic.allreduce(comm, send, recvbuf, op)
        nbytes = send.nbytes
        # thresholds from the recorded sweep (TUNE_SWEEP.json, 4 ranks):
        # rd wins ≤16K (1268µs vs ring 2122µs @16K), ring the mid band
        # (4291µs vs rd 7360µs @256K), segmented ring the largest sizes
        # (19.7ms vs ring 30.7ms @2M); rabenseifner never won on this host
        # but stays selectable for multi-core deployments
        default = ("recursive_doubling" if nbytes <= (1 << 16) else
                   ("ring" if nbytes <= (1 << 20) else "segmented_ring"))
        alg = self._pick("allreduce", comm, nbytes, default)
        if send.size < comm.size:   # tiny vectors can't be scattered
            alg = "recursive_doubling"
        if alg == "ring":
            allreduce_ring(comm, send, recvbuf, op)
        elif alg == "segmented_ring":
            allreduce_segmented_ring(
                comm, send, recvbuf, op,
                int(_var.get("coll_tuned_allreduce_segsize", 256 << 10)))
        elif alg == "rabenseifner":
            allreduce_rabenseifner(comm, send, recvbuf, op)
        else:
            allreduce_recursive_doubling(comm, send, recvbuf, op)
        return recvbuf

    def bcast(self, comm, buf, root: int = 0):
        buf = np.asarray(buf)
        if comm.size == 1:
            return buf
        nbytes = buf.nbytes
        # sweep-driven (TUNE_SWEEP.json, 4 ranks): chain wins the latency
        # regime (405µs vs binomial 715µs @64B), pipeline the bandwidth
        # regime (12.0ms vs binomial 14.0ms @2M); scatter_allgather and
        # binomial never won but remain selectable
        default = "chain" if nbytes <= (1 << 13) else "pipeline"
        alg = self._pick("bcast", comm, nbytes, default)
        if alg == "scatter_allgather" and buf.size >= comm.size:
            bcast_scatter_allgather(comm, buf, root)
        elif alg in ("pipeline", "chain"):
            bcast_pipeline(
                comm, buf, root,
                int(_var.get("coll_tuned_bcast_segsize", 128 << 10)),
                chains=1 if alg == "pipeline"
                else int(_var.get("coll_tuned_bcast_chains", 4)))
        elif alg == "knomial":
            bcast_knomial(comm, buf, root,
                          int(_var.get("coll_tuned_bcast_knomial_radix", 4)))
        else:
            bcast_binomial(comm, buf, root)
        return buf

    def reduce(self, comm, sendbuf, recvbuf=None, op: Op = None, root: int = 0):
        op = _sum_default(op)
        send = _inplace(sendbuf, recvbuf)
        if comm.size == 1:
            if recvbuf is None:
                recvbuf = np.empty_like(send)
            recvbuf[...] = send
            return recvbuf
        if not op.commutative:
            # in-order binary tree keeps the canonical fold order at
            # log(p) depth (vs the linear gather fallback)
            return reduce_inorder_binary(comm, send, recvbuf, op, root)
        # sweep (TUNE_SWEEP.json, 4 ranks, ONE core): binomial wins at all
        # sizes — the pipeline's wire/fold overlap needs ranks on their own
        # cores to pay off, so it stays selectable, not default
        alg = self._pick("reduce", comm, send.nbytes, "binomial")
        if alg == "inorder_binary":
            return reduce_inorder_binary(comm, send, recvbuf, op, root)
        if alg == "pipeline":
            return reduce_pipeline(
                comm, send, recvbuf, op, root,
                int(_var.get("coll_tuned_reduce_segsize", 256 << 10)))
        return reduce_binomial(comm, send, recvbuf, op, root)

    def gather(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if comm.size == 1:
            return self.basic.gather(comm, sendbuf, recvbuf, root)
        # sweep: binomial wins the latency regime, linear the bandwidth one
        # (interior nodes re-forward subtree data the linear root receives
        # once)
        alg = self._pick("gather", comm, np.asarray(sendbuf).nbytes,
                         "binomial" if np.asarray(sendbuf).nbytes <= (1 << 13)
                         else "linear")
        if alg == "linear":
            return self.basic.gather(comm, sendbuf, recvbuf, root)
        return gather_binomial(comm, np.asarray(sendbuf), recvbuf, root)

    def scatter(self, comm, sendbuf, recvbuf=None, root: int = 0):
        if comm.size == 1:
            return self.basic.scatter(comm, sendbuf, recvbuf, root)
        if recvbuf is None:
            if comm.rank != root:
                raise ValueError("non-root scatter needs recvbuf")
            sb = np.asarray(sendbuf)
            recvbuf = np.empty(sb.reshape((comm.size, -1)).shape[1:],
                               sb.dtype)
        # sweep: linear won at every size on 4 ranks (forwarding doubles
        # interior bytes); binomial stays selectable for large rank counts
        # where the root's p-1 sends become the bottleneck
        alg = self._pick("scatter", comm,
                         np.asarray(recvbuf).nbytes, "linear")
        if alg == "binomial":
            return scatter_binomial(comm, sendbuf, recvbuf, root)
        return self.basic.scatter(comm, sendbuf, recvbuf, root)

    def allgatherv(self, comm, sendbuf, recvbuf=None, counts=None,
                   displs=None):
        if counts is None or comm.size == 1:
            return self.basic.allgatherv(comm, sendbuf, recvbuf, counts,
                                         displs)
        nbytes = int(np.sum(counts)) * np.asarray(sendbuf).dtype.itemsize
        if self._pick("allgatherv", comm, nbytes, "ring") == "linear":
            return self.basic.allgatherv(comm, sendbuf, recvbuf, counts,
                                         displs)
        if displs is None:
            displs = list(np.concatenate([[0], np.cumsum(counts)[:-1]]))
        if recvbuf is None:
            # size by the furthest write, not sum(counts): user displs may
            # leave gaps (same contract as the basic module)
            total = max(int(d) + int(c) for d, c in zip(displs, counts))
            recvbuf = np.empty(total, np.asarray(sendbuf).dtype)
        allgatherv_ring(comm, np.asarray(sendbuf), recvbuf, counts, displs)
        return recvbuf

    def allgather(self, comm, sendbuf, recvbuf=None):
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty((comm.size,) + sendbuf.shape, sendbuf.dtype)
        if comm.size == 1:
            recvbuf.reshape(1, -1)[0] = sendbuf.reshape(-1)
            return recvbuf
        nbytes = sendbuf.nbytes
        pof2 = (comm.size & (comm.size - 1)) == 0
        even = comm.size % 2 == 0
        default = ("recursive_doubling" if pof2 and nbytes <= (1 << 16)
                   else ("bruck" if nbytes <= 4096
                         else ("neighbor_exchange" if even else "ring")))
        alg = self._pick("allgather", comm, nbytes, default)
        if alg == "recursive_doubling" and pof2:
            allgather_recursive_doubling(comm, sendbuf, recvbuf)
        elif alg == "bruck":
            allgather_bruck(comm, sendbuf, recvbuf)
        elif alg == "neighbor_exchange" and even:
            allgather_neighbor_exchange(comm, sendbuf, recvbuf)
        else:
            allgather_ring(comm, sendbuf, recvbuf)
        return recvbuf

    def alltoall(self, comm, sendbuf, recvbuf=None):
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(sendbuf)
        if comm.size == 1:
            recvbuf[...] = sendbuf
            return recvbuf
        nbytes = sendbuf.nbytes // comm.size
        alg = self._pick("alltoall", comm, nbytes,
                         "bruck" if nbytes <= 1024 else "pairwise")
        if alg == "bruck":
            alltoall_bruck(comm, sendbuf, recvbuf)
        else:
            alltoall_pairwise(comm, sendbuf, recvbuf)
        return recvbuf

    def reduce_scatter_block(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = _sum_default(op)
        sendbuf = np.asarray(sendbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(sendbuf.reshape(comm.size, -1)[0])
        pof2 = (comm.size & (comm.size - 1)) == 0
        if comm.size == 1:
            recvbuf.reshape(-1)[:] = sendbuf.reshape(-1)
            return recvbuf
        if not op.commutative or sendbuf.size % comm.size != 0:
            return self.basic.reduce_scatter_block(comm, sendbuf, recvbuf, op)
        alg = self._pick("reduce_scatter_block", comm, sendbuf.nbytes,
                         "recursive_halving" if pof2 else "butterfly")
        if alg == "butterfly" or not pof2:
            reduce_scatter_block_butterfly(comm, sendbuf, recvbuf, op)
        else:
            reduce_scatter_block_recursive_halving(comm, sendbuf, recvbuf, op)
        return recvbuf

    def barrier(self, comm):
        if comm.size <= 1:
            return
        alg = self._pick("barrier", comm, 0, "recursive_doubling")
        if alg == "double_ring":
            barrier_double_ring(comm)
        else:
            barrier_recursive_doubling(comm)

    def scan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = _sum_default(op)
        send = _inplace(sendbuf, recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        scan_recursive_doubling(comm, send, recvbuf, op, exclusive=False)
        return recvbuf

    def exscan(self, comm, sendbuf, recvbuf=None, op: Op = None):
        op = _sum_default(op)
        send = _inplace(sendbuf, recvbuf)
        if recvbuf is None:
            recvbuf = np.empty_like(send)
        scan_recursive_doubling(comm, send, recvbuf, op, exclusive=True)
        return recvbuf


@component("coll", "tuned", priority=30)
class TunedColl(Component):
    name = "tuned"

    def query(self, comm):
        return self.priority, TunedModule(comm)
